"""Priority preemption: victim selection as a pure, replayable function.

The reference cluster leaves preemption to the upstream scheduler's
PostFilter; here the coordinator owns the whole evict-and-rebind path,
so the selection logic must be a pure function of the host mirror — the
drill replays it against a frozen snapshot and asserts the stored bytes
byte-identical (the same contract as the breaker's oracle fallback,
tools/overload_drill.py phase 4).

Selection contract (documented order, gated by tests):

1. A node already feasible for the pod WITHOUT eviction means no
   preemption (``None``): the pod simply hasn't met its row in a
   sampled score window yet — retrying is cheaper than evicting.
2. Per candidate node, victims are considered **lowest priority first;
   at equal priority, other-tenant pods before the preemptor's own
   tenant (same-tenant-last); then newest bind first** — and only pods
   strictly below the preemptor's priority are evictable.  Victims are
   taken greedily until the node turns feasible.
3. Among nodes that CAN be made feasible, pick the one needing the
   fewest victims; break ties by the lowest maximum victim priority
   (disturb the least important work), then by the lowest row (the
   device path's earlier-index rule).

Eviction itself lives in the coordinator (store CAS + the pipedream
dirty-row/quarantine machinery); this module never touches state.
"""

from __future__ import annotations

import dataclasses

from k8s1m_tpu.obs.metrics import Counter
from k8s1m_tpu.oracle import oracle_feasible

_EVICTIONS = Counter(
    "preemption_evictions_total",
    "Bound pods evicted (CAS'd back to pending and requeued) to make "
    "room for a higher-priority pod",
    (),
)


def note_eviction() -> None:
    """Counted at the coordinator's eviction CAS (kept here so the
    tenancy subsystem owns its own evidence)."""
    _EVICTIONS.inc()


@dataclasses.dataclass(frozen=True)
class Victim:
    """One bound pod as a preemption candidate (host-mirror view)."""

    key: str          # "<ns>/<name>"
    node: str
    row: int
    cpu_milli: int
    mem_kib: int
    priority: int
    seq: int          # bind sequence; larger = bound more recently
    tenant: str


def victim_sort_key(preemptor_tenant: str):
    """Victim preference within one node (see module doc, rule 2)."""
    def key(v: Victim):
        return (v.priority, v.tenant == preemptor_tenant, -v.seq)

    return key


@dataclasses.dataclass(frozen=True)
class PreemptionChoice:
    row: int
    node: str
    victims: tuple[Victim, ...]


def select_preemption(
    pod,
    preemptor_tenant: str,
    preemptor_priority: int,
    nodes,                 # [(row, NodeInfo)] ascending row
    usage: dict,           # row -> (cpu_req, mem_kib_req, pods_req)
    victims_by_row: dict,  # row -> list[Victim] (any order)
) -> PreemptionChoice | None:
    """Pick (node, victims) for ``pod``, or None when preemption is not
    warranted (already feasible somewhere) or cannot help (no node can
    be made feasible by evicting strictly-lower-priority pods).

    Pure: consumes only its arguments, so a drill that logged them can
    replay the exact choice.  ``nodes`` ascending-row keeps every
    tie-break deterministic.
    """
    # Rule 1: feasible somewhere as-is -> not a preemption case.
    for row, nd in nodes:
        if oracle_feasible(nd, pod, usage.get(row, (0, 0, 0))):
            return None

    best: tuple[int, int, int, PreemptionChoice] | None = None
    order = victim_sort_key(preemptor_tenant)
    for row, nd in nodes:
        candidates = sorted(
            (
                v for v in victims_by_row.get(row, ())
                if v.priority < preemptor_priority
            ),
            key=order,
        )
        if not candidates:
            continue
        cpu, mem, pods = usage.get(row, (0, 0, 0))
        taken: list[Victim] = []
        feasible = False
        for v in candidates:
            taken.append(v)
            cpu -= v.cpu_milli
            mem -= v.mem_kib
            pods -= 1
            if oracle_feasible(nd, pod, (cpu, mem, pods)):
                feasible = True
                break
        if not feasible:
            # Even a fully-evicted node can stay infeasible (static
            # filters: taints, selectors, allocatable too small).
            continue
        rank = (len(taken), max(v.priority for v in taken), row)
        if best is None or rank < best[:3]:
            best = (*rank, PreemptionChoice(row, nd.name, tuple(taken)))
    return best[3] if best is not None else None
