"""k8s1m_tpu.tenancy — multi-tenant fairness, preemption, gangs.

The admission→schedule→evict chain for thousands of tenants (ROADMAP
item 2): weighted-fair admission (tenancy/admission.py), priority
preemption with pure replayable victim selection (tenancy/preempt.py),
and minimal all-or-none gang scheduling (tenancy/gang.py), all wired
through ``Coordinator(tenancy=...)`` and the admission webhook.

``TenancyController`` is the one object call sites construct: it owns
the policy, the (possibly shared) loadshed HealthController, and the
FairAdmission bucket state.
"""

from __future__ import annotations

from k8s1m_tpu.loadshed import HealthController, LoadshedConfig
from k8s1m_tpu.tenancy.admission import FairAdmission
from k8s1m_tpu.tenancy.policy import (
    GANG_LABEL,
    GANG_SIZE_LABEL,
    TENANT_LABEL,
    TenancyPolicy,
    gang_of_labels,
    tenant_of_key,
    tenant_of_namespace,
    tenant_of_obj,
    tenant_of_pod,
)
from k8s1m_tpu.tenancy.preempt import (
    PreemptionChoice,
    Victim,
    select_preemption,
    victim_sort_key,
)

__all__ = [
    "FairAdmission",
    "GANG_LABEL",
    "GANG_SIZE_LABEL",
    "PreemptionChoice",
    "TENANT_LABEL",
    "TenancyController",
    "TenancyPolicy",
    "Victim",
    "gang_of_labels",
    "select_preemption",
    "tenant_of_key",
    "tenant_of_namespace",
    "tenant_of_obj",
    "tenant_of_pod",
    "victim_sort_key",
]


class TenancyController:
    """The tenancy subsystem as one constructor argument.

    ``Coordinator(tenancy=TenancyController(policy))`` is the whole
    opt-in.  When no HealthController is passed, one is built from
    ``loadshed_config`` and the coordinator adopts it as its loadshed
    controller too — one state machine drives both the degraded
    scheduling knobs and the per-tenant admission gates.
    """

    def __init__(
        self,
        policy: TenancyPolicy | None = None,
        controller: HealthController | None = None,
        *,
        loadshed_config: LoadshedConfig | None = None,
        capacity_per_tick: int = 256,
        name: str = "coordinator",
    ):
        self.policy = policy or TenancyPolicy()
        self.controller = controller or HealthController(
            loadshed_config, name=name
        )
        self.admission = FairAdmission(
            self.policy, self.controller, capacity_per_tick=capacity_per_tick
        )
