"""Weighted-fair admission: per-tenant token buckets under overload.

PR 2's loadshed gave admission ONE global lever — an adaptive priority
floor — so a flash-crowd tenant submitting at priority 5 starves every
tenant submitting at 4, forever.  ``FairAdmission`` replaces the floor
with a proportional-share answer layered on the same HealthController:

- **HEALTHY** — admit everything (the buckets refill to their burst cap
  but are never drawn, so enforcement starts with a full cushion).
- **DEGRADED / SHEDDING** — every admission draws one token from its
  tenant's bucket; an empty bucket rejects with reason ``"tenant"``
  (HTTP 429 at the webhook, ``Overloaded`` from ``submit_external``).
  Buckets refill once per scheduling cycle (``tick``), each active
  tenant getting ``capacity * weight / sum(active weights)`` tokens —
  so under N-fold overload every tenant's *admitted* throughput
  converges to its weight share, and the overload degrades the flash
  crowd instead of the cluster.
- The HealthController's hard ``queue_cap`` stays global (a full queue
  is full no matter whose pods fill it); its priority *floor* is
  bypassed (``floor=False``) — priority's job moves to preemption
  (tenancy/preempt.py), fairness's job lives here.

State discipline: everything webhook handler threads and the cycle
thread both touch lives under ``_admit_lock`` (``@guarded_by``-declared;
the lint static pass and the runtime audit both prove it).  No RNG, no
wall clock: buckets move only on ``tick`` and on admission calls, so a
drill replays the same admit/reject trajectory from the same submit
schedule (the faultline determinism contract, extended to tenancy).

Metrics: ``tenant_admitted_total{tenant_class}`` and
``tenant_debt{tenant_class}`` (tokens of unmet demand, decaying as
refills catch up); rejections land in the existing
``admission_rejected_total{point,reason}`` with reason ``tenant``.
"""

from __future__ import annotations

import threading

from k8s1m_tpu.lint import guarded_by
from k8s1m_tpu.loadshed.controller import (
    HEALTHY,
    HealthController,
    Overloaded,
    _REJECTED,
)
from k8s1m_tpu.obs.metrics import Counter, Gauge
from k8s1m_tpu.ops.priority import pod_priority_of
from k8s1m_tpu.tenancy.policy import TenancyPolicy, tenant_of_obj

_ADMITTED = Counter(
    "tenant_admitted_total",
    "Pods admitted, by tenant class (bounded-cardinality: tenants are "
    "grouped by TenancyPolicy class, never labeled by name)",
    ("tenant_class",),
)
_DEBT = Gauge(
    "tenant_debt",
    "Tokens of unmet tenant demand (rejections not yet covered by "
    "refills) — a persistently indebted class is over its weight share",
    ("tenant_class",),
)


@guarded_by(
    # Webhook handler threads and the cycle thread race on all of it:
    # buckets (drawn per admission, refilled per tick), the demand
    # window (drives the active set), debt, and the cumulative
    # admitted/rejected ledger the drills settle on.
    _buckets="_admit_lock",
    _demand="_admit_lock",
    _debt="_admit_lock",
    _admitted="_admit_lock",
    _rejected="_admit_lock",
    _last_active="_admit_lock",
    _tick_n="_admit_lock",
    _debt_classes="_admit_lock",
)
class FairAdmission:
    """Per-tenant weighted-fair token buckets over a HealthController.

    Presents the same surface the webhook and ``submit_external``
    already consume (``admit``/``check_admit``/``retry_after_s``) plus
    the object-aware forms (``admit_obj``/``check_admit_obj``) that
    derive the tenant — callers with a pod object should prefer those.
    """

    def __init__(
        self,
        policy: TenancyPolicy | None = None,
        controller: HealthController | None = None,
        *,
        capacity_per_tick: int = 256,
    ):
        self.policy = policy or TenancyPolicy()
        self.controller = controller or HealthController()
        if capacity_per_tick < 1:
            raise ValueError("capacity_per_tick must be >= 1")
        self.capacity_per_tick = capacity_per_tick
        # First-sight cushion: a tenant first seen mid-pressure gets a
        # small starter bucket instead of an instant reject (its first
        # refill lands at the next tick).
        self._starter = max(1.0, self.policy.burst_ticks)
        self._buckets: dict[str, float] = {}
        self._demand: dict[str, int] = {}     # try_admit calls this tick
        self._debt: dict[str, float] = {}
        self._admitted: dict[str, int] = {}   # cumulative, per tenant
        self._rejected: dict[str, int] = {}   # cumulative "tenant" rejects
        # Idle-tenant eviction: the working state (_buckets/_debt) is
        # bounded by ACTIVE tenants, not tenants-ever-seen —
        # with tenants derived from namespaces, namespace churn must
        # not grow tick()'s per-cycle work (run under _admit_lock, the
        # lock webhook threads contend on) forever.  A long-idle
        # tenant forfeits its banked burst and re-enters on the starter
        # cushion.  The cumulative _admitted/_rejected ledger is kept
        # (drill evidence; a few ints per tenant-ever-seen).
        self._last_active: dict[str, int] = {}
        self._tick_n = 0
        # Classes whose debt gauge is currently nonzero — zeroed when
        # their debt fully decays (entries are dropped from _debt, so
        # the gauge would otherwise freeze at the last nonzero value).
        self._debt_classes: set[str] = set()
        self._idle_evict_ticks = max(8, int(4 * self.policy.burst_ticks))
        self._admit_lock = threading.Lock()

    # ---- admission -----------------------------------------------------

    def try_admit(
        self, tenant: str, priority: int = 0, point: str = "coordinator"
    ) -> str | None:
        """None = admitted; else the rejection reason: ``"tenant"`` =
        over the tenant's fair share while the controller is under
        pressure, ``"cap"`` = the global hard queue bound (any tenant,
        any priority).  The loadshed priority floor does NOT run here
        (``floor=False``): under tenancy, shedding is proportional by
        tenant, and priority acts through preemption instead."""
        # Controller state is read through its own locked accessor BEFORE
        # taking ours: lock order is FairAdmission -> HealthController,
        # never the reverse (artifacts/lockgraph.json).
        enforcing = self.controller.current_state() != HEALTHY
        cls = self.policy.class_of(tenant)
        with self._admit_lock:
            self._demand[tenant] = self._demand.get(tenant, 0) + 1
            if enforcing:
                bucket = self._buckets.get(tenant, self._starter)
                if bucket < 1.0:
                    self._debt[tenant] = self._debt.get(tenant, 0.0) + 1.0
                    self._rejected[tenant] = self._rejected.get(tenant, 0) + 1
                    reason = "tenant"
                else:
                    reason = self.controller.try_admit(
                        priority, point, floor=False
                    )
                    if reason is None:
                        self._buckets[tenant] = bucket - 1.0
                        self._admitted[tenant] = (
                            self._admitted.get(tenant, 0) + 1
                        )
            else:
                reason = self.controller.try_admit(priority, point, floor=False)
                if reason is None:
                    self._admitted[tenant] = self._admitted.get(tenant, 0) + 1
        if reason is None:
            _ADMITTED.inc(tenant_class=cls)
        elif reason == "tenant":
            # "cap"/other reasons were already counted by the controller.
            _REJECTED.inc(point=point, reason="tenant")
        return reason

    def try_admit_obj(self, obj: dict, point: str = "coordinator") -> str | None:
        return self.try_admit(tenant_of_obj(obj), pod_priority_of(obj), point)

    def admit_obj(self, obj: dict, point: str = "coordinator") -> bool:
        """Boolean form for the webhook's 429 gate."""
        return self.try_admit_obj(obj, point) is None

    def admit(self, priority: int = 0, point: str = "coordinator") -> bool:
        """Legacy priority-only form (no object in hand): the caller
        could not name a tenant, so the pod draws from ``"default"``."""
        return self.try_admit("default", priority, point) is None

    def check_admit_obj(self, obj: dict, point: str = "coordinator") -> None:
        """``try_admit_obj`` that raises ``Overloaded`` (the
        ``submit_external`` form), carrying the real reason."""
        reason = self.try_admit_obj(obj, point)
        if reason is not None:
            raise Overloaded(self.controller.retry_after_s(), reason)

    def retry_after_s(self) -> float:
        return self.controller.retry_after_s()

    def bucket_level(self, tenant: str) -> float:
        """Current token balance of ``tenant``'s bucket (the starter
        cushion for a tenant not yet seen) — a cheap locked read for
        observability (the podtrace admit-span attribute), never an
        admission decision."""
        with self._admit_lock:
            return round(self._buckets.get(tenant, self._starter), 3)

    # ---- the per-cycle refill ------------------------------------------

    def tick(self, capacity: int | None = None) -> None:
        """Refill buckets once per scheduling cycle.

        ``capacity`` is this cycle's admit budget (the coordinator
        passes its batch size).  Active tenants — those that offered
        load since the last tick, or still carry debt — split it by
        weight; each bucket caps at ``burst_ticks`` ticks of that
        tenant's share, so an idle tenant banks a bounded burst, never
        an unbounded one.  Debt decays by the refill: a tenant whose
        rejections were transient returns to zero, one persistently
        over its share keeps a visible balance."""
        cap = float(capacity if capacity is not None else self.capacity_per_tick)
        per_class: dict[str, float] = {}
        with self._admit_lock:
            self._tick_n += 1
            active = sorted(
                set(t for t, d in self._demand.items() if d > 0)
                | set(t for t, d in self._debt.items() if d > 0)
            )
            total_w = sum(self.policy.weight_of(t) for t in active)
            for t in active:
                share = cap * self.policy.weight_of(t) / total_w
                burst = max(1.0, self.policy.burst_ticks * share)
                self._buckets[t] = min(
                    self._buckets.get(t, self._starter) + share, burst
                )
                debt = max(0.0, self._debt.get(t, 0.0) - share)
                if debt > 0.0:
                    self._debt[t] = debt
                else:
                    self._debt.pop(t, None)
                self._last_active[t] = self._tick_n
            self._demand = {}
            if self._tick_n % self._idle_evict_ticks == 0:
                horizon = self._tick_n - self._idle_evict_ticks
                stale = [
                    t for t, last in self._last_active.items()
                    if last <= horizon
                ]
                for t in stale:
                    del self._last_active[t]
                    self._buckets.pop(t, None)
                    self._debt.pop(t, None)
            for t, d in self._debt.items():
                c = self.policy.class_of(t)
                per_class[c] = per_class.get(c, 0.0) + d
            for c in self._debt_classes - set(per_class):
                per_class[c] = 0.0
            self._debt_classes = {c for c, d in per_class.items() if d > 0}
        for c, d in per_class.items():
            _DEBT.set(round(d, 3), tenant_class=c)

    # ---- evidence ------------------------------------------------------

    def counters(self) -> dict:
        """Cumulative per-tenant admit/reject snapshot (drill evidence;
        values are plain ints so the dict is JSON-ready)."""
        with self._admit_lock:
            return {
                "admitted": dict(self._admitted),
                "rejected": dict(self._rejected),
                "debt": {t: round(d, 3) for t, d in self._debt.items() if d},
            }
