"""Minimal gang scheduling: all-or-none pod groups riding one wave.

A multi-pod job that binds half its pods and then waits holds capacity
hostage — two half-placed jobs can deadlock a full cluster forever.
The gang contract here is deliberately minimal and rides the existing
wave/epoch machinery instead of adding a second scheduler:

- A pod declares its gang with labels ``k8s1m.io/gang=<name>`` and
  ``k8s1m.io/gang-size=<N>`` (namespace-qualified id, so tenants never
  collide).  Gang pods carry labels, so they always take the full
  decode path — the label-less native fast lane is untouched.
- Members **stage** until all N are present, then enter the queue
  contiguously; ``_take_batch`` never splits a gang across a batch
  boundary, so the whole gang rides ONE device wave (N must fit the
  wave: oversize gangs degrade to plain scheduling, counted).
- At wave retire the gang settles **all-or-none inside the wave-epoch
  window**: every member bound -> admitted; any member failed (CAS
  conflict, no feasible row, tombstoned row) -> every provisional bind
  is evicted through the same CAS + dirty-row machinery preemption
  uses, and the gang requeues as a unit — partial capacity is never
  held across a quiesce, because settlement happens before the wave's
  retire returns.

State lives on the coordinator (cycle-thread-owned, ``THREAD_OWNER``
annotated); this module holds the shared helpers and the evidence
counter.
"""

from __future__ import annotations

from k8s1m_tpu.obs.metrics import Counter
from k8s1m_tpu.tenancy.policy import gang_of_labels  # noqa: F401  (re-export)

_GANGS = Counter(
    "gang_admit_total",
    "All-or-none pod-group settlements, by outcome: bound = every "
    "member bound in one wave; requeued = partial/failed wave, every "
    "provisional bind released and the gang re-staged; parked = retry "
    "budget exhausted, all members unschedulable; oversize = gang "
    "larger than a wave, degraded to plain scheduling",
    ("outcome",),
)


def note_gang(outcome: str) -> None:
    _GANGS.inc(outcome=outcome)
