"""Bulk-create pending pods for the scheduler (the make_pods equivalent,
reference kwok/make_pods/main.go:109-172).

    python -m k8s1m_tpu.tools.make_pods --count 100000 --cpu 100 --mem-mib 200
"""

from __future__ import annotations

import argparse
import asyncio
import json

from k8s1m_tpu.control.objects import encode_pod, pod_key
from k8s1m_tpu.snapshot.pod_encoding import PodInfo, Toleration
from k8s1m_tpu.tools.common import (
    RateReporter,
    add_common_args,
    client_factory,
    run_sharded,
)


def build_pod(
    i: int,
    *,
    prefix: str = "bench-pod",
    namespace: str = "default",
    cpu_milli: int = 100,
    mem_kib: int = 200 << 10,
    tolerate_kwok: bool = True,
) -> PodInfo:
    return PodInfo(
        name=f"{prefix}-{i}",
        namespace=namespace,
        cpu_milli=cpu_milli,
        mem_kib=mem_kib,
        labels={"app": prefix},
        # The reference's pods tolerate the kwok taint
        # (make_pods/main.go sets tolerations for kwok.x-k8s.io/node).
        tolerations=(
            [Toleration(key="kwok.x-k8s.io/node")] if tolerate_kwok else []
        ),
    )


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="bulk-create pending pods")
    add_common_args(ap)
    ap.add_argument("--count", type=int, default=1000)
    ap.add_argument("--start", type=int, default=0)
    ap.add_argument("--prefix", default="bench-pod")
    ap.add_argument("--namespace", default="default")
    ap.add_argument("--cpu", type=int, default=100, help="milliCPU request")
    ap.add_argument("--mem-mib", type=int, default=200)
    ap.add_argument(
        "--tenants", type=int, default=0,
        help="spread pods over N tenant namespaces (tenant-0..tenant-N-1) "
        "with zipf-skewed tenant sizes (cluster/workload.py); 0 = the "
        "single --namespace",
    )
    ap.add_argument("--tenant-skew", type=float, default=1.0,
                    help="zipf skew of tenant sizes (0 = uniform)")
    ap.add_argument(
        "--tenant-schedule", default="steady",
        choices=("steady", "diurnal", "flash"),
        help="arrival-shape of the tenant mix along the index sequence "
        "(flash: tenant-0 crowds 10x in the middle fifth)",
    )
    ap.add_argument("--seed", type=int, default=0,
                    help="tenant-assignment seed (deterministic stream)")
    return ap.parse_args(argv)


async def amain(args) -> dict:
    reporter = RateReporter("pods created", quiet=args.quiet)
    tenant_of = None
    if args.tenants > 0:
        from k8s1m_tpu.cluster.workload import tenant_assignments

        tenant_of = tenant_assignments(
            args.count, args.tenants, skew=args.tenant_skew,
            seed=args.seed, schedule=args.tenant_schedule,
        )

    async def work(client, i):
        ns = (
            args.namespace if tenant_of is None
            else f"tenant-{tenant_of[i]}"
        )
        pod = build_pod(
            args.start + i, prefix=args.prefix, namespace=ns,
            cpu_milli=args.cpu, mem_kib=args.mem_mib << 10,
        )
        await client.put(pod_key(pod.namespace, pod.name), encode_pod(pod))

    await run_sharded(
        args.count, args.concurrency, client_factory(args), work,
        clients=args.clients, reporter=reporter,
    )
    return reporter.summary()


def main(argv=None):
    print(json.dumps(asyncio.run(amain(parse_args(argv)))))


if __name__ == "__main__":
    main()
