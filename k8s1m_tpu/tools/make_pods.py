"""Bulk-create pending pods for the scheduler (the make_pods equivalent,
reference kwok/make_pods/main.go:109-172).

    python -m k8s1m_tpu.tools.make_pods --count 100000 --cpu 100 --mem-mib 200
"""

from __future__ import annotations

import argparse
import asyncio
import json

from k8s1m_tpu.control.objects import encode_pod, pod_key
from k8s1m_tpu.snapshot.pod_encoding import PodInfo, Toleration
from k8s1m_tpu.tools.common import (
    RateReporter,
    add_common_args,
    client_factory,
    run_sharded,
)


def build_pod(
    i: int,
    *,
    prefix: str = "bench-pod",
    namespace: str = "default",
    cpu_milli: int = 100,
    mem_kib: int = 200 << 10,
    tolerate_kwok: bool = True,
) -> PodInfo:
    return PodInfo(
        name=f"{prefix}-{i}",
        namespace=namespace,
        cpu_milli=cpu_milli,
        mem_kib=mem_kib,
        labels={"app": prefix},
        # The reference's pods tolerate the kwok taint
        # (make_pods/main.go sets tolerations for kwok.x-k8s.io/node).
        tolerations=(
            [Toleration(key="kwok.x-k8s.io/node")] if tolerate_kwok else []
        ),
    )


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="bulk-create pending pods")
    add_common_args(ap)
    ap.add_argument("--count", type=int, default=1000)
    ap.add_argument("--start", type=int, default=0)
    ap.add_argument("--prefix", default="bench-pod")
    ap.add_argument("--namespace", default="default")
    ap.add_argument("--cpu", type=int, default=100, help="milliCPU request")
    ap.add_argument("--mem-mib", type=int, default=200)
    return ap.parse_args(argv)


async def amain(args) -> dict:
    reporter = RateReporter("pods created", quiet=args.quiet)

    async def work(client, i):
        pod = build_pod(
            args.start + i, prefix=args.prefix, namespace=args.namespace,
            cpu_milli=args.cpu, mem_kib=args.mem_mib << 10,
        )
        await client.put(pod_key(pod.namespace, pod.name), encode_pod(pod))

    await run_sharded(
        args.count, args.concurrency, client_factory(args), work,
        clients=args.clients, reporter=reporter,
    )
    return reporter.summary()


def main(argv=None):
    print(json.dumps(asyncio.run(amain(parse_args(argv)))))


if __name__ == "__main__":
    main()
