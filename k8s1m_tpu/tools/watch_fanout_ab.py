"""Watch-amplification A/B — and the watchplane storm drill — through
the watch-cache tier.

A/B mode reproduces the shape of the reference's apiserver findings
(reference README.adoc:410-416, 495-499): every node holds several
watches on its own objects (18 per kubelet+kube-proxy in the reference;
``--watchers-per-node`` here), all served by the fan-out tier from ONE
store watch — the store sees the write load, never the watch load.  The
``--index both`` mode runs the experiment under the hash and btree cache
storages, the reference's ``BtreeWatchCache`` ceiling axis.

    python -m k8s1m_tpu.tools.watch_fanout_ab --nodes 50 --writes 20000

Prints one BENCH-style JSON line per index mode:
``store_events_per_sec`` (events entering the tier) vs
``delivered_per_sec`` (events fanned out to client watches), plus the
store-side watcher count proving the amplification never reaches it.

STORM mode (``--watchers`` / ``--fault-plan`` / ``--smoke``) is the
ISSUE 15 kill drill: six figures of multiplexed client watches on the
18-per-node profile (3 hot + 15 idle), a seq-stamped lease-flood write
load, and a composed fault plan (``--fault-plan watchstorm``: upstream
stream breaks + pump-lane stalls + subscriber wedges) — gated on

- **zero event loss by ledger**: every hot watch ends at its key's
  final written seq, monotonically (coalescing may elide, never
  reorder or lose net state; a canceled watch must recover it by
  relist);
- **resume rate**: >= 90% of injected upstream breaks resolved by
  diff-replay resume (``watchcache_resumes_total``), not a
  cancel-everyone relist storm (``watchcache_invalidations_total``);
- **bounded delivery lag**: p99 write->delivery under ``--p99-budget``
  across the composed churn + flood window;
- **bounded memory** (``--smoke``): peak RSS under ``--rss-budget-mb``.

    python -m k8s1m_tpu.tools.watch_fanout_ab --watchers 100000 \\
        --fault-plan watchstorm --out artifacts/watchstorm_cpu.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import resource
import time

from k8s1m_tpu.store.etcd_client import EtcdClient
from k8s1m_tpu.store.etcd_server import serve
from k8s1m_tpu.store.native import MemStore
from k8s1m_tpu.store.watch_cache import serve_watch_cache
from k8s1m_tpu.control.objects import lease_key
from k8s1m_tpu.tools.lease_flood import LEASE_NS, lease_value

_STREAMS_PER_CHANNEL = 80   # under the server's max_concurrent_streams=100


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="watch fan-out A/B + storm drill")
    ap.add_argument("--nodes", type=int, default=50)
    ap.add_argument("--watchers-per-node", type=int, default=3,
                    help="HOT client watches per node object (lease "
                         "updates fan out to these)")
    ap.add_argument("--idle-watches-per-node", type=int, default=0,
                    help="additional idle watches per node on objects "
                         "that never change (configmaps/secrets in the "
                         "reference's 18-watches-per-kubelet profile, "
                         "README.adoc:410-416) — they must cost the "
                         "store nothing and deliver nothing")
    ap.add_argument("--writes", type=int, default=10000)
    ap.add_argument("--batch", type=int, default=500,
                    help="producer batch size (BatchKV wave)")
    ap.add_argument("--index", choices=("hash", "btree", "both"),
                    default="both")
    ap.add_argument("--quiet", action="store_true")
    # ---- storm-drill mode ----
    ap.add_argument("--watchers", type=int, default=0,
                    help="STORM mode: total client watches on the "
                         "18-per-node profile (watchers-per-node hot + "
                         "15 idle per node)")
    ap.add_argument("--fault-plan", default=None,
                    help="faultline plan for the storm window: a named "
                         "plan ('watchstorm'), inline JSON, or @path")
    ap.add_argument("--streams", type=int, default=16,
                    help="storm mode: bidi streams the watches "
                         "multiplex over")
    ap.add_argument("--flood-factor", type=int, default=4,
                    help="storm mode: lease-flood burst multiplier for "
                         "the middle third of the write window")
    ap.add_argument("--rate", type=int, default=1000,
                    help="storm mode: steady offered write rate "
                         "(writes/s), sized to the 1-core in-process "
                         "lane's sustainable fan-out; the flood third "
                         "runs unpaced at flood-factor x the batch size")
    ap.add_argument("--lag-budget", type=int, default=32,
                    help="storm mode: the tier's per-subscriber FIFO "
                         "budget (tight by default so the flood third "
                         "actually exercises latest-only coalescing)")
    ap.add_argument("--p99-budget", type=float, default=5.0,
                    help="storm gate: write->delivery p99 seconds")
    ap.add_argument("--rss-budget-mb", type=float, default=0.0,
                    help="storm gate: peak process RSS (0 = report "
                         "only; --smoke sets a budget)")
    ap.add_argument("--replica-drill", action="store_true",
                    help="storm mode: run a watch-cache REPLICA as a "
                         "subprocess serving a slice of the hot keys, "
                         "SIGKILL it mid-storm, and relaunch it with "
                         "--resume-floor — its watches must resume "
                         "from revision (warm restart), not relist")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 storm shape: 10k watchers, same gates "
                         "plus the RSS budget and the replica "
                         "warm-restart drill")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.smoke:
        args.watchers = args.watchers or 10_000
        args.writes = 8_000 if args.writes == 10000 else args.writes
        args.fault_plan = args.fault_plan or "watchstorm"
        args.replica_drill = True
        if not args.rss_budget_mb:
            args.rss_budget_mb = 1500.0
    return args


async def run_one(index: str, args, store: MemStore, store_port: int) -> dict:
    lease_prefix = lease_key(LEASE_NS, "x")[:-1]    # .../kube-node-lease/
    cm_prefix = b"/registry/configmaps/kube-system/"
    prefixes = [lease_prefix]
    producer = EtcdClient(f"127.0.0.1:{store_port}")
    if args.idle_watches_per_node:
        # The idle population watches per-node config objects that are
        # written once and never again (the configmap/secret share of the
        # reference's 18-watches-per-kubelet profile).
        prefixes.append(cm_prefix)
        await producer.put_batch([
            (cm_prefix + f"node-cfg-{i}-{j}".encode(), b'{"data":{}}')
            for i in range(args.nodes)
            for j in range(args.idle_watches_per_node)
        ])
    tier = await serve_watch_cache(
        f"127.0.0.1:{store_port}", prefixes, port=0, index=index,
    )
    cache, cache_port = tier.cache, tier.port
    n_hot = args.nodes * args.watchers_per_node
    n_idle = args.nodes * args.idle_watches_per_node
    n_sessions = n_hot + n_idle
    n_channels = (n_sessions + _STREAMS_PER_CHANNEL - 1) // _STREAMS_PER_CHANNEL
    clients = [
        EtcdClient(f"127.0.0.1:{cache_port}",
                   options=[("grpc.use_local_subchannel_pool", 1)])
        for _ in range(max(1, n_channels))
    ]
    sessions = []
    idle_sessions = []
    for i in range(n_hot):
        node = f"kwok-node-{i % args.nodes}"
        s = clients[i % len(clients)].watch(lease_key(LEASE_NS, node))
        await s.__aenter__()
        sessions.append(s)
    for i in range(n_idle):
        key = cm_prefix + (
            f"node-cfg-{i % args.nodes}-{i // args.nodes}".encode()
        )
        s = clients[(n_hot + i) % len(clients)].watch(key)
        await s.__aenter__()
        idle_sessions.append(s)

    expected = args.writes * args.watchers_per_node
    delivered = 0
    stream_errors = 0
    done = asyncio.Event()

    async def drain(s):
        nonlocal delivered, stream_errors
        while not done.is_set():
            try:
                batch = await s.next(timeout=15)
            except asyncio.TimeoutError:
                return
            # Counted, not logged: stream_errors is the report's signal.
            except Exception:  # graftlint: disable=broad-except
                # A broken stream must surface as an error, not masquerade
                # as a fan-out throughput ceiling.
                stream_errors += 1
                return
            delivered += len(batch.events)
            if delivered >= expected:
                done.set()

    drainers = [asyncio.create_task(drain(s)) for s in sessions]

    idle_delivered = 0

    async def idle_drain(s):
        nonlocal idle_delivered, stream_errors
        while not done.is_set():
            try:
                batch = await s.next(timeout=15)
            except asyncio.TimeoutError:
                continue    # expected quiet — keep listening to the end
            # Counted, not logged: stream_errors is the report's signal.
            except Exception:  # graftlint: disable=broad-except
                # A broken idle stream must not masquerade as "idle
                # watches deliver nothing" — that's the claim under test.
                stream_errors += 1
                return
            idle_delivered += len(batch.events)

    drainers += [asyncio.create_task(idle_drain(s)) for s in idle_sessions]

    t0 = time.perf_counter()
    i = 0
    while i < args.writes:
        n = min(args.batch, args.writes - i)
        items = []
        for j in range(i, i + n):
            node = f"kwok-node-{j % args.nodes}"
            items.append(
                (lease_key(LEASE_NS, node), lease_value(node, j // args.nodes))
            )
        await producer.put_batch(items)
        i += n
    write_s = time.perf_counter() - t0
    try:
        await asyncio.wait_for(done.wait(), timeout=60)
    except asyncio.TimeoutError:
        pass
    total_s = time.perf_counter() - t0

    store_watchers = store.stats()["watchers"]
    st = cache.stats()
    for t in drainers:
        t.cancel()
    for s in sessions + idle_sessions:
        await s.cancel()
    for c in clients:
        await c.close()
    await producer.close()
    await tier.close()

    return {
        "index": index,
        "nodes": args.nodes,
        "client_watches": n_sessions,
        "idle_watches": n_idle,
        "store_watches": store_watchers,     # 1 per prefix: fan-out proof
        "writes": args.writes,
        "writes_per_sec": round(args.writes / write_s, 1),
        "store_events_per_sec": round(st["events_in"] / total_s, 1),
        "delivered": delivered,
        "idle_delivered": idle_delivered,    # must be 0: idle watches are free
        "delivered_per_sec": round(delivered / total_s, 1),
        "amplification": round(delivered / max(1, st["events_in"]), 2),
        "stream_errors": stream_errors,
    }


async def amain(args) -> list[dict]:
    store = MemStore()
    server, store_port = await serve(store, port=0)
    out = []
    try:
        modes = ("hash", "btree") if args.index == "both" else (args.index,)
        for index in modes:
            out.append(await run_one(index, args, store, store_port))
    finally:
        await server.stop(None)
        store.close()
    return out


# ---------------------------------------------------------------------------
# Storm mode (ISSUE 15 watchplane): the kill drill.

_IDLE_PER_NODE = 15          # reference profile: 3 hot + 15 idle = 18
_SEQ_W = 12                  # zero-padded seq prefix of every hot value
_PAD = b'|{"kind":"Lease","spec":{"renew":"' + b"x" * 140 + b'"}}'
_LAG_SAMPLE_CAP = 500_000
STORM_IDLE_PREFIX = b"/registry/configmaps/storm/"


def _rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024.0
    return 0.0


class _StormLedger:
    """The drill's exactly-once accounting: per-key final written seq,
    per-write stamp times, per-watch last delivered seq.  Coalescing
    may ELIDE intermediate seqs (latest-only is the contract) but may
    never regress one or miss the final state at quiesce."""

    def __init__(self, nkeys: int):
        self.final_seq = [0] * nkeys
        self.write_t: dict[tuple[int, int], float] = {}
        self.last_seq: dict[int, int] = {}    # wid -> newest seq seen
        self.key_of: dict[int, int] = {}      # hot wid -> key index
        # Watches excluded from the p99 population but NOT from the
        # loss/regression axes: the replica drill's watches sit behind
        # a deliberate mid-storm SIGKILL outage, and their catch-up lag
        # measures the restart window, not the fan-out path the p99
        # gate exists to bound.
        self.lag_exempt: set[int] = set()
        self.lags: list[float] = []
        self.regressions = 0
        self.idle_delivered = 0
        self.relisted = 0

    def on_event(self, wid: int, value: bytes, now: float) -> None:
        ki = self.key_of.get(wid)
        if ki is None:
            self.idle_delivered += 1
            return
        seq = int(value[:_SEQ_W])
        if seq < self.last_seq.get(wid, -1):
            self.regressions += 1
            return
        self.last_seq[wid] = seq
        if wid in self.lag_exempt:
            return
        t = self.write_t.get((ki, seq))
        if t is not None and len(self.lags) < _LAG_SAMPLE_CAP:
            self.lags.append(now - t)

    def lagging(self) -> int:
        n = 0
        for wid, ki in self.key_of.items():
            if self.last_seq.get(wid, 0) < self.final_seq[ki]:
                n += 1
        return n


class _StormMux:
    """One bidi Watch stream multiplexing many drill watches (the
    kube-apiserver-to-etcd shape; the only honest way to hold 100K
    watches from one core), feeding the ledger from its reader.

    The stream is read RAW (bytes deserializer): the reader decodes the
    wiretier shared-frame tail itself, fans one frame's events to every
    watch id riding it (index selection, never a re-parse per watch),
    and keeps the drill's wire accounting — actual bytes received vs
    what the unshared encoding would have cost for the same deliveries.
    """

    def __init__(self, channel, ledger: _StormLedger, cancels: asyncio.Queue):
        from k8s1m_tpu.store.proto import rpc_pb2

        self._pb = rpc_pb2
        self._call = channel.stream_stream(
            "/etcdserverpb.Watch/Watch",
            request_serializer=rpc_pb2.WatchRequest.SerializeToString,
            response_deserializer=lambda b: b,
        )()
        self.ledger = ledger
        self.cancels = cancels
        self.created = 0
        self.delivered = 0
        self.canceled = 0
        self.frames = 0
        self.shared_frames = 0
        self.bytes_on_wire = 0
        self.unshared_bytes = 0      # core bytes x watch ids sharing them
        self.create_rev = 0          # newest header revision on a create ack
        self.watch_rev: dict[int, int] = {}   # wid -> last delivered mod_rev
        self._reader = asyncio.create_task(self._read())

    async def create(self, pairs, start_revision: int = 0,
                     start_revisions: dict | None = None) -> None:
        """pairs: (wid, key) tuples to register on this stream.
        ``start_revisions`` overrides per wid (warm-restart reattach)."""
        pb = self._pb
        for wid, key in pairs:
            sr = start_revision
            if start_revisions is not None:
                sr = start_revisions.get(wid, start_revision)
            await self._call.write(
                pb.WatchRequest(
                    create_request=pb.WatchCreateRequest(
                        key=key, watch_id=wid,
                        start_revision=sr,
                    )
                )
            )

    async def wait_created(self, n: int, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while self.created < n:
            if time.monotonic() > deadline:
                raise TimeoutError(f"only {self.created}/{n} watches created")
            await asyncio.sleep(0.05)

    async def _read(self) -> None:
        import grpc

        from k8s1m_tpu.store.native import decode_shared_tail

        led = self.ledger
        pb = self._pb
        try:
            async for raw in self._call:
                extra, _from_rev, core_len = decode_shared_tail(raw)
                resp = pb.WatchResponse.FromString(raw)
                # canceled BEFORE created: a compact-cancel arrives as
                # ONE response with created=True AND canceled=True —
                # counting it as a successful create would leave the
                # watch silently dead (found by review).
                if resp.canceled:
                    self.canceled += 1
                    # Tier-initiated cancel (overflow / wedge break /
                    # invalidate / compact): the client's relist
                    # contract — hand the wid to the recreator.
                    await self.cancels.put((self, resp.watch_id))
                    continue
                if resp.created:
                    self.created += 1
                    if resp.header.revision > self.create_rev:
                        self.create_rev = resp.header.revision
                    continue
                if resp.events:
                    now = time.perf_counter()
                    wids = (resp.watch_id, *extra)
                    self.frames += 1
                    self.bytes_on_wire += len(raw)
                    # What len(wids) separate WatchResponses for the
                    # same events would have cost (each is the frame's
                    # core — header + watch_id + event chunks — minus
                    # the few extension varints the sharing adds).
                    self.unshared_bytes += core_len * len(wids)
                    if extra:
                        self.shared_frames += 1
                    self.delivered += len(resp.events) * len(wids)
                    last = resp.events[-1].kv.mod_revision
                    for wid in wids:
                        for ev in resp.events:
                            led.on_event(wid, ev.kv.value, now)
                        if last > self.watch_rev.get(wid, 0):
                            self.watch_rev[wid] = last
        except (asyncio.CancelledError, grpc.RpcError):
            pass

    async def close(self) -> None:
        self._reader.cancel()
        try:
            await self._reader
        # Close-path cancel: the reader is being torn down either way.
        except (asyncio.CancelledError, Exception):  # graftlint: disable=broad-except
            pass


class _ReplicaDrill:
    """The storm's fleet lane: a REAL watch-cache replica subprocess
    serving a slice of the hot keys, SIGKILLed mid-storm and relaunched
    with ``--resume-floor`` — the warm-restart contract under test is
    that its watch population resumes from revision (the relaunched
    replica catches its history window up from the floor and clients
    re-attach with per-watch start_revision) instead of relisting."""

    def __init__(self, upstream: str, lag_budget: int):
        self.upstream = upstream
        self.lag_budget = lag_budget
        self.proc = None
        self.port = 0
        self.metrics_port = 0
        self.chan = None
        self.mux: _StormMux | None = None
        self.keys: list = []        # (wid, key) pairs this replica serves
        self.report: dict = {}

    async def launch(self, resume_floor: int = 0) -> None:
        import socket
        import subprocess
        import sys

        from k8s1m_tpu.cluster.harness import _free_port

        self.port = _free_port()
        self.metrics_port = _free_port()
        cmd = [
            sys.executable, "-m", "k8s1m_tpu.store.watch_cache",
            "--upstream", self.upstream,
            "--host", "127.0.0.1", "--port", str(self.port),
            "--prefix", lease_key(LEASE_NS, "x")[:-1].decode(),
            "--lag-budget", str(self.lag_budget),
            "--metrics-port", str(self.metrics_port),
        ]
        if resume_floor:
            cmd += ["--resume-floor", str(resume_floor)]
        self.proc = subprocess.Popen(
            cmd, env={**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"},
        )
        deadline = time.monotonic() + 180
        while True:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"replica exited rc={self.proc.returncode}"
                )
            try:
                with socket.create_connection(
                    ("127.0.0.1", self.port), timeout=0.2
                ):
                    return
            except OSError:
                if time.monotonic() > deadline:
                    raise TimeoutError("replica did not bind")
                # Deadline-bounded readiness poll, not an op retry.
                await asyncio.sleep(0.05)  # graftlint: disable=retry-through-policy

    async def attach(self, ledger, cancels, pairs,
                     start_revisions: dict | None = None) -> None:
        from grpc import aio

        self.chan = aio.insecure_channel(
            f"127.0.0.1:{self.port}",
            options=[("grpc.max_receive_message_length", 64 << 20),
                     ("grpc.use_local_subchannel_pool", 1)],
        )
        self.mux = _StormMux(self.chan, ledger, cancels)
        await self.mux.create(pairs, start_revisions=start_revisions)
        await self.mux.wait_created(len(pairs), timeout=180)

    async def kill_and_restart(self, ledger, cancels) -> None:
        t0 = time.perf_counter()
        self.proc.kill()            # SIGKILL: no goodbye, no flush
        await asyncio.to_thread(self.proc.wait)
        old = self.mux
        await old.close()
        await self.chan.close()
        # The floor is the weakest watch's proven position: everything
        # after it is owed to SOMEONE, so the relaunched replica must
        # rebuild history from there.  Per-watch re-attach points stay
        # individual (a stream-level max would skip events for the
        # laggards).
        resume_at = {
            wid: max(old.watch_rev.get(wid, 0), old.create_rev)
            for wid, _ in self.keys
        }
        floor = min(resume_at.values())
        await self.launch(resume_floor=floor)
        await self.attach(
            ledger, cancels, self.keys,
            start_revisions={w: r + 1 for w, r in resume_at.items()},
        )
        self.report = {
            "resume_floor": floor,
            "restart_seconds": round(time.perf_counter() - t0, 2),
        }

    async def scrape(self) -> dict:
        """The relaunched replica's own /metrics, summed per counter."""
        import urllib.request

        def _get():
            with urllib.request.urlopen(
                f"http://127.0.0.1:{self.metrics_port}/metrics", timeout=10
            ) as r:
                return r.read().decode()

        out: dict = {}
        for line in (await asyncio.to_thread(_get)).splitlines():
            if line.startswith("#") or not line.strip():
                continue
            name, _, val = line.rpartition(" ")
            base = name.split("{", 1)[0]
            try:
                out[base] = out.get(base, 0.0) + float(val)
            except ValueError:
                continue
        return out

    async def close(self) -> None:
        import subprocess

        if self.mux is not None:
            await self.mux.close()
        if self.chan is not None:
            await self.chan.close()
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                await asyncio.to_thread(self.proc.wait, 10)
            except subprocess.TimeoutExpired:
                self.proc.kill()


async def run_storm(args) -> dict:
    """The watchplane kill drill: 18-per-node watch profile at
    ``--watchers`` total, seq-ledgered lease flood, composed fault plan,
    gates on loss / resume rate / delivery p99 / RSS."""
    from k8s1m_tpu import faultline
    from k8s1m_tpu.faultline import FaultPlan, install_plan
    from k8s1m_tpu.obs.metrics import REGISTRY
    from k8s1m_tpu.store.native import WireFront
    from grpc import aio

    per_node = args.watchers_per_node + _IDLE_PER_NODE
    nodes = max(1, args.watchers // per_node)
    nkeys = nodes
    n_hot = nodes * args.watchers_per_node
    n_idle = nodes * _IDLE_PER_NODE
    total_watches = n_hot + n_idle

    resumes = REGISTRY.get("watchcache_resumes_total")
    invals = REGISTRY.get("watchcache_invalidations_total")
    coalesced = REGISTRY.get("watchcache_coalesced_events_total")
    r0, i0, c0 = resumes.value(), invals.value(), coalesced.value()

    if args.fault_plan:
        install_plan(FaultPlan.from_arg(args.fault_plan))

    store = MemStore()
    # Native wire server: keeps the store off this event loop (the
    # tier, the writers and the mux readers all share it already).
    wf = WireFront(store)
    seed = EtcdClient(f"127.0.0.1:{wf.port}")
    ledger = _StormLedger(nkeys)
    hot_keys = [lease_key(LEASE_NS, f"storm-{i:06d}") for i in range(nkeys)]
    tier = None
    muxes: list[_StormMux] = []
    channels = []
    relist_client = None
    recreator = None
    replica = (
        _ReplicaDrill(f"127.0.0.1:{wf.port}", args.lag_budget)
        if args.replica_drill else None
    )
    rep_restart = None
    rep_scrape: dict = {}
    try:
        wave = []
        for i in range(n_idle):
            wave.append((STORM_IDLE_PREFIX + b"cm-%07d" % i, b'{"data":{}}'))
            if len(wave) >= 8192:
                await seed.put_batch(wave)
                wave.clear()
        for ki in range(nkeys):
            wave.append((hot_keys[ki], b"%0*d" % (_SEQ_W, 0) + _PAD))
        if wave:
            await seed.put_batch(wave)

        t_prime = time.perf_counter()
        tier = await serve_watch_cache(
            f"127.0.0.1:{wf.port}", [STORM_IDLE_PREFIX,
                                     lease_key(LEASE_NS, "x")[:-1]],
            port=0, index="hash", lag_budget=args.lag_budget,
        )
        prime_s = time.perf_counter() - t_prime
        cancels: asyncio.Queue = asyncio.Queue()
        channels = [
            aio.insecure_channel(
                f"127.0.0.1:{tier.port}",
                options=[("grpc.max_receive_message_length", 64 << 20),
                         ("grpc.use_local_subchannel_pool", 1)],
            )
            for _ in range(max(1, args.streams // 8))
        ]
        muxes = [
            _StormMux(channels[i % len(channels)], ledger, cancels)
            for i in range(args.streams)
        ]
        relist_client = EtcdClient(
            f"127.0.0.1:{tier.port}",
            options=[("grpc.use_local_subchannel_pool", 1)],
        )

        async def recreate_canceled():
            """The client half of the relist contract: a canceled watch
            reads its key through the tier (progress-gated, so the read
            reflects every write the cancel postdates) and re-attaches
            from the read revision."""
            import grpc as _grpc

            while True:
                mux, wid = await cancels.get()
                ki = ledger.key_of.get(wid)
                if ki is None:
                    continue        # idle watch: count only (no loss axis)
                resp = await relist_client.range(hot_keys[ki])
                if resp.kvs:
                    seq = int(resp.kvs[0].value[:_SEQ_W])
                    if seq > ledger.last_seq.get(wid, 0):
                        ledger.last_seq[wid] = seq
                ledger.relisted += 1
                try:
                    await mux.create(
                        [(wid, hot_keys[ki])],
                        start_revision=resp.header.revision + 1,
                    )
                except _grpc.RpcError:
                    # A cancel racing the replica drill's SIGKILL: the
                    # stream died under us.  The warm-restart path
                    # re-attaches the replica's whole population from
                    # per-watch revisions — nothing to do here.
                    continue

        recreator = asyncio.create_task(recreate_canceled())

        # ---- create the watch population (idle first, then hot) ----
        t0 = time.perf_counter()
        next_wid = 1
        per_mux = (n_idle + len(muxes) - 1) // len(muxes)
        expect = [0] * len(muxes)
        for mi, m in enumerate(muxes):
            lo = mi * per_mux
            pairs = [
                (next_wid + j, STORM_IDLE_PREFIX + b"cm-%07d" % (lo + j))
                for j in range(min(per_mux, max(0, n_idle - lo)))
            ]
            next_wid += len(pairs)
            expect[mi] += len(pairs)
            await m.create(pairs)
        hot_pairs: list[list] = [[] for _ in muxes]
        for wi in range(n_hot):
            ki = wi % nkeys
            # Place a key's hot watchers on the SAME stream (keyed, not
            # round-robin by watcher): the kube shape — one apiserver
            # multiplexes all watches for an object over one etcd
            # stream — and the layout under which the tier's shared
            # frames actually share (a frame can only carry the watch
            # ids of one stream).
            mi = ki % len(muxes)
            wid = next_wid
            next_wid += 1
            ledger.key_of[wid] = ki
            hot_pairs[mi].append((wid, hot_keys[ki]))
        for mi, pairs in enumerate(hot_pairs):
            expect[mi] += len(pairs)
            await muxes[mi].create(pairs)
        for m, n in zip(muxes, expect):
            await m.wait_created(n, timeout=240 + total_watches / 500)
        create_s = time.perf_counter() - t0
        rss_after_create = _rss_mb()

        # ---- the replica fleet lane: a watch-cache replica subprocess
        # serves the TOP slice of the hot key range (disjoint from the
        # flood subset, keys [0, nkeys/8)), gets SIGKILLed as the flood
        # opens, and must come back warm.  Its watches ride the same
        # ledger (zero-loss and monotonicity axes) but are lag-exempt:
        # their catch-up lag measures the deliberate outage window.
        if replica is not None:
            await replica.launch()
            n_rep_keys = min(256, max(1, nkeys // 4))
            pairs = []
            for i in range(n_rep_keys):
                rki = nkeys - 1 - i
                wid = next_wid
                next_wid += 1
                ledger.key_of[wid] = rki
                ledger.lag_exempt.add(wid)
                pairs.append((wid, hot_keys[rki]))
            replica.keys = pairs
            await replica.attach(ledger, cancels, pairs)

        # ---- the storm window: steady -> flood -> steady writes.
        # Steady thirds pace at --rate over ALL keys (the kubelet-
        # renewal shape); the flood third bursts unpaced at
        # flood-factor x the batch onto a 1/8 key subset — a true
        # thundering herd, so the floodiest watchers' queues actually
        # cross the lag budget and degrade to latest-only while the
        # rest of the population stays on FIFO delivery.
        t0 = time.perf_counter()
        total = args.writes
        written = 0
        ki = 0
        base = max(64, min(1000, args.rate // 8))
        # The flood third must actually FLOOD: bound the hot subset so
        # each unpaced burst lands ~2x the tier's lag budget on every
        # flooded key, forcing the latest-only coalescing the wire and
        # p99 gates are about — not a polite elevated drizzle the pumps
        # absorb without ever degrading anyone.
        flood_keys = max(1, min(
            nkeys // 8,
            base * args.flood_factor // max(1, args.lag_budget * 2),
        ))
        while written < total:
            in_flood = total // 3 <= written < 2 * (total // 3)
            if replica is not None and rep_restart is None and in_flood:
                # SIGKILL the replica exactly as the flood opens — the
                # worst moment — and warm-restart it while the storm
                # keeps writing.
                rep_restart = asyncio.create_task(
                    replica.kill_and_restart(ledger, cancels)
                )
            n = min(base * (args.flood_factor if in_flood else 1),
                    total - written)
            t = time.perf_counter()
            items = []
            span = flood_keys if in_flood else nkeys
            for j in range(n):
                k = (ki + j) % span
                s = ledger.final_seq[k] + 1
                ledger.final_seq[k] = s
                ledger.write_t[(k, s)] = t
                items.append((hot_keys[k], b"%0*d" % (_SEQ_W, s) + _PAD))
            ki = (ki + n) % span
            await seed.put_batch(items)
            written += n
            if not in_flood:
                # Pace to the steady rate, net of time already spent.
                pause = n / args.rate - (time.perf_counter() - t)
                if pause > 0:
                    await asyncio.sleep(pause)
        write_s = time.perf_counter() - t0
        if rep_restart is not None:
            await asyncio.wait_for(rep_restart, timeout=300)

        rss_after_writes = _rss_mb()
        # ---- quiesce: every hot watch must reach its key's final seq
        deadline = time.monotonic() + 180
        lagging = ledger.lagging()
        while lagging and time.monotonic() < deadline:
            await asyncio.sleep(0.25)
            lagging = ledger.lagging()
        window_s = time.perf_counter() - t0
        store_watchers = store.stats()["watchers"]
        tier_stats = tier.cache.stats()
        rss_quiesce = _rss_mb()
        if replica is not None:
            rep_scrape = await replica.scrape()
    finally:
        if recreator is not None:
            recreator.cancel()
        if replica is not None:
            await replica.close()
        for m in muxes:
            await m.close()
        for ch in channels:
            await ch.close()
        if relist_client is not None:
            await relist_client.close()
        if tier is not None:
            await tier.close()
        await seed.close()
        fired = faultline.active_injector().fire_report()
        install_plan(None)
        wf.close()
        store.close()

    breaks = sum(
        f["fires"] for f in fired
        if f["op"] == "upstream.recv"
        and f["kind"] not in ("delay", "slow_cycle")
    )
    d_resumes = resumes.value() - r0
    d_invals = invals.value() - i0
    d_coalesced = coalesced.value() - c0
    resume_rate = (
        d_resumes / max(1, d_resumes + d_invals) if breaks else None
    )
    lags = sorted(ledger.lags)
    p50 = lags[len(lags) // 2] if lags else None
    p99 = lags[min(len(lags) - 1, int(len(lags) * 0.99))] if lags else None
    peak_rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    delivered = sum(m.delivered for m in muxes)
    # ---- wire accounting (main fan-out muxes; the replica lane is a
    # separate outage drill).  measured_fanout is the drill's ACTUAL
    # per-event delivery degree (nominal 3 hot watchers per key, net of
    # latest-only elisions and cancel->relist gaps); the shared-frame
    # wire must recoup at least that factor for bytes_per_delivered_event
    # to have dropped by the fan-out degree vs the unshared encoding.
    frames = sum(m.frames for m in muxes)
    shared_frames = sum(m.shared_frames for m in muxes)
    bytes_on_wire = sum(m.bytes_on_wire for m in muxes)
    unshared_bytes = sum(m.unshared_bytes for m in muxes)
    measured_fanout = delivered / max(1, tier_stats["events_in"])
    wire_drop = unshared_bytes / max(1, bytes_on_wire)
    rep_resumes = rep_scrape.get("watchcache_resumes_total", 0.0)
    rep_invals = rep_scrape.get("watchcache_invalidations_total", 0.0)
    gates = {
        "zero_loss": lagging == 0,
        "no_regressions": ledger.regressions == 0,
        "idle_silent": ledger.idle_delivered == 0,
        "lag_measured": bool(lags),
        "p99_bounded": p99 is not None and p99 <= args.p99_budget,
        # The named storm must actually have stormed: upstream breaks
        # injected, and >= 90% of them resolved by resume, not relist.
        "stormed": args.fault_plan != "watchstorm" or breaks > 0,
        "breaks_resolved": breaks == 0 or (d_resumes + d_invals) > 0,
        "resume_rate": resume_rate is None or resume_rate >= 0.9,
        # Gate on the steady resident footprint at quiesce — the
        # tier's actual cost at this watch population.  The ru_maxrss
        # peak is reported alongside but not gated: under CI
        # contention transient allocator spikes (glibc arena growth
        # across grpc's thread pool) poison the peak with non-tier
        # memory while the steady footprint stays flat.
        "rss_bounded": (
            not args.rss_budget_mb or rss_quiesce <= args.rss_budget_mb
        ),
        # Shared frames must recoup at least the measured fan-out
        # degree in bytes: what N unshared responses would have cost
        # for the SAME deliveries, over what actually crossed the wire.
        "wire_compaction": frames > 0 and wire_drop >= measured_fanout,
        # The killed replica must come back WARM: its own counters show
        # resume-from-revision (diff replay against the rebuilt history
        # window), and zero invalidations — no relist storm.
        "replica_warm_restart": (
            not args.replica_drill
            or (rep_resumes >= 1 and rep_invals == 0)
        ),
    }
    passed = all(gates.values())
    return {
        "metric": "watch_fanout_storm" + ("_smoke" if args.smoke else ""),
        "value": total_watches,
        "unit": "client watches under composed storm",
        "vs_baseline": round(total_watches / 18_000_000, 5),
        "passed": passed,
        "shape": {
            "watchers": total_watches, "hot": n_hot, "idle": n_idle,
            "keys": nkeys, "writes": args.writes, "streams": args.streams,
            "flood_factor": args.flood_factor,
            "fault_plan": args.fault_plan,
        },
        "gates": gates,
        "evidence": {
            "store_watchers": store_watchers,
            "prime_seconds": round(prime_s, 2),
            "create_per_sec": round(total_watches / create_s, 1),
            "write_seconds": round(write_s, 2),
            "window_seconds": round(window_s, 2),
            "delivered": delivered,
            "delivered_per_sec": round(delivered / window_s, 1),
            "frames": frames,
            "frames_shared_ratio": round(shared_frames / max(1, frames), 4),
            "bytes_on_wire_total": bytes_on_wire,
            "bytes_per_delivered_event": round(
                bytes_on_wire / max(1, delivered), 1
            ),
            "unshared_bytes_per_event": round(
                unshared_bytes / max(1, delivered), 1
            ),
            "wire_compaction_drop": round(wire_drop, 3),
            "measured_fanout": round(measured_fanout, 3),
            "coalesced_events": int(d_coalesced),
            "tier_backlog_at_end": tier_stats["backlog"],
            "upstream_breaks": breaks,
            "resumes": int(d_resumes),
            "invalidations": int(d_invals),
            "resume_rate": resume_rate,
            "watches_canceled": sum(m.canceled for m in muxes),
            "watches_relisted": ledger.relisted,
            "lagging_at_quiesce": lagging,
            "seq_regressions": ledger.regressions,
            "idle_delivered": ledger.idle_delivered,
            "lag_p50_ms": round(p50 * 1000, 1) if p50 is not None else None,
            "lag_p99_ms": round(p99 * 1000, 1) if p99 is not None else None,
            "p99_budget_s": args.p99_budget,
            "rss_mb_after_create": round(rss_after_create, 1),
            "rss_mb_after_writes": round(rss_after_writes, 1),
            "rss_mb_at_quiesce": round(rss_quiesce, 1),
            "peak_rss_mb": round(peak_rss_mb, 1),
            "rss_budget_mb": args.rss_budget_mb or None,
            "replica_drill": (
                {
                    **(replica.report if replica is not None else {}),
                    "replica_watches": (
                        len(replica.keys) if replica is not None else 0
                    ),
                    "replica_delivered": (
                        replica.mux.delivered
                        if replica is not None and replica.mux is not None
                        else 0
                    ),
                    "resumes": int(rep_resumes),
                    "invalidations": int(rep_invals),
                }
                if args.replica_drill else None
            ),
            "faults": fired,
        },
    }


def main(argv=None):
    args = parse_args(argv)
    if args.watchers or args.fault_plan or args.smoke:
        result = asyncio.run(run_storm(args))
        if args.out:
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                json.dump(result, f, indent=1)
        print(json.dumps(result))
        return
    for line in asyncio.run(amain(args)):
        print(json.dumps(line))


if __name__ == "__main__":
    main()
