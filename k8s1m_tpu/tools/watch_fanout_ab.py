"""Watch-amplification A/B through the watch-cache tier.

Reproduces the shape of the reference's apiserver findings
(reference README.adoc:410-416, 495-499): every node holds several
watches on its own objects (18 per kubelet+kube-proxy in the reference;
``--watchers-per-node`` here), all served by the fan-out tier from ONE
store watch — the store sees the write load, never the watch load.  The
``--index both`` mode runs the experiment under the hash and btree cache
storages, the reference's ``BtreeWatchCache`` ceiling axis.

    python -m k8s1m_tpu.tools.watch_fanout_ab --nodes 50 --writes 20000

Prints one BENCH-style JSON line per index mode:
``store_events_per_sec`` (events entering the tier) vs
``delivered_per_sec`` (events fanned out to client watches), plus the
store-side watcher count proving the amplification never reaches it.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

from k8s1m_tpu.store.etcd_client import EtcdClient
from k8s1m_tpu.store.etcd_server import serve
from k8s1m_tpu.store.native import MemStore
from k8s1m_tpu.store.watch_cache import serve_watch_cache
from k8s1m_tpu.control.objects import lease_key
from k8s1m_tpu.tools.lease_flood import LEASE_NS, lease_value

_STREAMS_PER_CHANNEL = 80   # under the server's max_concurrent_streams=100


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="watch fan-out A/B")
    ap.add_argument("--nodes", type=int, default=50)
    ap.add_argument("--watchers-per-node", type=int, default=3,
                    help="HOT client watches per node object (lease "
                         "updates fan out to these)")
    ap.add_argument("--idle-watches-per-node", type=int, default=0,
                    help="additional idle watches per node on objects "
                         "that never change (configmaps/secrets in the "
                         "reference's 18-watches-per-kubelet profile, "
                         "README.adoc:410-416) — they must cost the "
                         "store nothing and deliver nothing")
    ap.add_argument("--writes", type=int, default=10000)
    ap.add_argument("--batch", type=int, default=500,
                    help="producer batch size (BatchKV wave)")
    ap.add_argument("--index", choices=("hash", "btree", "both"),
                    default="both")
    ap.add_argument("--quiet", action="store_true")
    return ap.parse_args(argv)


async def run_one(index: str, args, store: MemStore, store_port: int) -> dict:
    lease_prefix = lease_key(LEASE_NS, "x")[:-1]    # .../kube-node-lease/
    cm_prefix = b"/registry/configmaps/kube-system/"
    prefixes = [lease_prefix]
    producer = EtcdClient(f"127.0.0.1:{store_port}")
    if args.idle_watches_per_node:
        # The idle population watches per-node config objects that are
        # written once and never again (the configmap/secret share of the
        # reference's 18-watches-per-kubelet profile).
        prefixes.append(cm_prefix)
        await producer.put_batch([
            (cm_prefix + f"node-cfg-{i}-{j}".encode(), b'{"data":{}}')
            for i in range(args.nodes)
            for j in range(args.idle_watches_per_node)
        ])
    tier = await serve_watch_cache(
        f"127.0.0.1:{store_port}", prefixes, port=0, index=index,
    )
    cache, cache_port = tier.cache, tier.port
    n_hot = args.nodes * args.watchers_per_node
    n_idle = args.nodes * args.idle_watches_per_node
    n_sessions = n_hot + n_idle
    n_channels = (n_sessions + _STREAMS_PER_CHANNEL - 1) // _STREAMS_PER_CHANNEL
    clients = [
        EtcdClient(f"127.0.0.1:{cache_port}",
                   options=[("grpc.use_local_subchannel_pool", 1)])
        for _ in range(max(1, n_channels))
    ]
    sessions = []
    idle_sessions = []
    for i in range(n_hot):
        node = f"kwok-node-{i % args.nodes}"
        s = clients[i % len(clients)].watch(lease_key(LEASE_NS, node))
        await s.__aenter__()
        sessions.append(s)
    for i in range(n_idle):
        key = cm_prefix + (
            f"node-cfg-{i % args.nodes}-{i // args.nodes}".encode()
        )
        s = clients[(n_hot + i) % len(clients)].watch(key)
        await s.__aenter__()
        idle_sessions.append(s)

    expected = args.writes * args.watchers_per_node
    delivered = 0
    stream_errors = 0
    done = asyncio.Event()

    async def drain(s):
        nonlocal delivered, stream_errors
        while not done.is_set():
            try:
                batch = await s.next(timeout=15)
            except asyncio.TimeoutError:
                return
            # Counted, not logged: stream_errors is the report's signal.
            except Exception:  # graftlint: disable=broad-except
                # A broken stream must surface as an error, not masquerade
                # as a fan-out throughput ceiling.
                stream_errors += 1
                return
            delivered += len(batch.events)
            if delivered >= expected:
                done.set()

    drainers = [asyncio.create_task(drain(s)) for s in sessions]

    idle_delivered = 0

    async def idle_drain(s):
        nonlocal idle_delivered, stream_errors
        while not done.is_set():
            try:
                batch = await s.next(timeout=15)
            except asyncio.TimeoutError:
                continue    # expected quiet — keep listening to the end
            # Counted, not logged: stream_errors is the report's signal.
            except Exception:  # graftlint: disable=broad-except
                # A broken idle stream must not masquerade as "idle
                # watches deliver nothing" — that's the claim under test.
                stream_errors += 1
                return
            idle_delivered += len(batch.events)

    drainers += [asyncio.create_task(idle_drain(s)) for s in idle_sessions]

    t0 = time.perf_counter()
    i = 0
    while i < args.writes:
        n = min(args.batch, args.writes - i)
        items = []
        for j in range(i, i + n):
            node = f"kwok-node-{j % args.nodes}"
            items.append(
                (lease_key(LEASE_NS, node), lease_value(node, j // args.nodes))
            )
        await producer.put_batch(items)
        i += n
    write_s = time.perf_counter() - t0
    try:
        await asyncio.wait_for(done.wait(), timeout=60)
    except asyncio.TimeoutError:
        pass
    total_s = time.perf_counter() - t0

    store_watchers = store.stats()["watchers"]
    st = cache.stats()
    for t in drainers:
        t.cancel()
    for s in sessions + idle_sessions:
        await s.cancel()
    for c in clients:
        await c.close()
    await producer.close()
    await tier.close()

    return {
        "index": index,
        "nodes": args.nodes,
        "client_watches": n_sessions,
        "idle_watches": n_idle,
        "store_watches": store_watchers,     # 1 per prefix: fan-out proof
        "writes": args.writes,
        "writes_per_sec": round(args.writes / write_s, 1),
        "store_events_per_sec": round(st["events_in"] / total_s, 1),
        "delivered": delivered,
        "idle_delivered": idle_delivered,    # must be 0: idle watches are free
        "delivered_per_sec": round(delivered / total_s, 1),
        "amplification": round(delivered / max(1, st["events_in"]), 2),
        "stream_errors": stream_errors,
    }


async def amain(args) -> list[dict]:
    store = MemStore()
    server, store_port = await serve(store, port=0)
    out = []
    try:
        modes = ("hash", "btree") if args.index == "both" else (args.index,)
        for index in modes:
            out.append(await run_one(index, args, store, store_port))
    finally:
        await server.stop(None)
        store.close()
    return out


def main(argv=None):
    for line in asyncio.run(amain(parse_args(argv))):
        print(json.dumps(line))


if __name__ == "__main__":
    main()
