"""Bulk-create KWOK-style Node objects (the make_nodes equivalent,
reference kwok/make_nodes/main.go:116-182).

    python -m k8s1m_tpu.tools.make_nodes --count 100000 --zones 8 --regions 4

Nodes get the same shape the reference gives its KWOK nodes: type=kwok
annotation-ish label, a kwok-group shard label (10 groups, matching the
reference's 10-controller StatefulSet, kwok-controller.yaml:9,53),
topology zone/region labels, and allocatable capacity.
"""

from __future__ import annotations

import argparse
import asyncio
import json

from k8s1m_tpu.control.objects import encode_node, node_key
from k8s1m_tpu.snapshot.node_table import NodeInfo
from k8s1m_tpu.tools.common import (
    RateReporter,
    add_common_args,
    client_factory,
    run_sharded,
)

KWOK_GROUPS = 10


def build_node(
    i: int,
    *,
    prefix: str = "kwok-node",
    zones: int = 8,
    regions: int = 4,
    cpu_milli: int = 32000,
    mem_kib: int = 64 << 20,
    pods: int = 110,
) -> NodeInfo:
    return NodeInfo(
        name=f"{prefix}-{i}",
        cpu_milli=cpu_milli,
        mem_kib=mem_kib,
        pods=pods,
        labels={
            "type": "kwok",
            "kwok-group": str(i % KWOK_GROUPS),
            "topology.kubernetes.io/zone": f"zone-{i % zones}",
            "topology.kubernetes.io/region": f"region-{i % regions}",
        },
    )


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="bulk-create KWOK-style nodes")
    add_common_args(ap)
    ap.add_argument("--count", type=int, default=1000)
    ap.add_argument("--start", type=int, default=0)
    ap.add_argument("--prefix", default="kwok-node")
    ap.add_argument("--zones", type=int, default=8)
    ap.add_argument("--regions", type=int, default=4)
    ap.add_argument("--cpu", type=int, default=32000, help="milliCPU allocatable")
    ap.add_argument("--mem-kib", type=int, default=64 << 20)
    ap.add_argument("--pods", type=int, default=110)
    return ap.parse_args(argv)


async def amain(args) -> dict:
    reporter = RateReporter("nodes created", quiet=args.quiet)

    async def work(client, i):
        n = args.start + i
        node = build_node(
            n, prefix=args.prefix, zones=args.zones, regions=args.regions,
            cpu_milli=args.cpu, mem_kib=args.mem_kib, pods=args.pods,
        )
        await client.put(node_key(node.name), encode_node(node))

    await run_sharded(
        args.count, args.concurrency, client_factory(args), work,
        clients=args.clients, reporter=reporter,
    )
    return reporter.summary()


def main(argv=None):
    print(json.dumps(asyncio.run(amain(parse_args(argv)))))


if __name__ == "__main__":
    main()
