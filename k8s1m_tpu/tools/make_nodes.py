"""Bulk-create KWOK-style Node objects (the make_nodes equivalent,
reference kwok/make_nodes/main.go:116-182).

    python -m k8s1m_tpu.tools.make_nodes --count 100000 --zones 8 --regions 4

Nodes get the same shape the reference gives its KWOK nodes: type=kwok
annotation-ish label, a kwok-group shard label (10 groups, matching the
reference's 10-controller StatefulSet, kwok-controller.yaml:9,53),
topology zone/region labels, and allocatable capacity.
"""

from __future__ import annotations

import argparse
import asyncio
import json

from k8s1m_tpu.control.objects import encode_node, node_key
from k8s1m_tpu.snapshot.node_table import NodeInfo
from k8s1m_tpu.tools.common import (
    RateReporter,
    add_common_args,
    client_factory,
    run_sharded,
)

KWOK_GROUPS = 10


def build_node(
    i: int,
    *,
    prefix: str = "kwok-node",
    zones: int = 8,
    regions: int = 4,
    cpu_milli: int = 32000,
    mem_kib: int = 64 << 20,
    pods: int = 110,
) -> NodeInfo:
    return NodeInfo(
        name=f"{prefix}-{i}",
        cpu_milli=cpu_milli,
        mem_kib=mem_kib,
        pods=pods,
        labels={
            "type": "kwok",
            "kwok-group": str(i % KWOK_GROUPS),
            "topology.kubernetes.io/zone": f"zone-{i % zones}",
            "topology.kubernetes.io/region": f"region-{i % regions}",
        },
    )


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="bulk-create KWOK-style nodes")
    add_common_args(ap)
    ap.add_argument("--count", type=int, default=1000)
    ap.add_argument("--start", type=int, default=0)
    ap.add_argument("--prefix", default="kwok-node")
    ap.add_argument("--zones", type=int, default=8)
    ap.add_argument("--regions", type=int, default=4)
    ap.add_argument("--cpu", type=int, default=32000, help="milliCPU allocatable")
    ap.add_argument("--mem-kib", type=int, default=64 << 20)
    ap.add_argument("--pods", type=int, default=110)
    ap.add_argument("--bulk", type=int, default=1,
                    help="batch N node puts per RPC over the BatchKV "
                    "put-frame extension (our store server; connection "
                    "reuse comes from the shared client pool).  The "
                    "one-put-per-node default is itself a bottleneck "
                    "at 1M nodes; --bulk 1024 is the megarow "
                    "registration lane")
    return ap.parse_args(argv)


async def amain(args) -> dict:
    reporter = RateReporter(
        "nodes created", quiet=args.quiet, milestone=100_000,
    )

    def node_item(n: int) -> tuple[bytes, bytes]:
        node = build_node(
            n, prefix=args.prefix, zones=args.zones, regions=args.regions,
            cpu_milli=args.cpu, mem_kib=args.mem_kib, pods=args.pods,
        )
        return node_key(node.name), encode_node(node)

    if args.bulk > 1:
        bulk = args.bulk

        async def work(client, b):
            lo = args.start + b * bulk
            hi = min(lo + bulk, args.start + args.count)
            items = [node_item(n) for n in range(lo, hi)]
            await client.put_batch(items)
            return len(items)

        total = -(-args.count // bulk)
    else:
        async def work(client, i):
            key, value = node_item(args.start + i)
            await client.put(key, value)

        total = args.count

    await run_sharded(
        total, args.concurrency, client_factory(args), work,
        clients=args.clients, reporter=reporter,
    )
    return reporter.summary()


def main(argv=None):
    print(json.dumps(asyncio.run(amain(parse_args(argv)))))


if __name__ == "__main__":
    main()
