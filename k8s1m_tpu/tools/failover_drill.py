"""ISSUE 9 failover drill: kill-active-mid-wave and paused-leader
split-brain, under tenant load, gated on crash consistency.

Two replicas (``alpha`` the initial leader, ``beta`` the standby) run
the full HA surface over one store — tick-driven with an injected
clock, so every scenario replays deterministically:

**mid_wave_kill** (run twice: warm standby and cold standby) — alpha
pipelines waves at depth N under continuous tenant load (including
4-pod gangs); the faultline ``kill_process`` kind on the
``coordinator.lease`` hook SIGKILLs it mid-wave (no lease release, no
flush; a partially-bound gang is seeded in the store the way a crash
between a wave's bind CASes and its gang settlement leaves one).  Beta
takes over on lease expiry — warm: ``Coordinator.promote`` (drain the
mirror's watch backlog + pinned relist-from-revision diff); cold:
full bootstrap — recovers the half-bound gang all-or-none, and drains
the backlog.

**split_brain** — alpha is SIGSTOP'd (faultline ``pause``) *between its
leadership check and its writes*, with in-flight waves, past lease
expiry; the drill's ``on_pause`` callback advances beta through the
steal deterministically.  When alpha resumes it still believes its
pre-pause election observation and tries to retire its waves: every
bind must be refused by the lease-epoch fence
(``fencing_rejected_total`` > 0) and drain to requeue, never to the
store.

Gates (one JSON line; committed to ``artifacts/failover_drill.json``):

- 0 lost pods: every admitted pod is bound in the final store state;
- 0 double-binds: the full store event history (watched from revision
  1) never shows a bind landing on an already-bound pod;
- fencing rejects > 0 in the split-brain scenario (and the deposed
  reign binds nothing);
- takeover ≤ a few cycles: first bind within ``--takeover-cycles`` of
  lease acquisition;
- byte consistency: the recovered coordinator's host mirror
  (cpu/mem/pods per node, bound-key set) equals an independent
  recomputation from the final store facts, exactly;
- warm < cold: ``failover_recovery_seconds`` for the warm takeover
  beats the cold boot (both reported).

    python -m k8s1m_tpu.tools.failover_drill --smoke \\
        --out artifacts/failover_drill.json
"""

from __future__ import annotations

import argparse
import json
import os
import time


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="coordinator failover drill")
    ap.add_argument("--nodes", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument("--depth", type=int, default=3)
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--pods-per-tick", type=int, default=192)
    ap.add_argument("--pre-ticks", type=int, default=12,
                    help="loaded ticks before the kill/pause lands")
    ap.add_argument("--drain-ticks", type=int, default=4000)
    ap.add_argument("--takeover-cycles", type=int, default=2,
                    help="slack cycles past the pipeline ramp: the first "
                    "bind must land within depth + this many cycles of "
                    "lease acquisition (a depth-N pipeline retires its "
                    "first wave on cycle N+1 by design)")
    ap.add_argument("--seed", type=int, default=9)
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 shape: tiny cluster, same gates")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.smoke:
        args.nodes, args.batch, args.chunk = 256, 64, 64
        args.pods_per_tick = 48
        args.pre_ticks = 6
    return args


class World:
    """One scenario's cluster: store, nodes, replica pair, producer,
    and the exactly-once bind ledger."""

    def __init__(self, args, *, warm_standby: bool):
        from k8s1m_tpu.config import PodSpec, TableSpec
        from k8s1m_tpu.control.coordinator import (
            PODS_PREFIX,
            Coordinator,
        )
        from k8s1m_tpu.control.leader import HACoordinator, LeaderElector
        from k8s1m_tpu.control.objects import encode_node, node_key
        from k8s1m_tpu.loadshed import LoadshedConfig
        from k8s1m_tpu.plugins.registry import Profile
        from k8s1m_tpu.snapshot.node_table import NodeInfo
        from k8s1m_tpu.store.native import MemStore, prefix_end
        from k8s1m_tpu.tenancy import TenancyController, TenancyPolicy

        self.args = args
        self.store = MemStore()
        self.pods_prefix = PODS_PREFIX
        for i in range(args.nodes):
            self.store.put(
                node_key(f"n{i:05d}"),
                encode_node(NodeInfo(
                    f"n{i:05d}", cpu_milli=1 << 22, mem_kib=1 << 30,
                    pods=1 << 20,
                )),
            )
        # Full-history ledger watch BEFORE any pod exists: every pod
        # create/bind/evict event lands here for the double-bind audit.
        self.ledger = self.store.watch(
            PODS_PREFIX, prefix_end(PODS_PREFIX),
            start_revision=1, queue_cap=1 << 21,
        )

        b = args.batch
        weights = {f"tenant-{t}": t + 1 for t in range(args.tenants)}
        self.tenants = list(weights)

        def make_coord():
            tn = TenancyController(
                TenancyPolicy(weights=weights),
                loadshed_config=LoadshedConfig(
                    queue_degraded=64 * b, queue_shed=128 * b,
                    queue_cap=1 << 20, queue_recover=b,
                ),
                name=f"failover-{id(object())}",
            )
            return Coordinator(
                self.store,
                TableSpec(max_nodes=args.nodes, max_zones=16, max_regions=8),
                PodSpec(batch=b),
                Profile(topology_spread=0, interpod_affinity=0),
                chunk=args.chunk, k=4, with_constraints=False,
                seed=args.seed, score_pct=50, pipeline=True,
                depth=args.depth, tenancy=tn,
            )

        self.alpha = HACoordinator(LeaderElector(self.store, "alpha"),
                                   make_coord)
        self.beta = HACoordinator(
            LeaderElector(self.store, "beta", retry_period_s=1.0),
            make_coord, warm_standby=warm_standby,
        )
        self.seq = 0
        self.admitted: list[str] = []     # "<ns>/<name>" expected bound
        self.now = 0.0

    # ---- load ----------------------------------------------------------

    def produce(self, n: int, *, gang_every: int = 64) -> None:
        """Write n pending pods across tenants; every ``gang_every``th
        seq opens a 4-pod gang (labels force the full decode path)."""
        from k8s1m_tpu.control.objects import encode_pod, pod_key
        from k8s1m_tpu.snapshot.pod_encoding import PodInfo

        i = 0
        while i < n:
            self.seq += 1
            t = self.tenants[self.seq % len(self.tenants)]
            if gang_every and self.seq % gang_every == 0 and n - i >= 4:
                gid = f"g{self.seq:06d}"
                for m in range(4):
                    p = PodInfo(
                        f"{gid}-m{m}", namespace=t, cpu_milli=10,
                        mem_kib=1 << 10,
                        labels={"k8s1m.io/gang": gid,
                                "k8s1m.io/gang-size": "4"},
                    )
                    self.store.put(pod_key(t, p.name), encode_pod(p))
                    self.admitted.append(f"{t}/{p.name}")
                i += 4
                continue
            p = PodInfo(f"p{self.seq:07d}", namespace=t, cpu_milli=10,
                        mem_kib=1 << 10)
            self.store.put(pod_key(t, p.name), encode_pod(p))
            self.admitted.append(f"{t}/{p.name}")
            i += 1

    def seed_partial_gang(self) -> str:
        """The crash artifact recover_gangs exists for: a 4-pod gang
        with 2 members already bound in the store (the predecessor's
        CASes landed) and 2 still pending — written directly, the way
        a death between a wave's binds and its gang settlement leaves
        it.  Returns the gang id."""
        from k8s1m_tpu.control.objects import encode_pod, pod_key
        from k8s1m_tpu.snapshot.pod_encoding import PodInfo

        t = self.tenants[0]
        gid = "crash-gang"
        for m in range(4):
            p = PodInfo(
                f"{gid}-m{m}", namespace=t, cpu_milli=10, mem_kib=1 << 10,
                labels={"k8s1m.io/gang": gid, "k8s1m.io/gang-size": "4"},
                node_name=f"n{m:05d}" if m < 2 else "",
            )
            self.store.put(pod_key(t, p.name), encode_pod(p))
            self.admitted.append(f"{t}/{p.name}")
        return f"{t}/{gid}"

    # ---- settle + audits ----------------------------------------------

    def drain(self, ha) -> int:
        """Tick ``ha`` until the backlog settles; returns binds."""
        total = 0
        c = ha.coord
        for _ in range(self.args.drain_ticks):
            self.now += 1.0
            total += ha.tick(self.now)
            c = ha.coord
            if c is None:
                continue
            if (
                not c.queue and not c._inflights and not c._backoff
                and not c._gang_parked and not c._gang_staging
                and not c._external_pending()
            ):
                break
            w = c.backoff_wait_s()
            if w:
                time.sleep(min(w, 0.05))
        if c is not None:
            total += c.flush()
        return total

    def audit_ledger(self) -> dict:
        """Replay the full pod event history: a PUT carrying a nodeName
        on a pod already in the bound state is a double-bind (an evict
        — PUT without nodeName — legally returns it to pending)."""
        from k8s1m_tpu.store.native import drain_events_light

        bound: set[str] = set()
        double = 0
        binds = 0
        evicts = 0
        for etype, key, value, _mrev in drain_events_light(
            self.ledger, limit=1 << 30
        ):
            k = key[len(self.pods_prefix):].decode()
            if etype == 1:
                bound.discard(k)
                continue
            if b'"nodeName"' in value:
                if k in bound:
                    double += 1
                else:
                    bound.add(k)
                    binds += 1
            else:
                if k in bound:
                    evicts += 1
                bound.discard(k)
        return {"binds": binds, "evictions": evicts,
                "double_binds": double}

    def audit_lost(self) -> int:
        from k8s1m_tpu.control.objects import pod_key

        lost = 0
        for k in self.admitted:
            ns, name = k.split("/", 1)
            kv = self.store.get(pod_key(ns, name))
            if kv is None or b'"nodeName"' not in kv.value:
                lost += 1
        return lost

    def audit_consistency(self, coord) -> dict:
        """Byte consistency: recompute per-node (cpu, mem, pods) and
        the bound-key set from the final store facts alone and demand
        EXACT equality with the recovered coordinator's host mirror."""
        from k8s1m_tpu.control.objects import decode_pod
        from k8s1m_tpu.store.native import list_prefix

        exp: dict[str, list[int]] = {}
        exp_bound: set[str] = set()
        kvs, _ = list_prefix(self.store, self.pods_prefix)
        for kv in kvs:
            if b'"nodeName"' not in kv.value:
                continue
            pod = decode_pod(kv.value, coord.tracker)
            if not pod.node_name:
                continue
            exp_bound.add(pod.key)
            u = exp.setdefault(pod.node_name, [0, 0, 0])
            u[0] += pod.cpu_milli
            u[1] += pod.mem_kib
            u[2] += 1
        host = coord.host
        mismatches = 0
        for name, row in host._row_of.items():
            want = exp.get(name, [0, 0, 0])
            got = [int(host.cpu_req[row]), int(host.mem_req[row]),
                   int(host.pods_req[row])]
            if got != want:
                mismatches += 1
        extra = set(coord._bound) - exp_bound
        missing = exp_bound - set(coord._bound)
        return {
            "row_mismatches": mismatches,
            "bound_extra": len(extra),
            "bound_missing": len(missing),
            "byte_consistent": not (mismatches or extra or missing),
        }

    def close(self) -> None:
        for ha in (self.alpha, self.beta):
            try:
                ha.stop()
            except Exception:  # graftlint: disable=broad-except (drill teardown must reach store.close)
                pass
        self.ledger.cancel()
        self.store.close()


def run_kill(args, *, warm: bool) -> dict:
    """Kill-active-mid-wave: SIGKILL alpha via faultline, beta takes
    over (warm promote or cold boot), recovers the half-bound gang,
    drains everything."""
    from k8s1m_tpu import faultline
    from k8s1m_tpu.faultline import FaultPlan, FaultSpec, install_plan

    w = World(args, warm_standby=warm)
    try:
        w.produce(args.batch)
        bound = w.alpha.tick(w.now)          # alpha cold-boots, leads
        assert w.alpha.elector.is_leader
        for _ in range(args.pre_ticks):
            w.now += 1.0
            w.produce(args.pods_per_tick)
            bound += w.alpha.tick(w.now)
            w.beta.tick(w.now)               # beta follows (warm) or idles
        inflight_at_kill = len(w.alpha.coord._inflights)
        mirror_queue = (
            len(w.beta._mirror.queue) if w.beta._mirror is not None else 0
        )
        # The SIGKILL, by plan: fires on alpha's NEXT lease tick only.
        install_plan(FaultPlan(
            [FaultSpec("coordinator.lease", "tick/alpha",
                       kind="kill_process", every_n=1, max_fires=1)],
            seed=args.seed,
        ))
        w.now += 1.0
        w.alpha.tick(w.now)
        assert w.alpha._killed
        killed_at = w.now
        gang_key = w.seed_partial_gang()
        # No-leader window: the webhook sink is queue-or-429.
        from k8s1m_tpu.loadshed import Overloaded
        from k8s1m_tpu.control.objects import encode_pod, pod_key
        from k8s1m_tpu.snapshot.pod_encoding import PodInfo

        queued_429 = {"queued": 0, "rejected": 0}
        for i in range(8):
            p = PodInfo(f"noleader-{i}", namespace=w.tenants[0],
                        cpu_milli=10, mem_kib=1 << 10)
            try:
                w.beta.submit_external(json.loads(encode_pod(p)))
                queued_429["queued"] += 1
            except Overloaded as e:
                assert e.reason == "no-leader"
                queued_429["rejected"] += 1
                continue
            w.store.put(pod_key(p.namespace, p.name), encode_pod(p))
            w.admitted.append(f"{p.namespace}/{p.name}")
        # Beta waits out the lease and takes over (the acquiring tick
        # itself already steps the promoted coordinator once).
        got = 0
        while not w.beta.elector.is_leader and w.now < killed_at + 60:
            w.now += 1.0
            got = w.beta.tick(w.now)
        assert w.beta.elector.is_leader
        acquired_at = w.now
        # Takeover promptness: cycles from acquisition to the first
        # bind.  A depth-N pipeline retires its first wave on cycle N+1
        # by design, so the gate is depth + slack.
        cycle_limit = args.depth + args.takeover_cycles
        b_bound = got
        cycles_to_bind = 1 if got else None
        c = 1
        while cycles_to_bind is None and c < cycle_limit:
            c += 1
            w.now += 1.0
            got = w.beta.tick(w.now)
            b_bound += got
            if got:
                cycles_to_bind = c
        b_bound += w.drain(w.beta)
        fired = faultline.active_injector().fire_counts()
        install_plan(None)
        ledger = w.audit_ledger()
        lost = w.audit_lost()
        consistency = w.audit_consistency(w.beta.coord)
        gang_ns = gang_key.split("/")[0]
        gang_ok = all(
            b'"nodeName"' in w.store.get(
                pod_key(gang_ns, f"crash-gang-m{m}")
            ).value
            for m in range(4)
        )
        return {
            "mode": w.beta.takeover_mode,
            "recovery_s": w.beta.last_recovery_s,
            "promote_stats": w.beta.last_promote_stats,
            "admitted": len(w.admitted),
            "leader_bound_before_kill": bound,
            "standby_bound_after": b_bound,
            "inflight_at_kill": inflight_at_kill,
            "standby_mirror_queue_at_kill": mirror_queue,
            "takeover_wait_ticks": acquired_at - killed_at,
            "cycles_to_first_bind": cycles_to_bind,
            "no_leader_sink": queued_429,
            "kill_process_fired": fired.get("kill_process", 0),
            "crash_gang_recovered_bound": gang_ok,
            "ledger": ledger,
            "lost": lost,
            "consistency": consistency,
            "passed": bool(
                lost == 0
                and ledger["double_binds"] == 0
                and consistency["byte_consistent"]
                and gang_ok
                and cycles_to_bind is not None
                and cycles_to_bind <= cycle_limit
                and inflight_at_kill > 0
            ),
        }
    finally:
        install_plan(None)
        w.close()


def run_split_brain(args) -> dict:
    """Paused-leader split-brain: alpha freezes (SIGSTOP) between its
    leadership check and its writes, past lease expiry; beta steals;
    alpha resumes and tries to retire its in-flight waves — the fence
    must reject every one."""
    from k8s1m_tpu import faultline
    from k8s1m_tpu.faultline import FaultPlan, FaultSpec, install_plan
    from k8s1m_tpu.obs.metrics import REGISTRY

    w = World(args, warm_standby=True)
    fence_rej = REGISTRY.get("fencing_rejected_total")

    def rejects() -> float:
        return sum(
            fence_rej.value(path=p) for p in ("bind", "evict", "preempt")
        )

    try:
        w.produce(args.batch)
        w.alpha.tick(w.now)
        assert w.alpha.elector.is_leader
        for _ in range(args.pre_ticks):
            w.now += 1.0
            w.produce(args.pods_per_tick)
            w.alpha.tick(w.now)
            w.beta.tick(w.now)
        inflight_at_pause = len(w.alpha.coord._inflights)
        lease = w.alpha.elector.lease_duration_s

        stolen = {"at": None}

        def on_pause(_decision):
            # The world moves on while alpha is frozen: beta ticks
            # through lease expiry and takes over (warm promote).
            t = w.now
            for _ in range(int(lease) + 5):
                t += 1.0
                w.produce(args.pods_per_tick // 4)
                w.beta.tick(t)
            assert w.beta.elector.is_leader
            stolen["at"] = t

        w.alpha.on_pause = on_pause
        install_plan(FaultPlan(
            [FaultSpec("coordinator.lease", "tick/alpha", kind="pause",
                       delay_s=lease + 5.0, every_n=1, max_fires=1)],
            seed=args.seed,
        ))
        r0 = rejects()
        # Alpha's paused tick: its elector (frozen clock) still believes
        # leadership; after the freeze it retires in-flight waves — the
        # fence must send every bind to requeue, not the store.
        w.now += 1.0
        deposed_bound = w.alpha.tick(w.now)
        fencing_rejected = rejects() - r0
        # Alpha catches up with real time and steps down.
        w.now = stolen["at"] + 1.0
        deposed_bound += w.alpha.tick(w.now)
        alpha_stepped_down = not w.alpha.elector.is_leader
        fired = faultline.active_injector().fire_counts()
        install_plan(None)
        b_bound = w.drain(w.beta)
        ledger = w.audit_ledger()
        lost = w.audit_lost()
        consistency = w.audit_consistency(w.beta.coord)
        return {
            "mode": w.beta.takeover_mode,
            "recovery_s": w.beta.last_recovery_s,
            "promote_stats": w.beta.last_promote_stats,
            "admitted": len(w.admitted),
            "inflight_at_pause": inflight_at_pause,
            "pause_fired": fired.get("pause", 0),
            "fencing_rejected": fencing_rejected,
            "deposed_leader_bound": deposed_bound,
            "alpha_stepped_down": alpha_stepped_down,
            "standby_bound_after": b_bound,
            "ledger": ledger,
            "lost": lost,
            "consistency": consistency,
            "passed": bool(
                lost == 0
                and ledger["double_binds"] == 0
                and consistency["byte_consistent"]
                and fencing_rejected > 0
                and deposed_bound == 0
                and alpha_stepped_down
                and inflight_at_pause > 0
            ),
        }
    finally:
        install_plan(None)
        w.close()


def run(args) -> dict:
    kill_cold = run_kill(args, warm=False)
    kill_warm = run_kill(args, warm=True)
    split = run_split_brain(args)
    warm_s = kill_warm["recovery_s"]
    cold_s = kill_cold["recovery_s"]
    return {
        "mid_wave_kill_cold": kill_cold,
        "mid_wave_kill_warm": kill_warm,
        "split_brain": split,
        "recovery_warm_s": warm_s,
        "recovery_cold_s": cold_s,
        "warm_speedup": (cold_s / warm_s) if warm_s else None,
        "passed": bool(
            kill_cold["passed"] and kill_warm["passed"] and split["passed"]
            and warm_s is not None and cold_s is not None
            and warm_s < cold_s
        ),
    }


def main(argv=None) -> dict:
    args = parse_args(argv)
    evidence = run(args)
    result = {
        "metric": "failover_drill" + ("_smoke" if args.smoke else ""),
        "value": evidence["warm_speedup"],
        "unit": "warm-standby takeover speedup vs cold boot (x)",
        "vs_baseline": None,
        "passed": evidence["passed"],
        "seed": args.seed,
        "shape": {
            "nodes": args.nodes, "batch": args.batch, "depth": args.depth,
            "tenants": args.tenants, "pods_per_tick": args.pods_per_tick,
            "pre_ticks": args.pre_ticks,
            "takeover_cycles_gate": args.takeover_cycles,
        },
        "evidence": evidence,
    }
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
