"""Component-cost probe for the fused pallas kernel.

Times fused_topk over a pct-window-sized table with individual score
plugins disabled — weights are static arguments, so a zeroed plugin is
dead-code-eliminated from the trace and its cost shows up as the delta
against the full profile.  The tool for answering "where do the
ms/batch go" on the real chip (the XLA scan path can be profiled the
same way through bench.py --backend xla).

    python -m k8s1m_tpu.tools.kernel_probe --nodes 53248 --batch 8192

Prints one JSON line per variant.  Run variants serially on the one
real chip; each recompiles (~15-30s).
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp

from k8s1m_tpu.config import PodSpec, TableSpec
from k8s1m_tpu.cluster import populate_kwok_nodes, uniform_pods
from k8s1m_tpu.ops.pallas_topk import fused_topk
from k8s1m_tpu.plugins.registry import Profile
from k8s1m_tpu.snapshot import NodeTableHost, PodBatchHost


def variants() -> dict[str, Profile]:
    base = dict(node_affinity=0, topology_spread=0, interpod_affinity=0)
    return {
        "full": Profile(**base),
        "no-least-allocated": Profile(least_allocated=0, **base),
        "no-balanced-allocation": Profile(balanced_allocation=0, **base),
        "no-taint-toleration": Profile(taint_toleration=0, **base),
        "filter-only": Profile(
            least_allocated=0, balanced_allocation=0, taint_toleration=0,
            **base,
        ),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description="pallas kernel component probe")
    ap.add_argument("--nodes", type=int, default=13 * 4096,
                    help="table rows (default: the 1M-table pct5 window)")
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--chunk", type=int, default=1 << 12)
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--only", default=None,
                    help="comma-separated variant names (default: all)")
    ap.add_argument(
        "--backend", choices=("pallas", "xla"), default="pallas",
        help="pallas times the fused kernel; xla times the scan path's "
        "filter_score_topk with the same plugin-knockout variants "
        "(engine/cycle.py) — the decomposition tool for whichever "
        "backend is under investigation",
    )
    args = ap.parse_args(argv)

    spec = TableSpec(max_nodes=args.nodes)
    host = NodeTableHost(spec)
    populate_kwok_nodes(host, args.nodes)
    table = host.to_device()
    enc = PodBatchHost(PodSpec(batch=args.batch), spec, host.vocab)
    batch = enc.encode(uniform_pods(args.batch))

    if args.backend == "xla":
        import functools

        from k8s1m_tpu.engine.cycle import filter_score_topk
        from k8s1m_tpu.snapshot.pod_encoding import unpack_pod_batch

        # The PRODUCTION path: packed buffers + trace-time field groups,
        # so selector-free waves prune the affinity machinery exactly
        # like the coordinator's step does.  (Probing with a plain
        # PodBatch keeps all-NONE selector arrays as runtime inputs XLA
        # cannot DCE — ~45s/wave of dead label resolution on CPU.)
        packed = enc.encode_packed(uniform_pods(args.batch))

        @functools.lru_cache(maxsize=None)
        def _xla_fn(prof):
            # One jit wrapper per profile — rebuilding it per step would
            # recompile every step.
            def fn(table, ints, bools, key):
                b = unpack_pod_batch(
                    ints, bools, packed.spec, packed.table_spec,
                    packed.groups,
                )
                return filter_score_topk(
                    table, b, key, prof, chunk=args.chunk, k=args.k
                ).idx

            return jax.jit(fn)

        def run_xla(prof, key):
            return _xla_fn(prof)(table, packed.ints, packed.bools, key)

    picked = variants()
    if args.only:
        names = {n.strip() for n in args.only.split(",")}
        picked = {n: p for n, p in picked.items() if n in names}
    for name, prof in picked.items():
        if args.backend == "xla":
            keys = list(jax.random.split(jax.random.key(0), args.steps + 1))
            idx = run_xla(prof, keys[0])
            jax.device_get(idx)  # compile + settle
            t0 = time.perf_counter()
            for i in range(args.steps):
                idx = run_xla(prof, keys[i + 1])
            jax.device_get(idx)
        else:
            idx, _ = fused_topk(
                table, batch, jnp.int32(0), prof,
                chunk=args.chunk, k=args.k, with_affinity=False,
            )
            jax.device_get(idx)      # compile + settle
            t0 = time.perf_counter()
            for i in range(args.steps):
                idx, _ = fused_topk(
                    table, batch, jnp.int32(i + 1), prof,
                    chunk=args.chunk, k=args.k, with_affinity=False,
                )
            # the relay needs a fetch, not block_until_ready
            jax.device_get(idx)
        dt = (time.perf_counter() - t0) / args.steps
        print(json.dumps({
            "variant": name,
            "backend": args.backend,
            "ms_per_batch": round(dt * 1e3, 2),
            "binds_per_sec_equiv": round(args.batch / dt, 1),
            "nodes": args.nodes,
            "batch": args.batch,
        }), flush=True)


if __name__ == "__main__":
    main()
