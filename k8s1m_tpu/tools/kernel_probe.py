"""Component-cost probe for the fused pallas kernel.

Times fused_topk over a pct-window-sized table with individual score
plugins disabled — weights are static arguments, so a zeroed plugin is
dead-code-eliminated from the trace and its cost shows up as the delta
against the full profile.  The tool for answering "where do the
ms/batch go" on the real chip (the XLA scan path can be profiled the
same way through bench.py --backend xla).

    python -m k8s1m_tpu.tools.kernel_probe --nodes 53248 --batch 8192

Prints one JSON line per variant.  Run variants serially on the one
real chip; each recompiles (~15-30s).
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp

from k8s1m_tpu.config import PodSpec, TableSpec
from k8s1m_tpu.cluster import populate_kwok_nodes, uniform_pods
from k8s1m_tpu.ops.pallas_topk import fused_topk
from k8s1m_tpu.plugins.registry import Profile
from k8s1m_tpu.snapshot import NodeTableHost, PodBatchHost


def variants() -> dict[str, Profile]:
    base = dict(node_affinity=0, topology_spread=0, interpod_affinity=0)
    return {
        "full": Profile(**base),
        "no-least-allocated": Profile(least_allocated=0, **base),
        "no-balanced-allocation": Profile(balanced_allocation=0, **base),
        "no-taint-toleration": Profile(taint_toleration=0, **base),
        "filter-only": Profile(
            least_allocated=0, balanced_allocation=0, taint_toleration=0,
            **base,
        ),
    }


def profile_stages(
    table,
    enc,
    *,
    chunk: int,
    k: int = 4,
    steps: int = 3,
    repeats: int = 3,
    backend: str = "xla",
    only: set[str] | None = None,
) -> dict:
    """Per-stage ms/batch via the plugin-knockout DCE trick, reusable
    from sched_bench's ``--kernel-profile`` lane.

    Zeroed plugin weights are static arguments, so a disabled scorer is
    dead-code-eliminated from the trace; ``full - no-X`` is X's cost and
    ``filter-only`` is the irreducible filter+top-k floor.  ``table``
    may be either snapshot layout (packed tables decode in the chunk
    slice, so the probe measures the production decode cost too); ``enc``
    is a PodBatchHost-compatible encoder sharing the table's vocab.

    Each variant is timed as the MIN over ``repeats`` independent
    ``steps``-iteration blocks: the minimum is the right estimator for
    a deterministic program under one-sided scheduler noise, and a
    single-block mean let a noisy ``full`` sample push knockout deltas
    negative (the committed taint_toleration -3.524 ms/batch artifact).
    Deltas can still dip slightly negative at tiny shapes; they are
    reported raw, not clamped — but ``repeats`` is recorded in the
    return so the report says how hard the noise was squeezed.

    Returns {"backend", "repeats", "ms_per_batch": {variant: ms},
    "stages": {plugin: ms-delta}}.
    """
    import functools as _ft

    from k8s1m_tpu.engine.cycle import filter_score_topk
    from k8s1m_tpu.snapshot.pod_encoding import unpack_pod_batch

    pods = uniform_pods(enc.spec.batch)
    picked = variants()
    if only:
        picked = {n: p for n, p in picked.items() if n in only}
    ms: dict[str, float] = {}

    if backend == "pallas":
        batch = enc.encode(pods)

        def run(prof, i):
            idx, _ = fused_topk(
                table, batch, jnp.int32(i), prof,
                chunk=chunk, k=k, with_affinity=False,
            )
            return idx
    else:
        packed = enc.encode_packed(pods)
        keys = list(jax.random.split(jax.random.key(0), steps + 1))

        @_ft.lru_cache(maxsize=None)
        def _fn(prof):
            def fn(table, ints, bools, key):
                b = unpack_pod_batch(
                    ints, bools, packed.spec, packed.table_spec,
                    packed.groups,
                )
                return filter_score_topk(
                    table, b, key, prof, chunk=chunk, k=k
                ).idx

            return jax.jit(fn)

        def run(prof, i):
            return _fn(prof)(table, packed.ints, packed.bools, keys[i])

    if repeats < 1:
        raise ValueError(f"repeats must be >= 1, got {repeats}")
    for name, prof in picked.items():
        idx = run(prof, 0)
        jax.device_get(idx)      # compile + settle
        best = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            for i in range(steps):
                idx = run(prof, i + 1)
            jax.device_get(idx)  # the relay needs a fetch (module doc)
            dt = (time.perf_counter() - t0) / steps * 1e3
            best = dt if best is None else min(best, dt)
        ms[name] = round(best, 3)

    stages: dict[str, float] = {}
    if "full" in ms:
        for knock, label in (
            ("no-least-allocated", "least_allocated"),
            ("no-balanced-allocation", "balanced_allocation"),
            ("no-taint-toleration", "taint_toleration"),
        ):
            if knock in ms:
                # full - knocked-out = the zeroed plugin's cost.
                stages[label] = round(ms["full"] - ms[knock], 3)
        if "filter-only" in ms:
            stages["filter_topk_floor"] = ms["filter-only"]
    return {
        "backend": backend, "repeats": repeats,
        "ms_per_batch": ms, "stages": stages,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description="pallas kernel component probe")
    ap.add_argument("--nodes", type=int, default=13 * 4096,
                    help="table rows (default: the 1M-table pct5 window)")
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--chunk", type=int, default=1 << 12)
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument(
        "--repeats", type=int, default=3,
        help="timing blocks per variant; min-of-repeats is reported "
        "(one-sided noise estimator — keeps knockout deltas from going "
        "negative when a single block catches a scheduler hiccup)",
    )
    ap.add_argument("--only", default=None,
                    help="comma-separated variant names (default: all)")
    ap.add_argument(
        "--backend", choices=("pallas", "xla"), default="pallas",
        help="pallas times the fused kernel; xla times the scan path's "
        "filter_score_topk with the same plugin-knockout variants "
        "(engine/cycle.py) — the decomposition tool for whichever "
        "backend is under investigation",
    )
    ap.add_argument(
        "--packing", choices=("off", "packed"), default=None,
        help="device-snapshot layout (snapshot/packing.py): 'packed' "
        "probes the bit/byte-packed production layout, so the per-chunk "
        "decode cost shows up in every variant's ms.  Unset defers to "
        "K8S1M_PACKING — same resolution as bench.py/sched_bench, so "
        "one env var keeps the whole evidence pipeline on one layout",
    )
    args = ap.parse_args(argv)
    from k8s1m_tpu.snapshot.packing import resolve_packing

    args.packing = resolve_packing(args.packing)

    spec = TableSpec(max_nodes=args.nodes)
    host = NodeTableHost(spec)
    populate_kwok_nodes(host, args.nodes)
    from k8s1m_tpu.snapshot.packing import is_packed, pack_table_auto

    if args.packing == "packed":
        table = pack_table_auto(host, spec)
    else:
        table = host.to_device()
    enc = PodBatchHost(PodSpec(batch=args.batch), spec, host.vocab)

    only = (
        {n.strip() for n in args.only.split(",")} if args.only else None
    )
    picked = variants()
    for name in picked:
        if only and name not in only:
            continue
        # One variant per profile_stages call so each JSON line lands as
        # soon as its variant finishes (serial on-chip runs recompile
        # per variant, ~15-30s each).
        res = profile_stages(
            table, enc, chunk=args.chunk, k=args.k, steps=args.steps,
            repeats=args.repeats, backend=args.backend, only={name},
        )
        dt_ms = res["ms_per_batch"][name]
        print(json.dumps({
            "variant": name,
            "backend": args.backend,
            "repeats": args.repeats,
            # The mode actually in effect: pack_table_auto falls back
            # to unpacked when taint_slots outgrow the meta word.
            "packing": "packed" if is_packed(table) else "off",
            "ms_per_batch": dt_ms,
            "binds_per_sec_equiv": (
                round(args.batch / (dt_ms / 1e3), 1) if dt_ms else None
            ),
            "nodes": args.nodes,
            "batch": args.batch,
        }), flush=True)


if __name__ == "__main__":
    main()
