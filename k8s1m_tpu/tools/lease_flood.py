"""Flood the store with node-lease updates — the dominant write load of a
1M-node cluster (the etcd-lease-flood equivalent, reference
etcd-lease-flood/main.go:117-149: 1M kubelets renewing a 40s lease every
10s is ~100K writes/s, README.adoc:142-151).

Progress prints every 100K leases (the make_nodes ``--bulk``
convention — an hour-scale flood's heartbeat, not 1s rate spam), and
``--fault-plan`` (tools/common.py; named plans like ``watchstorm``
work) installs a deterministic injector so the storm drill can break
the tier's upstream watch MID-flood.

    python -m k8s1m_tpu.tools.lease_flood --nodes 10000 --rounds 10
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

from k8s1m_tpu.control.objects import lease_key
from k8s1m_tpu.tools.common import (
    RateReporter,
    add_common_args,
    apply_fault_plan,
    client_factory,
    run_sharded,
)

LEASE_NS = "kube-node-lease"


def lease_value(node: str, seq: int) -> bytes:
    # Kubernetes Lease objects are small; model the renewTime bump that
    # makes every renewal a fresh write.
    return json.dumps(
        {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": node, "namespace": LEASE_NS},
            "spec": {
                "holderIdentity": node,
                "leaseDurationSeconds": 40,
                "renewTime": f"seq-{seq}",
            },
        },
        separators=(",", ":"),
    ).encode()


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="node-lease write flood")
    add_common_args(ap)
    ap.add_argument("--nodes", type=int, default=1000)
    ap.add_argument("--rounds", type=int, default=10,
                    help="lease renewals per node")
    ap.add_argument("--prefix", default="kwok-node")
    ap.add_argument("--batch", type=int, default=0,
                    help="puts per BatchKV.PutFrame RPC (0 = per-put RPCs "
                         "like the reference's etcd-lease-flood; >0 "
                         "pipelines waves over the private batch wire)")
    return ap.parse_args(argv)


async def amain(args) -> dict:
    apply_fault_plan(args)
    reporter = RateReporter(
        "lease puts", quiet=args.quiet, milestone=100_000
    )
    total = args.nodes * args.rounds

    async def work(client, i):
        node = f"{args.prefix}-{i % args.nodes}"
        seq = i // args.nodes
        await client.put(lease_key(LEASE_NS, node), lease_value(node, seq))

    async def work_batched(client, bi):
        lo = bi * args.batch
        items = []
        for i in range(lo, min(lo + args.batch, total)):
            node = f"{args.prefix}-{i % args.nodes}"
            items.append(
                (lease_key(LEASE_NS, node), lease_value(node, i // args.nodes))
            )
        await client.put_batch(items)
        return len(items)  # run_sharded counts individual puts, not RPCs

    t0 = time.perf_counter()
    if args.batch > 0:
        n_batches = (total + args.batch - 1) // args.batch
        await run_sharded(
            n_batches, args.concurrency, client_factory(args), work_batched,
            clients=args.clients, reporter=reporter,
        )
    else:
        await run_sharded(
            total, args.concurrency, client_factory(args), work,
            clients=args.clients, reporter=reporter,
        )
    out = reporter.summary()
    out["count"] = total
    out["puts_per_sec"] = round(total / (time.perf_counter() - t0), 1)
    return out


def main(argv=None):
    print(json.dumps(asyncio.run(amain(parse_args(argv)))))


if __name__ == "__main__":
    main()
