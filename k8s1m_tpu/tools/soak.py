"""Secured-tier churn soak: the week-long-watch scenario at bench scale.

The reference's apiserver findings are about what survives TIME: 18M
kubelet watches held for days over a control plane sustaining continuous
create/bind/delete churn (reference README.adoc:410-416, 721-730).  This
driver runs that shape end to end for ``--seconds`` (default 600):

  native store server  <-TLS+bearer-  watch-cache tier  <-TLS+bearer-
  { an idle watch population (mux streams, never written),
    a hot canary watch set,
    sched_bench --churn --rate  (create -> schedule -> CAS bind ->
    delete, the full coordinator loop) }

while sampling the tier's and the store server's RSS every
``--sample-every`` seconds.  Pass criteria, printed as one JSON line and
written (with the RSS series) to ``--out``:

- ``rss_flat``: neither process's RSS trend grows more than
  ``--max-growth-pct`` between the first and last thirds of the window
  (no per-watch or per-event leak);
- ``canceled == 0``: the idle population survives the whole soak (the
  round-4 flow-control hardening exists precisely so long-lived streams
  never stall out);
- ``stalls == 0``: after the churn window every canary watch still
  delivers a fresh write within ``--canary-timeout`` seconds — the
  streams are live, not just uncanceled;
- ``event_loss == 0``: every canary write issued during the soak is
  delivered exactly once, counted across any mid-soak failover (the
  watch-event-loss ledger).

    python -m k8s1m_tpu.tools.soak --seconds 600 --idle 5000 --rate 300

**Faultline mode** (the hour-scale robustness drill, ISSUE 1): run the
same shape under an active deterministic fault plan
(k8s1m_tpu/faultline) with a mid-soak tier-replica SIGKILL, WAL fsync
on, and a forced compaction right behind the kill:

    python -m k8s1m_tpu.tools.soak --seconds 3600 --rate 300 \
        --fault-plan default --tier-replicas 2 --kill-tier-at 1800 \
        --wal-mode fsync --out artifacts/soak_faultline.json

The canary population rides the victim replica; at ``--kill-tier-at``
the driver SIGKILLs it, then resumes every canary on the survivor from
its last delivered revision (the haproxy-pulls-a-dead-backend contract,
test_tier_replicas.py) and measures recovery time until the ledger is
caught up.  The fault plan itself reaches the churn bench via
``K8S1M_FAULT_PLAN`` — injected wire faults are retried by the shared
RetryPolicy and surface in the output as ``resilience`` (injected-fault
counts, retry totals, p50/p99 recovery per fault class).  Note: a
``watch.tier`` upstream fault cancels that replica's clients BY
CONTRACT (the cache cannot re-serve lost events), so the canned default
plan exercises the client-side classes and leaves tier failure to the
harsher SIGKILL drill.

**Overload phase** (``--overload-at`` / ``--overload-factor`` /
``--overload-seconds``): mid-soak the churn bench's offered rate steps
to ``rate x factor`` for the window, then back — the hour-scale
shed-and-recover counterpart of the deterministic tier-1
``tools/overload_drill.py``.  Composes with ``--fault-plan`` and the
tier SIGKILL, so one soak exercises faults, failover, and overload in
the same run.

**Coordinator-failover phase** (``--kill-coordinator-at``, ISSUE 9):
the kill drills above exercise the STORE side of the control plane (a
watch-cache tier replica dies; canaries resume on the survivor).  This
phase kills the *scheduler*: at the given second of the churn window
the composed ``tools/failover_drill`` runs alongside the soak —
kill-active-mid-wave (warm standby promote vs cold boot) and the
paused-leader split-brain, gated on 0 lost pods / 0 double-binds /
fencing rejects observed — so one soak covers both halves of "kill any
control-plane process and nothing is lost".  Its result is merged as
``coordinator_failover`` and folds into the run's pass gate.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

from grpc import aio

IDLE_PREFIX = b"/registry/configmaps/soak/"
CANARY_PREFIX = b"/registry/leases/soak/"

# The canned --fault-plan=default drill: every client-side fault class
# at rates an hour of churn turns into hundreds of firings, plus a
# schedule-driven coordinator watch loss.  Deterministic by seed.
DEFAULT_FAULT_PLAN = {
    "seed": 42,
    "faults": [
        {"component": "store.wire", "op": "put", "kind": "disconnect",
         "probability": 0.002},
        {"component": "store.wire", "op": "put_batch",
         "kind": "partial_write", "probability": 0.01},
        {"component": "store.wire", "op": "bind_batch",
         "kind": "disconnect", "probability": 0.005},
        {"component": "store.wire", "op": "range", "kind": "delay",
         "probability": 0.005, "delay_s": 0.02},
        {"component": "store.wire", "op": "watch.recv",
         "kind": "disconnect", "probability": 0.0005},
        {"component": "coordinator.bind", "op": "cas",
         "kind": "stale_revision", "probability": 0.002},
        {"component": "coordinator.watch", "op": "poll",
         "kind": "disconnect", "after": 10_000, "every_n": 200_000},
    ],
}


def _rss_mb(pid: int) -> float:
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="secured-tier churn soak")
    ap.add_argument("--seconds", type=float, default=600.0,
                    help="churn window length (the soak proper)")
    ap.add_argument("--idle", type=int, default=5000,
                    help="idle watch population held through the tier")
    ap.add_argument("--canaries", type=int, default=32,
                    help="hot watches probed for liveness at the end")
    ap.add_argument("--rate", type=int, default=300,
                    help="offered churn load (pods/s) for sched_bench")
    ap.add_argument("--nodes", type=int, default=16384)
    ap.add_argument("--sample-every", type=float, default=5.0)
    ap.add_argument("--compact-every", type=float, default=60.0,
                    help="periodic MVCC compaction interval (the "
                    "apiserver's --etcd-compaction-interval role; "
                    "without it sustained churn grows store history "
                    "unboundedly by design)")
    ap.add_argument("--max-growth-pct", type=float, default=10.0,
                    help="max allowed RSS growth, first vs last third "
                    "of the post-warmup series")
    ap.add_argument("--warmup", type=float, default=180.0,
                    help="seconds excluded from the RSS-flatness gate: "
                    "watch history windows, MVCC steady-state population "
                    "and allocator arenas legitimately fill during "
                    "ramp-up; a LEAK keeps growing after it")
    ap.add_argument("--canary-timeout", type=float, default=30.0)
    ap.add_argument("--out", default=None,
                    help="result path (default: artifacts/soak_secured_"
                    "tier.json, or artifacts/soak_faultline.json when a "
                    "fault plan is active)")
    ap.add_argument("--fault-plan", default=None,
                    help="faultline plan: inline JSON, @path, or "
                    "'default' for the canned client-side drill "
                    "(k8s1m_tpu/faultline; exported to the churn bench "
                    "via K8S1M_FAULT_PLAN)")
    ap.add_argument("--tier-replicas", type=int, default=1,
                    help="watch-cache tier replicas (>= 2 enables the "
                    "kill drill: canaries ride the last replica)")
    ap.add_argument("--kill-tier-at", type=float, default=0.0,
                    help="SIGKILL the last tier replica this many "
                    "seconds into the churn window (0 = no kill; "
                    "requires --tier-replicas >= 2)")
    ap.add_argument("--kill-coordinator-at", type=float, default=0.0,
                    help="run the coordinator-failover drill "
                    "(tools/failover_drill --smoke: mid-wave SIGKILL "
                    "with warm-standby takeover + paused-leader "
                    "split-brain under fencing) alongside the soak, "
                    "launched this many seconds into the churn window "
                    "(0 = off)")
    ap.add_argument("--wal-mode", default="buffered",
                    choices=["none", "buffered", "fsync"],
                    help="store WAL durability for the soak (the "
                    "faultline drill runs fsync)")
    ap.add_argument("--overload-at", type=float, default=0.0,
                    help="seconds into the churn window to start a "
                    "sustained overload phase: the churn bench's "
                    "offered rate jumps to rate x --overload-factor "
                    "for --overload-seconds, then drops back — the "
                    "hour-scale shed-and-recover counterpart of the "
                    "tier-1 overload_drill (0 = off)")
    ap.add_argument("--overload-seconds", type=float, default=300.0)
    ap.add_argument("--overload-factor", type=float, default=5.0)
    ap.add_argument("--tenants", type=int, default=0,
                    help="tenant-aware churn load: the churn bench "
                    "spreads its pods over N tenant namespaces with "
                    "zipf-skewed sizes (sched_bench --tenants)")
    ap.add_argument("--tenant-skew", type=float, default=1.0)
    ap.add_argument("--tenant-schedule", default="steady",
                    choices=("steady", "diurnal", "flash"),
                    help="tenant-mix arrival shape over the churn "
                    "window (diurnal day curves / a tenant-0 flash "
                    "crowd mid-window)")
    args = ap.parse_args(argv)
    if args.overload_at and (
        args.overload_at + args.overload_seconds >= args.seconds
    ):
        ap.error("the overload phase must end inside the churn window "
                 "(the recovery half of shed-and-recover needs runway)")
    if args.rate <= 0:
        ap.error("--rate must be > 0 (the soak is a paced-churn shape; "
                 "sched_bench's rate=0 branch reports different fields)")
    if args.kill_tier_at and args.tier_replicas < 2:
        ap.error("--kill-tier-at requires --tier-replicas >= 2 (the "
                 "bench and idle population need a survivor)")
    if args.kill_tier_at and args.kill_tier_at >= args.seconds:
        ap.error("--kill-tier-at must fall inside the churn window")
    if args.kill_coordinator_at and args.kill_coordinator_at >= args.seconds:
        ap.error("--kill-coordinator-at must fall inside the churn window")
    if args.out is None:
        args.out = ("artifacts/soak_faultline.json" if args.fault_plan
                    else "artifacts/soak_secured_tier.json")
    return args


async def _kill_and_resume(
    args, tier_procs, canary_keys, canary_muxes, canary_delivered,
    canary_written, survivor_channel, seed,
) -> dict:
    """The mid-soak failover drill: SIGKILL the tier replica the
    canaries ride, resume every canary on the survivor from its own
    last-delivered revision (per-watch — the stream-level max would skip
    events for a lagged watch; test_tier_replicas.py contract), force a
    compaction right behind the kill (failover and history-trim
    interacting is the case single-fault drills never see), and measure
    recovery: wall time from SIGKILL until the event ledger is caught
    up again.

    Never fatal: an hour of soak evidence must not be destroyed by the
    drill itself, so a failed resume is REPORTED (``caught_up: false``
    plus ``error``, which fails the run's gate) instead of raised."""
    from k8s1m_tpu.tools.watch_scale import MuxWatch

    victim_proc = tier_procs[-1]
    victim = canary_muxes[0]
    t_kill = time.monotonic()
    victim_proc.kill()                      # SIGKILL, not terminate
    # Let the broken stream drain: events the victim already handed to
    # the client library still land in `delivered`/`watch_rev`; reading
    # the resume points too early would replay them as duplicates.
    await asyncio.sleep(0.5)
    resume = MuxWatch(survivor_channel)
    starts = [
        victim.watch_rev.get(1 + i, victim.create_rev) + 1
        for i in range(len(canary_keys))
    ]
    try:
        await resume.create(canary_keys, 1, start_revision=starts)
        # Generous create window: the survivor shares one event loop
        # with its full watch fan-out, and on a small host every other
        # soak process competes for the same cores.
        await resume.wait_created(
            len(canary_keys), timeout=max(120.0, 4 * args.canary_timeout)
        )
    # Reported in the drill's structured result (recovery_s: None).
    except Exception as e:  # graftlint: disable=broad-except
        print(f"# tier kill drill: resume FAILED: {e!r}", file=sys.stderr)
        canary_muxes.append(resume)      # count whatever it delivers
        return {
            "at_s": round(args.kill_tier_at, 1),
            "recovery_s": None,
            "caught_up": False,
            "error": repr(e),
        }
    canary_muxes.append(resume)
    try:
        st = await seed.status()
        if st.header.revision - 2000 > 1:
            await seed.compact(st.header.revision - 2000)
    # Best-effort compaction pressure; the canary gate is the check.
    except Exception:  # graftlint: disable=broad-except
        pass
    deadline = time.monotonic() + args.canary_timeout
    while (
        canary_delivered() < canary_written()
        and time.monotonic() < deadline
    ):
        await asyncio.sleep(0.05)
    recovery_s = time.monotonic() - t_kill
    caught_up = canary_delivered() >= canary_written()
    print(
        f"# tier kill drill: recovery_s={recovery_s:.2f} "
        f"caught_up={caught_up}", file=sys.stderr,
    )
    return {
        "at_s": round(args.kill_tier_at, 1),
        "recovery_s": round(recovery_s, 3),
        "caught_up": caught_up,
    }


async def _wait_port(port: int, proc, deadline_s: float) -> None:
    deadline = time.monotonic() + deadline_s
    while True:
        if proc.poll() is not None:
            raise RuntimeError(f"subprocess exited rc={proc.returncode}")
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=0.2):
                return
        except OSError:
            if time.monotonic() > deadline:
                raise TimeoutError(f"port {port} never bound")
            # Deadline-bounded readiness poll, not an op retry.
            await asyncio.sleep(0.1)  # graftlint: disable=retry-through-policy


async def amain(args) -> dict:
    from k8s1m_tpu.cluster.certs import provision
    from k8s1m_tpu.cluster.harness import _free_port
    from k8s1m_tpu.store.etcd_client import EtcdClient, secure_channel_for
    from k8s1m_tpu.tools.watch_scale import MuxWatch

    certs_dir = tempfile.mkdtemp(prefix="soak-certs-")
    certs = provision(certs_dir)
    token = "soak-bearer-token"
    env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}

    plan = None
    fault_env = env
    if args.fault_plan:
        from k8s1m_tpu.faultline import FaultPlan

        if args.fault_plan == "default":
            plan = FaultPlan.from_json(DEFAULT_FAULT_PLAN)
        else:
            plan = FaultPlan.from_arg(args.fault_plan)
        # The hooks live in the CLIENTS (bench coordinator + RemoteStore,
        # tier upstream pumps); the soak's own ledger writes stay clean.
        fault_env = {**env, "K8S1M_FAULT_PLAN": plan.to_json()}

    store_port = _free_port()
    wal_dir = tempfile.mkdtemp(prefix="soak-wal-")
    store_proc = subprocess.Popen(
        [sys.executable, "-m", "k8s1m_tpu.store.server_main",
         "--port", str(store_port), "--host", "127.0.0.1",
         "--metrics-port", "0", "--wal-dir", wal_dir,
         "--wal-default", args.wal_mode, "--wire", "native"],
        env=env,
    )
    procs = [store_proc]
    try:
        await _wait_port(store_port, store_proc, 60)
        # Seed the idle/canary objects BEFORE the tier primes.
        seed = EtcdClient(f"127.0.0.1:{store_port}")
        wave = []
        for i in range(args.idle):
            wave.append((IDLE_PREFIX + b"cm-%06d" % i, b'{"data":{}}'))
            if len(wave) == 4096:
                await seed.put_batch(wave)
                wave.clear()
        for i in range(args.canaries):
            wave.append((CANARY_PREFIX + b"canary-%03d" % i, b"0"))
        if wave:
            await seed.put_batch(wave)

        tier_ports = [_free_port() for _ in range(args.tier_replicas)]
        tier_procs = []
        for port in tier_ports:
            p = subprocess.Popen(
                [sys.executable, "-m", "k8s1m_tpu.store.watch_cache",
                 "--upstream", f"127.0.0.1:{store_port}",
                 "--host", "127.0.0.1", "--port", str(port),
                 "--prefix", "/registry/",
                 "--tls-cert", certs.cert_pem, "--tls-key", certs.key_pem,
                 "--auth-token", token],
                env=fault_env,
            )
            tier_procs.append(p)
            procs.append(p)
        for port, p in zip(tier_ports, tier_procs):
            await _wait_port(port, p, 120 + args.idle / 1000)
        tier_proc = tier_procs[0]       # survivor: RSS trend + bench target
        tier_port = tier_ports[0]
        # Canaries ride the LAST replica — the kill drill's victim.
        canary_port = tier_ports[-1]

        # Idle + canary populations through the SECURED tier.
        channel = secure_channel_for(
            f"127.0.0.1:{tier_port}", certs.ca_pem, token,
            options=[("grpc.max_receive_message_length", 64 << 20)],
        )
        muxes = [MuxWatch(channel) for _ in range(4)]
        per = (args.idle + len(muxes) - 1) // len(muxes)
        next_id = 1
        counts = []
        for m in muxes:
            lo = next_id - 1
            keys = [IDLE_PREFIX + b"cm-%06d" % (lo + i)
                    for i in range(max(0, min(per, args.idle - lo)))]
            await m.create(keys, next_id)
            counts.append(len(keys))
            next_id += len(keys)
        for m, n in zip(muxes, counts):
            await m.wait_created(n, timeout=120 + args.idle / 500)
        canary_channel = secure_channel_for(
            f"127.0.0.1:{canary_port}", certs.ca_pem, token,
            options=[("grpc.max_receive_message_length", 64 << 20)],
        )
        canary = MuxWatch(canary_channel)
        canary_keys = [CANARY_PREFIX + b"canary-%03d" % i
                       for i in range(args.canaries)]
        await canary.create(canary_keys, 1)
        await canary.wait_created(args.canaries, timeout=60)
        canary_muxes = [canary]         # victim stream [+ survivor resume]

        def canary_delivered() -> int:
            return sum(m.delivered for m in canary_muxes)

        # Churn through the tier: the full coordinator loop as a
        # subprocess (create -> watch -> schedule -> CAS bind -> delete)
        # at the offered rate for the whole window.
        pods = max(1000, int(args.rate * args.seconds))
        bench_cmd = [
            sys.executable, "-m", "k8s1m_tpu.tools.sched_bench",
            "--nodes", str(args.nodes), "--pods", str(pods),
            "--rate", str(args.rate), "--score-pct", "5",
            "--backend", "xla", "--churn",
            "--target", f"127.0.0.1:{tier_port}",
            "--ca-pem", certs.ca_pem, "--token", token,
        ]
        if args.overload_at:
            # The overload phase offers extra pods; size --pods so the
            # producer does not run dry before the window closes.
            pods += int(
                args.rate * (args.overload_factor - 1) * args.overload_seconds
            )
            bench_cmd[bench_cmd.index("--pods") + 1] = str(pods)
            bench_cmd += [
                "--overload-at", str(args.overload_at),
                "--overload-seconds", str(args.overload_seconds),
                "--overload-factor", str(args.overload_factor),
            ]
        if args.tenants:
            bench_cmd += [
                "--tenants", str(args.tenants),
                "--tenant-skew", str(args.tenant_skew),
                "--tenant-schedule", args.tenant_schedule,
            ]
        bench_proc = subprocess.Popen(
            bench_cmd, env=fault_env, stdout=subprocess.PIPE, text=True,
        )
        procs.append(bench_proc)

        # RSS sampler over the churn window, with periodic MVCC
        # compaction (keep a revision margin so the tier's watch
        # resume window stays usable).  Every sample tick also writes
        # one ledger value per canary key: `canary_written` vs
        # `canary_delivered()` is the exactly-once watch-event ledger
        # the `event_loss == 0` gate settles on.
        series = []
        canary_written = 0
        tick = 0
        kill_info = None
        failover_proc = None
        t0 = time.monotonic()
        next_compact = t0 + args.compact_every
        while bench_proc.poll() is None:
            if time.monotonic() >= next_compact:
                next_compact = time.monotonic() + args.compact_every
                try:
                    st = await seed.status()
                    target = st.header.revision - 5000
                    if target > 1:
                        await seed.compact(target)
                except Exception:  # graftlint: disable=broad-except
                    pass    # compaction is best-effort in the soak
            tick += 1
            try:
                for k in canary_keys:
                    await seed.put(k, b"tick-%06d" % tick)
                    # Counted per put, not per tick: a loop that dies
                    # after 3 of N puts DID write 3 events — counting 0
                    # would turn them into phantom negative event_loss.
                    canary_written += 1
            except Exception:  # graftlint: disable=broad-except
                pass        # ledger writes pause while the store restarts
            if (
                args.kill_coordinator_at
                and failover_proc is None
                and time.monotonic() - t0 >= args.kill_coordinator_at
            ):
                # The coordinator-failover phase rides its own process
                # (tick-driven, deterministic, own in-process store) so
                # the soak's wire ledger stays untouched while the
                # scheduler-kill scenarios run to their own gates.
                failover_proc = subprocess.Popen(
                    [sys.executable, "-m",
                     "k8s1m_tpu.tools.failover_drill", "--smoke"],
                    env=env, stdout=subprocess.PIPE, text=True,
                )
                procs.append(failover_proc)
            if (
                args.kill_tier_at
                and kill_info is None
                and time.monotonic() - t0 >= args.kill_tier_at
            ):
                kill_info = await _kill_and_resume(
                    args, tier_procs, canary_keys, canary_muxes,
                    canary_delivered, lambda: canary_written,
                    channel, seed,
                )
            series.append({
                "t_s": round(time.monotonic() - t0, 1),
                "tier_rss_mb": round(_rss_mb(tier_proc.pid), 1),
                "store_rss_mb": round(_rss_mb(store_proc.pid), 1),
                "idle_canceled": sum(m.canceled for m in muxes),
            })
            # Sleep in short slices so a finished bench is noticed
            # within ~0.5s, not a full sample interval late.
            slept = 0.0
            while slept < args.sample_every and bench_proc.poll() is None:
                await asyncio.sleep(0.5)
                slept += 0.5
            # Overload backlog legitimately drains past the window; give
            # the bench the extra runway before calling it hung.
            grace = 900 + (
                args.overload_factor * args.overload_seconds
                if args.overload_at else 0
            )
            if time.monotonic() - t0 > args.seconds + grace:
                bench_proc.kill()
                raise TimeoutError("churn bench overran the window")
        bench_out = bench_proc.stdout.read()
        if bench_proc.returncode != 0 or not bench_out.strip():
            raise RuntimeError(
                f"churn bench rc={bench_proc.returncode}, "
                f"stdout={bench_out!r}"
            )
        bench_line = json.loads(bench_out.strip().splitlines()[-1])
        soak_s = time.monotonic() - t0

        failover_info = None
        if failover_proc is not None:
            # Bound the wait WELL below the smoke test's 420s budget so
            # a slow/wedged drill reports as a failed gate instead of
            # timing out the whole soak (which would destroy both runs'
            # evidence); the drill itself is ~1-2 min at --smoke scale.
            try:
                fo_out, _ = failover_proc.communicate(timeout=240)
                fo = json.loads(fo_out.strip().splitlines()[-1])
                failover_info = {
                    "at_s": round(args.kill_coordinator_at, 1),
                    "passed": bool(fo.get("passed")),
                    "recovery_warm_s": fo["evidence"]["recovery_warm_s"],
                    "recovery_cold_s": fo["evidence"]["recovery_cold_s"],
                    "fencing_rejected": fo["evidence"]["split_brain"][
                        "fencing_rejected"],
                    "lost": max(
                        fo["evidence"][k]["lost"]
                        for k in ("mid_wave_kill_warm", "mid_wave_kill_cold",
                                  "split_brain")
                    ),
                }
            # A failed/hung drill must FAIL the gate, not destroy the
            # soak's own evidence.
            except Exception as e:  # graftlint: disable=broad-except
                failover_proc.kill()
                failover_info = {
                    "at_s": round(args.kill_coordinator_at, 1),
                    "passed": False,
                    "error": repr(e),
                }

        # Liveness probe: every canary stream must deliver a fresh write.
        base = canary_delivered()
        for i, k in enumerate(canary_keys):
            await seed.put(k, b"alive-%d" % i)
        canary_written += args.canaries
        deadline = time.monotonic() + args.canary_timeout
        while (
            canary_delivered() - base < args.canaries
            and time.monotonic() < deadline
        ):
            await asyncio.sleep(0.1)
        stalls = args.canaries - (canary_delivered() - base)

        # Event-loss ledger: every canary write issued after watch
        # registration must have been delivered exactly once, counted
        # across the victim stream and any failover resume.  Positive =
        # lost events; negative = duplicates (a resume that replayed).
        deadline = time.monotonic() + args.canary_timeout
        while (
            canary_delivered() < canary_written
            and time.monotonic() < deadline
        ):
            await asyncio.sleep(0.1)
        event_loss = canary_written - canary_delivered()

        canceled = (
            sum(m.canceled for m in muxes)
            + sum(m.canceled for m in canary_muxes)
        )

        # RSS trend: mean of the first vs last third of the POST-WARMUP
        # series (the ramp legitimately fills caches/arenas; a leak
        # keeps growing after it).
        # Short runs can't honor the full warmup; scale it down rather
        # than silently gating on the startup ramp (which would fail a
        # leak-free run).
        horizon = series[-1]["t_s"] if series else 0.0
        warmup = min(args.warmup, horizon / 3)

        def trend(key):
            vals = [
                s[key] for s in series
                if s[key] > 0 and s["t_s"] >= warmup
            ]
            if len(vals) < 6:
                return 0.0, 0.0
            third = len(vals) // 3
            first = sum(vals[:third]) / third
            last = sum(vals[-third:]) / third
            return first, last

        tier_first, tier_last = trend("tier_rss_mb")
        store_first, store_last = trend("store_rss_mb")
        growth = {
            "tier_pct": round(100 * (tier_last - tier_first)
                              / max(tier_first, 1e-9), 2),
            "store_pct": round(100 * (store_last - store_first)
                               / max(store_first, 1e-9), 2),
        }
        rss_flat = (
            growth["tier_pct"] <= args.max_growth_pct
            and growth["store_pct"] <= args.max_growth_pct
        )

        for m in muxes:
            await m.close()
        for m in canary_muxes:
            await m.close()
        await canary_channel.close()
        await channel.close()
        await seed.close()

        detail = bench_line["detail"]
        result = {
            "metric": ("soak_faultline_seconds" if plan
                       else "soak_secured_tier_seconds"),
            "value": round(soak_s, 1),
            "unit": "s",
            "vs_baseline": None,
            "passed": bool(
                rss_flat and canceled == 0 and stalls == 0
                and event_loss == 0
                and (kill_info is None or kill_info["caught_up"])
                and (failover_info is None or failover_info["passed"])
            ),
            "rss_flat": rss_flat,
            "rss_growth": growth,
            "canceled": canceled,
            "stalls": stalls,
            "event_loss": event_loss,
            "canary_writes": canary_written,
            "idle_watches": args.idle,
            "wal_mode": args.wal_mode,
            "tier_replicas": args.tier_replicas,
            "tier_kill": kill_info,
            "coordinator_failover": failover_info,
            "fault_plan": (
                {"seed": plan.seed, "specs": [f.to_obj() for f in plan.faults]}
                if plan else None
            ),
            # The churn bench's injected-fault + retry evidence (it is
            # the process the plan's client-side hooks fire in).
            "resilience": {
                k: detail[k]
                for k in ("faults_injected", "retry_attempts",
                          "give_ups", "recovery")
                if k in detail
            } or None,
            "overload": (
                {"at_s": args.overload_at,
                 "seconds": args.overload_seconds,
                 "factor": args.overload_factor}
                if args.overload_at else None
            ),
            "churn": {
                "rate": args.rate,
                "bound": detail["bound"],
                "deleted": detail["deleted"],
                "binds_per_sec": detail["binds_per_sec"],
                "p50_ms": detail["p50_ms"],
            },
            "samples": len(series),
        }
        if args.out:
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                json.dump({**result, "rss_series": series}, f, indent=1)
        return result
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        import shutil

        for d in (certs_dir, wal_dir):
            shutil.rmtree(d, ignore_errors=True)


def main(argv=None):
    args = parse_args(argv)
    print(json.dumps(asyncio.run(amain(args))))


if __name__ == "__main__":
    main()
