"""Secured-tier churn soak: the week-long-watch scenario at bench scale.

The reference's apiserver findings are about what survives TIME: 18M
kubelet watches held for days over a control plane sustaining continuous
create/bind/delete churn (reference README.adoc:410-416, 721-730).  This
driver runs that shape end to end for ``--seconds`` (default 600):

  native store server  <-TLS+bearer-  watch-cache tier  <-TLS+bearer-
  { an idle watch population (mux streams, never written),
    a hot canary watch set,
    sched_bench --churn --rate  (create -> schedule -> CAS bind ->
    delete, the full coordinator loop) }

while sampling the tier's and the store server's RSS every
``--sample-every`` seconds.  Pass criteria, printed as one JSON line and
written (with the RSS series) to ``--out``:

- ``rss_flat``: neither process's RSS trend grows more than
  ``--max-growth-pct`` between the first and last thirds of the window
  (no per-watch or per-event leak);
- ``canceled == 0``: the idle population survives the whole soak (the
  round-4 flow-control hardening exists precisely so long-lived streams
  never stall out);
- ``stalls == 0``: after the churn window every canary watch still
  delivers a fresh write within ``--canary-timeout`` seconds — the
  streams are live, not just uncanceled.

    python -m k8s1m_tpu.tools.soak --seconds 600 --idle 5000 --rate 300
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

from grpc import aio

IDLE_PREFIX = b"/registry/configmaps/soak/"
CANARY_PREFIX = b"/registry/leases/soak/"


def _rss_mb(pid: int) -> float:
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="secured-tier churn soak")
    ap.add_argument("--seconds", type=float, default=600.0,
                    help="churn window length (the soak proper)")
    ap.add_argument("--idle", type=int, default=5000,
                    help="idle watch population held through the tier")
    ap.add_argument("--canaries", type=int, default=32,
                    help="hot watches probed for liveness at the end")
    ap.add_argument("--rate", type=int, default=300,
                    help="offered churn load (pods/s) for sched_bench")
    ap.add_argument("--nodes", type=int, default=16384)
    ap.add_argument("--sample-every", type=float, default=5.0)
    ap.add_argument("--compact-every", type=float, default=60.0,
                    help="periodic MVCC compaction interval (the "
                    "apiserver's --etcd-compaction-interval role; "
                    "without it sustained churn grows store history "
                    "unboundedly by design)")
    ap.add_argument("--max-growth-pct", type=float, default=10.0,
                    help="max allowed RSS growth, first vs last third "
                    "of the post-warmup series")
    ap.add_argument("--warmup", type=float, default=180.0,
                    help="seconds excluded from the RSS-flatness gate: "
                    "watch history windows, MVCC steady-state population "
                    "and allocator arenas legitimately fill during "
                    "ramp-up; a LEAK keeps growing after it")
    ap.add_argument("--canary-timeout", type=float, default=30.0)
    ap.add_argument("--out", default="artifacts/soak_secured_tier.json")
    args = ap.parse_args(argv)
    if args.rate <= 0:
        ap.error("--rate must be > 0 (the soak is a paced-churn shape; "
                 "sched_bench's rate=0 branch reports different fields)")
    return args


async def _wait_port(port: int, proc, deadline_s: float) -> None:
    deadline = time.monotonic() + deadline_s
    while True:
        if proc.poll() is not None:
            raise RuntimeError(f"subprocess exited rc={proc.returncode}")
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=0.2):
                return
        except OSError:
            if time.monotonic() > deadline:
                raise TimeoutError(f"port {port} never bound")
            await asyncio.sleep(0.1)


async def amain(args) -> dict:
    from k8s1m_tpu.cluster.certs import provision
    from k8s1m_tpu.cluster.harness import _free_port
    from k8s1m_tpu.store.etcd_client import EtcdClient, secure_channel_for
    from k8s1m_tpu.tools.watch_scale import MuxWatch

    certs_dir = tempfile.mkdtemp(prefix="soak-certs-")
    certs = provision(certs_dir)
    token = "soak-bearer-token"
    env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}

    store_port = _free_port()
    wal_dir = tempfile.mkdtemp(prefix="soak-wal-")
    store_proc = subprocess.Popen(
        [sys.executable, "-m", "k8s1m_tpu.store.server_main",
         "--port", str(store_port), "--host", "127.0.0.1",
         "--metrics-port", "0", "--wal-dir", wal_dir, "--wire", "native"],
        env=env,
    )
    tier_port = _free_port()
    tier_proc = None
    procs = [store_proc]
    try:
        await _wait_port(store_port, store_proc, 60)
        # Seed the idle/canary objects BEFORE the tier primes.
        seed = EtcdClient(f"127.0.0.1:{store_port}")
        wave = []
        for i in range(args.idle):
            wave.append((IDLE_PREFIX + b"cm-%06d" % i, b'{"data":{}}'))
            if len(wave) == 4096:
                await seed.put_batch(wave)
                wave.clear()
        for i in range(args.canaries):
            wave.append((CANARY_PREFIX + b"canary-%03d" % i, b"0"))
        if wave:
            await seed.put_batch(wave)

        tier_proc = subprocess.Popen(
            [sys.executable, "-m", "k8s1m_tpu.store.watch_cache",
             "--upstream", f"127.0.0.1:{store_port}",
             "--host", "127.0.0.1", "--port", str(tier_port),
             "--prefix", "/registry/",
             "--tls-cert", certs.cert_pem, "--tls-key", certs.key_pem,
             "--auth-token", token],
            env=env,
        )
        procs.append(tier_proc)
        await _wait_port(tier_port, tier_proc, 120 + args.idle / 1000)

        # Idle + canary populations through the SECURED tier.
        channel = secure_channel_for(
            f"127.0.0.1:{tier_port}", certs.ca_pem, token,
            options=[("grpc.max_receive_message_length", 64 << 20)],
        )
        muxes = [MuxWatch(channel) for _ in range(4)]
        per = (args.idle + len(muxes) - 1) // len(muxes)
        next_id = 1
        counts = []
        for m in muxes:
            lo = next_id - 1
            keys = [IDLE_PREFIX + b"cm-%06d" % (lo + i)
                    for i in range(max(0, min(per, args.idle - lo)))]
            await m.create(keys, next_id)
            counts.append(len(keys))
            next_id += len(keys)
        for m, n in zip(muxes, counts):
            await m.wait_created(n, timeout=120 + args.idle / 500)
        canary = MuxWatch(channel)
        canary_keys = [CANARY_PREFIX + b"canary-%03d" % i
                       for i in range(args.canaries)]
        await canary.create(canary_keys, next_id)
        await canary.wait_created(args.canaries, timeout=60)

        # Churn through the tier: the full coordinator loop as a
        # subprocess (create -> watch -> schedule -> CAS bind -> delete)
        # at the offered rate for the whole window.
        pods = max(1000, int(args.rate * args.seconds))
        bench_proc = subprocess.Popen(
            [sys.executable, "-m", "k8s1m_tpu.tools.sched_bench",
             "--nodes", str(args.nodes), "--pods", str(pods),
             "--rate", str(args.rate), "--score-pct", "5",
             "--backend", "xla", "--churn",
             "--target", f"127.0.0.1:{tier_port}",
             "--ca-pem", certs.ca_pem, "--token", token],
            env=env, stdout=subprocess.PIPE, text=True,
        )
        procs.append(bench_proc)

        # RSS sampler over the churn window, with periodic MVCC
        # compaction (keep a revision margin so the tier's watch
        # resume window stays usable).
        series = []
        t0 = time.monotonic()
        next_compact = t0 + args.compact_every
        while bench_proc.poll() is None:
            if time.monotonic() >= next_compact:
                next_compact = time.monotonic() + args.compact_every
                try:
                    st = await seed.status()
                    target = st.header.revision - 5000
                    if target > 1:
                        await seed.compact(target)
                except Exception:
                    pass    # compaction is best-effort in the soak
            series.append({
                "t_s": round(time.monotonic() - t0, 1),
                "tier_rss_mb": round(_rss_mb(tier_proc.pid), 1),
                "store_rss_mb": round(_rss_mb(store_proc.pid), 1),
                "idle_canceled": sum(m.canceled for m in muxes),
            })
            # Sleep in short slices so a finished bench is noticed
            # within ~0.5s, not a full sample interval late.
            slept = 0.0
            while slept < args.sample_every and bench_proc.poll() is None:
                await asyncio.sleep(0.5)
                slept += 0.5
            if time.monotonic() - t0 > args.seconds + 900:
                bench_proc.kill()
                raise TimeoutError("churn bench overran the window")
        bench_out = bench_proc.stdout.read()
        if bench_proc.returncode != 0 or not bench_out.strip():
            raise RuntimeError(
                f"churn bench rc={bench_proc.returncode}, "
                f"stdout={bench_out!r}"
            )
        bench_line = json.loads(bench_out.strip().splitlines()[-1])
        soak_s = time.monotonic() - t0

        # Liveness probe: every canary stream must deliver a fresh write.
        base = canary.delivered
        for i, k in enumerate(canary_keys):
            await seed.put(k, b"alive-%d" % i)
        deadline = time.monotonic() + args.canary_timeout
        while (
            canary.delivered - base < args.canaries
            and time.monotonic() < deadline
        ):
            await asyncio.sleep(0.1)
        stalls = args.canaries - (canary.delivered - base)

        canceled = sum(m.canceled for m in muxes) + canary.canceled

        # RSS trend: mean of the first vs last third of the POST-WARMUP
        # series (the ramp legitimately fills caches/arenas; a leak
        # keeps growing after it).
        # Short runs can't honor the full warmup; scale it down rather
        # than silently gating on the startup ramp (which would fail a
        # leak-free run).
        horizon = series[-1]["t_s"] if series else 0.0
        warmup = min(args.warmup, horizon / 3)

        def trend(key):
            vals = [
                s[key] for s in series
                if s[key] > 0 and s["t_s"] >= warmup
            ]
            if len(vals) < 6:
                return 0.0, 0.0
            third = len(vals) // 3
            first = sum(vals[:third]) / third
            last = sum(vals[-third:]) / third
            return first, last

        tier_first, tier_last = trend("tier_rss_mb")
        store_first, store_last = trend("store_rss_mb")
        growth = {
            "tier_pct": round(100 * (tier_last - tier_first)
                              / max(tier_first, 1e-9), 2),
            "store_pct": round(100 * (store_last - store_first)
                               / max(store_first, 1e-9), 2),
        }
        rss_flat = (
            growth["tier_pct"] <= args.max_growth_pct
            and growth["store_pct"] <= args.max_growth_pct
        )

        for m in muxes:
            await m.close()
        await canary.close()
        await channel.close()
        await seed.close()

        result = {
            "metric": "soak_secured_tier_seconds",
            "value": round(soak_s, 1),
            "unit": "s",
            "vs_baseline": None,
            "passed": bool(rss_flat and canceled == 0 and stalls == 0),
            "rss_flat": rss_flat,
            "rss_growth": growth,
            "canceled": canceled,
            "stalls": stalls,
            "idle_watches": args.idle,
            "churn": {
                "rate": args.rate,
                "bound": bench_line["detail"]["bound"],
                "deleted": bench_line["detail"]["deleted"],
                "binds_per_sec": bench_line["detail"]["binds_per_sec"],
                "p50_ms": bench_line["detail"]["p50_ms"],
            },
            "samples": len(series),
        }
        if args.out:
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                json.dump({**result, "rss_series": series}, f, indent=1)
        return result
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        import shutil

        for d in (certs_dir, wal_dir):
            shutil.rmtree(d, ignore_errors=True)


def main(argv=None):
    args = parse_args(argv)
    print(json.dumps(asyncio.run(amain(args))))


if __name__ == "__main__":
    main()
