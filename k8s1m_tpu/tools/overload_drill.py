"""Deterministic overload drill: shed, degrade, break, recover — by seed.

The loadshed acceptance evidence (ISSUE 2), as one reproducible run:
an in-process store + coordinator under a 5x sustained submit burst,
tick-driven on a **virtual clock** (one tick = one scheduling cycle;
every controller and breaker decision is counted in cycles, so the
whole trajectory is a pure function of the submit schedule and the
seed — no wall-clock anywhere in the gates).  ``--tick-s`` > 0 adds a
real sleep per tick for wall-clock observation runs; the hour-scale
wall-clock shape lives in ``tools/soak.py --overload-at`` (which drives
the same machinery through sched_bench's paced producer).

Phases:

1. **healthy** — submit at 1x capacity (one batch per tick); baseline
   binds/tick.
2. **overload** — submit at ``--factor`` x capacity: the controller
   must walk HEALTHY -> DEGRADED -> SHEDDING, admission must hold the
   queue under ``queue_cap`` while shedding the lowest-priority pods
   first, and binds/tick must stay >= 50% of the healthy baseline.
3. **recovery** — submit at 0.5x capacity: the controller must walk
   back to HEALTHY (hysteresis) within ``--recover-ticks``, and every
   admitted pod must be bound in the store — the zero-loss ledger.
4. **breaker** (separate fresh store) — injected ``stall`` faults on
   cycle dispatch open the circuit breaker; open-state batches bind
   through the host-side oracle (asserted **byte-identical** to an
   independent replay of the oracle), and the half-open probe closes
   the breaker again.

    python -m k8s1m_tpu.tools.overload_drill --smoke \
        --out artifacts/overload_drill.json

``--smoke`` is the tier-1 shape (seconds on CPU); the default shape is
the same drill at bench scale.  Pass criteria print as one JSON line
(``passed``) and the full evidence lands in ``--out``.
"""

from __future__ import annotations

import argparse
import json
import os
import time

IDLE_DRAIN_TICKS = 2000


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="deterministic overload drill")
    ap.add_argument("--nodes", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument("--score-pct", type=int, default=50)
    ap.add_argument("--degraded-score-pct", type=int, default=13)
    ap.add_argument("--factor", type=int, default=5,
                    help="overload submit rate, in multiples of one "
                    "batch per tick")
    ap.add_argument("--healthy-ticks", type=int, default=8)
    ap.add_argument("--overload-ticks", type=int, default=10)
    ap.add_argument("--recover-ticks", type=int, default=40,
                    help="budget (ticks) for the walk back to HEALTHY")
    ap.add_argument("--priorities", type=int, default=4,
                    help="pods cycle through spec.priority 0..P-1")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--tick-s", type=float, default=0.0,
                    help="wall sleep per tick (0 = pure virtual clock)")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 shape: tiny cluster, same gates")
    ap.add_argument("--out", default=None,
                    help="evidence JSON path (e.g. "
                    "artifacts/overload_drill.json)")
    args = ap.parse_args(argv)
    if args.smoke:
        args.nodes, args.batch, args.chunk = 64, 32, 16
        args.score_pct, args.degraded_score_pct = 50, 25
        args.healthy_ticks, args.overload_ticks = 6, 6
        args.recover_ticks = 30
    return args


def _mk_cluster(args, *, loadshed=None, breaker=None, tenancy=None,
                ns="default"):
    """Store + coordinator of the drill shape (caller owns both)."""
    from k8s1m_tpu.config import PodSpec, TableSpec
    from k8s1m_tpu.control.coordinator import Coordinator
    from k8s1m_tpu.control.objects import encode_node, node_key
    from k8s1m_tpu.plugins.registry import Profile
    from k8s1m_tpu.snapshot.node_table import NodeInfo
    from k8s1m_tpu.store.native import MemStore

    store = MemStore()
    for i in range(args.nodes):
        store.put(node_key(f"n{i:05d}"), encode_node(NodeInfo(
            name=f"n{i:05d}", cpu_milli=64_000, mem_kib=64 << 20, pods=256,
        )))
    coord = Coordinator(
        store,
        TableSpec(max_nodes=args.nodes, max_zones=16, max_regions=8),
        PodSpec(batch=args.batch),
        Profile(topology_spread=0, interpod_affinity=0),
        chunk=args.chunk, k=4, with_constraints=False, seed=args.seed,
        score_pct=args.score_pct, loadshed=loadshed, breaker=breaker,
        tenancy=tenancy,
    )
    coord.bootstrap()
    return store, coord


def _submit(store, coord, start: int, n: int, priorities: int, accept, reject):
    """Offer ``n`` pods through the admission path (webhook shape:
    submit_external + the apiserver's store write on accept).  Priority
    cycles P-1..0 so every level is offered equally, descending within
    each round: when the hard queue cap cuts a round off mid-way, the
    suffix it rejects is the low-priority end — which is what makes the
    per-level acceptance counts exactly monotone in priority (the gate
    below) instead of monotone-up-to-round-truncation."""
    import json as _json

    from k8s1m_tpu.control.objects import encode_pod, pod_key
    from k8s1m_tpu.loadshed import Overloaded
    from k8s1m_tpu.snapshot.pod_encoding import PodInfo

    for i in range(start, start + n):
        prio = priorities - 1 - (i % priorities)
        pod = PodInfo(f"p{i:07d}", cpu_milli=10, mem_kib=1 << 10)
        obj = _json.loads(encode_pod(pod))
        obj["spec"]["priority"] = prio
        try:
            coord.submit_external(obj)
        except Overloaded:
            reject[prio] += 1
            continue
        accept[prio] += 1
        # The apiserver persists the admitted pod (canonical bytes: the
        # admission-only priority field stays out of the stored object
        # so the native fast lane and the splice path apply).
        store.put(pod_key("default", pod.name), encode_pod(pod))
    return start + n


def run_overload(args) -> dict:
    """Phases 1-3: shed + degrade + recover.  Returns the evidence dict."""
    from k8s1m_tpu.loadshed import (
        HEALTHY,
        SHEDDING,
        STATE_NAMES,
        HealthController,
        LoadshedConfig,
    )

    b = args.batch
    cfg = LoadshedConfig(
        queue_degraded=2 * b, queue_shed=4 * b, queue_cap=6 * b,
        queue_recover=b // 2, recover_cycles=3,
        degraded_score_pct=args.degraded_score_pct,
    )
    ls = HealthController(cfg, name="overload_drill")
    store, coord = _mk_cluster(args, loadshed=ls)
    accept = [0] * args.priorities
    reject = [0] * args.priorities
    o_accept = [0] * args.priorities
    o_reject = [0] * args.priorities
    seq = 0
    max_load = 0
    states_seen = set()
    binds = {"healthy": [], "overload": [], "recovery": []}

    def tick(phase: str, submit_n: int) -> None:
        nonlocal seq, max_load
        before = [accept[i] for i in range(args.priorities)], \
            [reject[i] for i in range(args.priorities)]
        seq = _submit(store, coord, seq, submit_n, args.priorities,
                      accept, reject)
        if phase == "overload":
            for i in range(args.priorities):
                o_accept[i] += accept[i] - before[0][i]
                o_reject[i] += reject[i] - before[1][i]
        binds[phase].append(coord.step())
        states_seen.add(ls.state)
        max_load = max(max_load, len(coord.queue) + len(coord._backoff))
        if args.tick_s:
            time.sleep(args.tick_s)

    try:
        for _ in range(args.healthy_ticks):
            tick("healthy", b)
        for _ in range(args.overload_ticks):
            tick("overload", args.factor * b)
        recovered_at = None
        for t in range(args.recover_ticks):
            tick("recovery", b // 2)
            if ls.state == HEALTHY and recovered_at is None:
                recovered_at = t + 1
        # Drain: every admitted pod must land (the zero-loss ledger).
        for _ in range(IDLE_DRAIN_TICKS):
            if not coord.queue and not coord._backoff and not coord._external:
                break
            binds["recovery"].append(coord.step())
            if coord.backoff_wait_s():
                time.sleep(min(coord.backoff_wait_s(), 0.05))
        coord.flush()

        admitted = sum(accept)
        bound_total = sum(sum(v) for v in binds.values())
        # Ledger settles on the store, not our counters: every admitted
        # pod's object must carry a nodeName.
        import json as _json

        from k8s1m_tpu.control.objects import pod_key

        lost = 0
        for i in range(seq):
            kv = store.get(pod_key("default", f"p{i:07d}"))
            if kv is None:
                continue          # rejected pods were never persisted
            if not _json.loads(kv.value)["spec"].get("nodeName"):
                lost += 1
    finally:
        coord.close()
        store.close()

    def per_tick(xs):
        return round(sum(xs) / max(len(xs), 1), 2)

    healthy_rate = per_tick(binds["healthy"])
    overload_rate = per_tick(binds["overload"])
    # Monotone acceptance: a lower priority never out-admits a higher
    # one during the overload phase (equal offered counts per level).
    monotone = all(
        o_accept[i] <= o_accept[i + 1] for i in range(args.priorities - 1)
    )
    return {
        "queue_cap": cfg.queue_cap,
        "max_load": max_load,
        "states_seen": sorted(STATE_NAMES[s] for s in states_seen),
        "healthy_binds_per_tick": healthy_rate,
        "overload_binds_per_tick": overload_rate,
        "throughput_ratio": round(overload_rate / max(healthy_rate, 1e-9), 3),
        "recovered_at_tick": recovered_at,
        "admitted": admitted,
        "rejected_by_priority": reject,
        "accepted_by_priority": accept,
        "overload_accepted_by_priority": o_accept,
        "overload_rejected_by_priority": o_reject,
        "bound": bound_total,
        "lost": lost,
        "monotone_acceptance": monotone,
        "passed": bool(
            max_load <= cfg.queue_cap
            and SHEDDING in states_seen
            and overload_rate >= 0.5 * healthy_rate
            and sum(o_reject) > 0
            and monotone
            and recovered_at is not None
            and lost == 0
            and bound_total == admitted
        ),
    }


def run_breaker(args) -> dict:
    """Phase 4: stall-open the breaker, bind through the oracle, prove
    the stored bytes byte-identical to an independent oracle replay,
    then close via the half-open probe."""
    import json as _json

    from k8s1m_tpu.control.coordinator import splice_node_name
    from k8s1m_tpu.control.objects import decode_node, encode_pod, pod_key
    from k8s1m_tpu.faultline import FaultPlan, FaultSpec, install_plan
    from k8s1m_tpu.loadshed import (
        CLOSED,
        OPEN,
        BreakerConfig,
        CircuitBreaker,
    )
    from k8s1m_tpu.oracle import oracle_feasible, oracle_score
    from k8s1m_tpu.snapshot.pod_encoding import PodInfo
    from k8s1m_tpu.store.native import list_prefix

    b = min(args.batch, 64)
    threshold = 2
    # cooldown 3: the two open cycles after the trip are the fallback
    # waves A and B; the third allow() is the half-open probe (wave C).
    br = CircuitBreaker(BreakerConfig(
        failure_threshold=threshold, cooldown_cycles=3, fallback_batch=b,
    ), component="overload_drill.cycle")
    plan = FaultPlan(
        [FaultSpec("coordinator.cycle", "dispatch", kind="stall",
                   every_n=1, max_fires=threshold)],
        seed=args.seed,
    )
    install_plan(plan)
    store, coord = _mk_cluster(args, breaker=br)
    opened = fallback_bound = 0
    mismatches = []
    try:
        raws = {}
        fallback_keys: list[str] = []

        def put_wave(tag: str):
            for i in range(b):
                pod = PodInfo(f"{tag}{i:04d}", cpu_milli=10, mem_kib=1 << 10)
                raw = encode_pod(pod)
                raws[pod.key] = raw
                store.put(pod_key("default", pod.name), raw)

        # Wave A trips the breaker (two stalls), then binds via oracle
        # fallback; wave B binds via fallback during cooldown; wave C is
        # the half-open probe (the stall budget is exhausted) and must
        # close the breaker on the device path.
        put_wave("a")
        for _ in range(threshold):
            coord.step()                      # stalls: breaker counts
        opened = int(br.state == OPEN)
        pre = _snapshot_usage(coord)
        n_a = coord.step()                    # fallback wave A
        fallback_keys += [f"default/a{i:04d}" for i in range(b)]
        put_wave("b")
        n_b = coord.step()                    # fallback wave B (cooldown)
        fallback_keys += [f"default/b{i:04d}" for i in range(b)]
        fallback_bound = n_a + n_b
        put_wave("c")
        n_c = 0
        for _ in range(8):
            n_c += coord.step()
            if br.state == CLOSED:
                break
        closed_again = br.state == CLOSED

        # Independent oracle replay over the SAME pre-fallback snapshot:
        # argmax oracle_score over feasible rows, earlier row wins ties,
        # usage updated pod by pod — the exact contract
        # Coordinator._fallback_schedule documents.  The stored bytes
        # must equal splice_node_name(raw, that choice).
        kvs, _ = list_prefix(store, b"/registry/minions/")
        nodes = []
        for kv in kvs:
            nd = decode_node(kv.value)
            nodes.append((coord.host.row_of(nd.name), nd))
        nodes.sort(key=lambda t: t[0])
        weights = (
            coord.profile.least_allocated, coord.profile.balanced_allocation,
            coord.profile.taint_toleration, coord.profile.node_affinity,
        )
        usage = pre
        for key in fallback_keys:
            ns, name = key.split("/", 1)
            pod = PodInfo(name, cpu_milli=10, mem_kib=1 << 10)
            best_row, best_score, best = -1, -1, None
            for row, nd in nodes:
                req = usage[row]
                if not oracle_feasible(nd, pod, req):
                    continue
                s = oracle_score(
                    nd, pod, req,
                    taint_slots=coord.table_spec.taint_slots,
                    weights=weights,
                )
                if s > best_score:
                    best_row, best_score, best = row, s, nd
            if best is None:
                mismatches.append((key, "oracle found no node"))
                continue
            usage[best_row] = (
                usage[best_row][0] + pod.cpu_milli,
                usage[best_row][1] + pod.mem_kib,
                usage[best_row][2] + 1,
            )
            want = splice_node_name(raws[key], best.name)
            got = store.get(pod_key(ns, name))
            if got is None or got.value != want:
                mismatches.append((key, best.name))
    finally:
        install_plan(None)
        coord.close()
        store.close()
    return {
        "stall_plan": _json.loads(plan.to_json()),
        "opened": bool(opened),
        "fallback_binds": fallback_bound,
        "byte_identical": not mismatches,
        "mismatches": mismatches[:5],
        "probe_binds": n_c,
        "closed_again": bool(closed_again),
        "passed": bool(
            opened and fallback_bound == 2 * b and not mismatches
            and closed_again and n_c >= b
        ),
    }


def run_tenant_asym(args) -> dict:
    """Two-tenant asymmetric overload (tenancy/admission.py): equal
    weights, the heavy tenant offering 10x the light tenant's rate, the
    aggregate a sustained overload.  The weighted-fair buckets must hold
    the light tenant's ADMITTED share within 10% of its weight share
    (0.5) for the whole enforcement window — the exact starvation the
    global priority floor could not prevent (both tenants submit at the
    same priority)."""
    import json as _json

    from k8s1m_tpu.control.objects import encode_pod, pod_key
    from k8s1m_tpu.loadshed import HEALTHY, LoadshedConfig, Overloaded
    from k8s1m_tpu.snapshot.pod_encoding import PodInfo
    from k8s1m_tpu.tenancy import TenancyController, TenancyPolicy

    b = args.batch
    cfg = LoadshedConfig(
        queue_degraded=2 * b, queue_shed=4 * b, queue_cap=64 * b,
        queue_recover=b // 2, recover_cycles=3,
        degraded_score_pct=args.degraded_score_pct,
    )
    tn = TenancyController(
        TenancyPolicy(weights={"heavy": 1, "light": 1}),
        loadshed_config=cfg, name="tenant_asym",
    )
    store, coord = _mk_cluster(args, tenancy=tn)
    # Light saturates just past its fair share (0.55 x capacity), heavy
    # offers 10x that: ~6x aggregate overload, every tenant saturating,
    # so admitted throughput should track weight shares exactly.
    light_per_tick = max(1, int(0.55 * b))
    heavy_per_tick = 10 * light_per_tick
    total = light_per_tick + heavy_per_tick
    seq = 0
    enforce_base = None
    enforce_ticks = 0
    try:
        for tick in range(args.healthy_ticks + 6 * args.overload_ticks):
            # Bresenham-interleaved arrivals: light pods spread through
            # the heavy flood (a bursty arrival order would test the
            # queue cap, not the fairness layer).
            acc = 0
            for i in range(total):
                acc += light_per_tick
                tenant = "light" if acc >= total else "heavy"
                if acc >= total:
                    acc -= total
                pod = PodInfo(
                    f"t{tick:03d}-{i:05d}", namespace=tenant,
                    cpu_milli=10, mem_kib=1 << 10,
                )
                obj = _json.loads(encode_pod(pod))
                try:
                    coord.submit_external(obj)
                except Overloaded:
                    continue
                store.put(pod_key(tenant, pod.name), encode_pod(pod))
                seq += 1
            coord.step()
            state = tn.controller.current_state()
            if state != HEALTHY:
                if enforce_base is None:
                    # Enforcement just engaged: measure shares from here
                    # (the pre-pressure ticks legitimately admit all).
                    enforce_base = tn.admission.counters()["admitted"]
                else:
                    enforce_ticks += 1
    finally:
        counters = tn.admission.counters()
        coord.close()
        store.close()
    adm = counters["admitted"]
    base = enforce_base or {}
    adm_l = adm.get("light", 0) - base.get("light", 0)
    adm_h = adm.get("heavy", 0) - base.get("heavy", 0)
    share_l = adm_l / max(adm_l + adm_h, 1)
    weight_share = 0.5
    return {
        "offered_per_tick": {"heavy": heavy_per_tick, "light": light_per_tick},
        "enforce_ticks": enforce_ticks,
        "admitted_under_enforcement": {"light": adm_l, "heavy": adm_h},
        "light_admitted_share": round(share_l, 4),
        "light_weight_share": weight_share,
        "rejected": counters["rejected"],
        "passed": bool(
            enforce_ticks >= 5
            and adm_l > 0
            and abs(share_l - weight_share) <= 0.10 * weight_share
        ),
    }


def _snapshot_usage(coord) -> dict[int, tuple[int, int, int]]:
    """Per-row (cpu, mem, pods) requested usage, copied host-side."""
    h = coord.host
    return {
        row: (int(h.cpu_req[row]), int(h.mem_req[row]), int(h.pods_req[row]))
        for row in h._row_of.values()
    }


def main(argv=None) -> dict:
    args = parse_args(argv)
    overload = run_overload(args)
    breaker = run_breaker(args)
    tenant_asym = run_tenant_asym(args)
    result = {
        "metric": "overload_drill" + ("_smoke" if args.smoke else ""),
        "value": overload["throughput_ratio"],
        "unit": "degraded/healthy binds ratio",
        "vs_baseline": None,
        "passed": bool(
            overload["passed"] and breaker["passed"]
            and tenant_asym["passed"]
        ),
        "seed": args.seed,
        "shape": {
            "nodes": args.nodes, "batch": args.batch, "chunk": args.chunk,
            "score_pct": args.score_pct,
            "degraded_score_pct": args.degraded_score_pct,
            "factor": args.factor, "priorities": args.priorities,
        },
        "overload": overload,
        "breaker": breaker,
        "tenant_asym": tenant_asym,
    }
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
