"""Cluster verification helpers: the kwok repo's shell one-liners as
store-native tools (reference kwok/count_ready.sh, kwok/find-gaps.sh).

- ``count-ready`` tallies nodes by Ready condition and pods by phase
  (count_ready.sh pipes ``kubectl get nodes`` through awk|sort|uniq).
- ``find-gaps`` scans kwok-node-<i> / any <prefix>-<i> numbering for
  holes — the smoke test that make_nodes/make_pods delivered a dense
  index range (find-gaps.sh's awk gap detector).

Both stream the store with paginated keys-only/value ranges rather than
materializing the object list, so they stay cheap at 1M objects.
"""

from __future__ import annotations

import argparse
import collections
import json
import re
import sys

from k8s1m_tpu.store.native import scan_prefix

NODES_PREFIX = b"/registry/minions/"
PODS_PREFIX = b"/registry/pods/"


def count_ready(store) -> dict:
    """{'nodes': {status: count}, 'pods': {phase: count}}."""
    nodes: collections.Counter = collections.Counter()
    for kv in scan_prefix(store, NODES_PREFIX):
        try:
            obj = json.loads(kv.value)
            ready = "Unknown"
            for cond in obj.get("status", {}).get("conditions", []):
                if cond.get("type") == "Ready":
                    ready = cond.get("status", "Unknown")
            nodes["Ready" if ready == "True" else f"NotReady({ready})"] += 1
        # Counted: "undecodable" in the report IS the diagnosis.
        except Exception:  # graftlint: disable=broad-except
            nodes["undecodable"] += 1
    pods: collections.Counter = collections.Counter()
    for kv in scan_prefix(store, PODS_PREFIX):
        try:
            obj = json.loads(kv.value)
            phase = obj.get("status", {}).get("phase", "Pending")
            if not obj.get("spec", {}).get("nodeName"):
                phase = f"{phase}(unbound)"
            pods[phase] += 1
        # Counted: "undecodable" in the report IS the diagnosis.
        except Exception:  # graftlint: disable=broad-except
            pods["undecodable"] += 1
    return {"nodes": dict(nodes), "pods": dict(pods)}


def find_gaps(store, prefix: bytes = NODES_PREFIX, pattern: str = r"-(\d+)$"):
    """Missing indices in a dense <name>-<i> keyspace; list of (lo, hi)
    inclusive gap ranges."""
    rx = re.compile(pattern.encode())
    seen = []
    for kv in scan_prefix(store, prefix, keys_only=True):
        m = rx.search(kv.key)
        if m:
            seen.append(int(m.group(1)))
    seen.sort()
    gaps = []
    for a, b in zip(seen, seen[1:]):
        if b != a and b != a + 1:
            gaps.append((a + 1, b - 1))
    return gaps


def main(argv=None):
    ap = argparse.ArgumentParser(description="cluster state verification")
    ap.add_argument("--ca-pem", default=None, help="TLS: trust this CA")
    ap.add_argument("--token", default=None, help="bearer token")
    ap.add_argument("--target", default=None,
                    help="remote store addr (default: in-process test store)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("count-ready")
    g = sub.add_parser("find-gaps")
    g.add_argument("--prefix", default="/registry/minions/")
    args = ap.parse_args(argv)

    if args.target:
        from k8s1m_tpu.store.remote import RemoteStore

        store = RemoteStore(args.target, ca_pem=args.ca_pem, token=args.token)
    else:
        ap.error("--target is required outside tests")
    try:
        if args.cmd == "count-ready":
            print(json.dumps(count_ready(store)))
        else:
            gaps = find_gaps(store, args.prefix.encode())
            for lo, hi in gaps:
                print(f"Gap detected: {lo} to {hi}")
            print(json.dumps({"gaps": len(gaps)}))
            return 1 if gaps else 0
    finally:
        store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
