"""Shared scaffolding for the load-generator CLIs.

The reference's generators shard work across 10-12 clientsets x 100
goroutines and report rates to stdout (reference kwok/make_pods/main.go:38,85-102,
etcd-lease-flood/main.go:88-101); here each tool is an asyncio worker
pool over one or more gRPC channels with a periodic rate reporter.
"""

from __future__ import annotations

import asyncio
import dataclasses
import sys
import time

from k8s1m_tpu import faultline
from k8s1m_tpu.faultline import GiveUp, policy_for
from k8s1m_tpu.store.etcd_client import EtcdClient


class RateReporter:
    """Prints ops/sec once per interval, like the reference's stdout logs."""

    def __init__(
        self, label: str, interval_s: float = 1.0, quiet: bool = False,
        milestone: int = 0,
    ):
        self.label = label
        self.interval_s = interval_s
        self.quiet = quiet
        # Progress line every ``milestone`` ops regardless of quiet —
        # the heartbeat of an hour-scale bulk run (megarow: every 100k
        # nodes), rare enough not to be the 1s rate spam --quiet mutes.
        self.milestone = milestone
        self._milestones = 0
        self.count = 0
        self.errors = 0
        self._t0 = time.perf_counter()
        self._last = self._t0
        self._last_count = 0

    def add(self, n: int = 1) -> None:
        self.count += n
        now = time.perf_counter()
        if self.milestone and self.count // self.milestone > self._milestones:
            self._milestones = self.count // self.milestone
            rate = self.count / max(now - self._t0, 1e-9)
            print(
                f"{self.label}: {self.count:,} "
                f"({now - self._t0:,.1f}s, {rate:,.0f}/s overall)",
                flush=True,
            )
            self._last, self._last_count = now, self.count
            return
        if not self.quiet and now - self._last >= self.interval_s:
            rate = (self.count - self._last_count) / (now - self._last)
            print(f"{self.label}: {self.count} total, {rate:,.0f}/s", flush=True)
            self._last, self._last_count = now, self.count

    def summary(self) -> dict:
        dt = time.perf_counter() - self._t0
        return {
            "label": self.label,
            "count": self.count,
            "errors": self.errors,
            "seconds": round(dt, 3),
            "rate": round(self.count / dt, 1) if dt > 0 else 0.0,
        }


async def run_sharded(
    total: int,
    concurrency: int,
    make_client,
    work,
    *,
    clients: int = 1,
    reporter: RateReporter | None = None,
    retries: int = 2,
    max_errors: int | None = None,
):
    """Run ``work(client, index)`` for index in [0, total) across a worker
    pool; ``clients`` separate channels spread HTTP/2 stream contention
    the way the reference uses multiple clientsets.

    A failing item is retried under the shared ``tools.loadgen``
    RetryPolicy (k8s1m_tpu/faultline/policy.py — jittered backoff, not
    the old zero-sleep hammer; ``retries`` overrides its attempt count),
    then counted in ``reporter.errors`` and skipped — one transient gRPC
    error must not abort an hours-long load run.  ``max_errors``
    (default: 1% of total, at least 100) aborts runs where the target is
    actually down.
    """
    if max_errors is None:
        max_errors = max(100, total // 100)
    policy = dataclasses.replace(
        policy_for("tools.loadgen"), max_attempts=retries + 1
    )
    pool = [make_client() for _ in range(max(1, clients))]
    queue: asyncio.Queue = asyncio.Queue()
    for i in range(total):
        queue.put_nowait(i)
    errors = 0

    async def worker(wid: int):
        nonlocal errors
        client = pool[wid % len(pool)]
        while True:
            try:
                i = queue.get_nowait()
            except asyncio.QueueEmpty:
                return
            try:
                done = await policy.acall(
                    lambda: work(client, i), op="work",
                    retryable=lambda e: True,
                )
                if reporter:
                    # A work item that returns an int covers that many
                    # logical ops (e.g. one batched RPC of N puts).
                    reporter.add(done if isinstance(done, int) else 1)
            except GiveUp as e:
                errors += 1
                if reporter:
                    reporter.errors += 1
                print(
                    f"work item {i} failed after {e.attempts} "
                    f"attempts: {e.cause!r}",
                    file=sys.stderr,
                )
                if errors > max_errors:
                    raise
            if errors > max_errors:
                return

    try:
        await asyncio.gather(*(worker(w) for w in range(concurrency)))
    finally:
        for c in pool:
            await c.close()


def add_common_args(ap):
    ap.add_argument("--target", default="127.0.0.1:2379", help="etcd server addr")
    ap.add_argument("--concurrency", type=int, default=100)
    ap.add_argument("--clients", type=int, default=4, help="separate gRPC channels")
    ap.add_argument("--quiet", action="store_true")
    # Secured-tier targets (store/watch_cache.py --tls-cert/--auth-token):
    # the generators authenticate like any other apiserver client.
    ap.add_argument("--ca-pem", default=None,
                    help="TLS: trust this CA for --target (rig chain)")
    ap.add_argument("--token", default=None,
                    help="bearer token sent as authorization metadata")
    ap.add_argument("--fault-plan", default=None,
                    help="faultline plan: inline JSON or @path "
                    "(k8s1m_tpu/faultline — deterministic fault "
                    "injection for the run)")


def apply_fault_plan(args) -> None:
    """Install the --fault-plan (if any) as the process's injector."""
    fp = getattr(args, "fault_plan", None)
    if fp:
        faultline.install_plan(faultline.FaultPlan.from_arg(fp))


def client_factory(args):
    # Each client must be a real separate connection: grpc Python shares
    # one TCP connection across channels to the same target (global
    # subchannel pool), so without this every "client" multiplexes onto a
    # single connection and trips the server's HTTP/2
    # max_concurrent_streams=100 (RST_STREAM REFUSED_STREAM) under load —
    # the same reason the reference shards across 10-12 clientsets.
    return lambda: EtcdClient(
        args.target, options=[("grpc.use_local_subchannel_pool", 1)],
        ca_pem=getattr(args, "ca_pem", None),
        token=getattr(args, "token", None),
    )
