"""N concurrent watches measuring event delivery rates (the
apiserver-stress equivalent, reference apiserver-stress/src/main.rs:54-97:
N watchers against the apiserver count events/sec to expose watch
amplification — 18M watches at 1M nodes, README.adoc:410-416).

    python -m k8s1m_tpu.tools.watch_stress --watchers 100 --writes 10000
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

from k8s1m_tpu.store.etcd_client import EtcdClient
from k8s1m_tpu.store.native import prefix_end

PREFIX = b"/stress/watched/"


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="concurrent watch stress")
    ap.add_argument("--target", default="127.0.0.1:2379")
    ap.add_argument("--watchers", type=int, default=50)
    ap.add_argument("--writes", type=int, default=1000)
    ap.add_argument("--write-concurrency", type=int, default=50)
    ap.add_argument("--quiet", action="store_true")
    return ap.parse_args(argv)


async def amain(args) -> dict:
    # Every watcher sees every write: total deliveries = watchers x writes.
    watch_client = EtcdClient(args.target, ca_pem=getattr(args, 'ca_pem', None), token=getattr(args, 'token', None))
    sessions = []
    for _ in range(args.watchers):
        s = watch_client.watch(PREFIX, prefix_end(PREFIX))
        await s.__aenter__()
        sessions.append(s)

    delivered = 0
    stream_errors = 0
    done = asyncio.Event()

    async def drain(s):
        nonlocal delivered, stream_errors
        while delivered < args.watchers * args.writes:
            try:
                batch = await s.next(timeout=10)
            except asyncio.TimeoutError:
                return
            # Counted, not logged: stream_errors is the report's signal.
            except Exception:  # graftlint: disable=broad-except
                # A failed stream must not masquerade as slow delivery:
                # count it so the summary distinguishes error from lag.
                stream_errors += 1
                return
            delivered += len(batch.events)
            if delivered >= args.watchers * args.writes:
                done.set()

    drainers = [asyncio.create_task(drain(s)) for s in sessions]

    write_client = EtcdClient(args.target, ca_pem=getattr(args, 'ca_pem', None), token=getattr(args, 'token', None))
    t0 = time.perf_counter()

    async def writer(wid: int):
        for i in range(wid, args.writes, args.write_concurrency):
            await write_client.put(PREFIX + b"key-%06d" % (i % 100), b"x" * 64)

    await asyncio.gather(*(writer(w) for w in range(args.write_concurrency)))
    write_s = time.perf_counter() - t0
    try:
        await asyncio.wait_for(done.wait(), timeout=30)
    except asyncio.TimeoutError:
        pass
    total_s = time.perf_counter() - t0

    for t in drainers:
        t.cancel()
    for s in sessions:
        await s.cancel()
    await watch_client.close()
    await write_client.close()

    return {
        "watchers": args.watchers,
        "writes": args.writes,
        "writes_per_sec": round(args.writes / write_s, 1),
        "events_delivered": delivered,
        "events_per_sec": round(delivered / total_s, 1),
        "amplification": args.watchers,
        "stream_errors": stream_errors,
    }


def main(argv=None):
    print(json.dumps(asyncio.run(amain(parse_args(argv)))))


if __name__ == "__main__":
    main()
