"""KWOK-vs-kubelet fidelity A/B — the reference's realism experiment.

The reference ran the same workload under KWOK fake nodes and under
100K real kubelets-in-pods and compared control-plane load shapes
(reference README.adoc:789-861): request rates were about equal, but
kubelets added more watches, more Events, and more DB size.  This tool
reproduces that comparison against our store with our two simulators:

    python -m k8s1m_tpu.tools.fidelity_ab --nodes 2000 --pods 2000

Each arm gets a fresh in-process store: make nodes, run a coordinator
to bind pods, drive the node simulator for --sim-seconds of simulated
time, then report write counts (revision delta), key counts, and DB
size.  The expected shape mirrors the reference's finding: kubelet
arms write Events and full-Node heartbeats that KWOK skips.
"""

from __future__ import annotations

import argparse
import json

from k8s1m_tpu.cluster.kwok_controller import KwokController
from k8s1m_tpu.cluster.kubelet_sim import EVENTS_PREFIX, KubeletPool
from k8s1m_tpu.store.native import prefix_end

LEASES_PREFIX = b"/registry/leases/"
from k8s1m_tpu.config import PodSpec, TableSpec
from k8s1m_tpu.control.coordinator import Coordinator
from k8s1m_tpu.control.objects import encode_node, encode_pod, node_key, pod_key
from k8s1m_tpu.plugins.registry import Profile
from k8s1m_tpu.snapshot.pod_encoding import PodInfo
from k8s1m_tpu.store.native import MemStore
from k8s1m_tpu.tools.make_nodes import build_node


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="kwok vs kubelet-sim load A/B")
    ap.add_argument("--nodes", type=int, default=2000)
    ap.add_argument("--pods", type=int, default=2000)
    ap.add_argument("--sim-seconds", type=int, default=60)
    ap.add_argument("--batch", type=int, default=512)
    return ap.parse_args(argv)


def run_arm(args, make_sim) -> dict:
    store = MemStore()
    for i in range(args.nodes):
        node = build_node(i)
        node.labels["kwok-group"] = "0"
        store.put(node_key(node.name), encode_node(node))
    rev_after_nodes = store.current_revision

    cap = 1 << max(10, (args.nodes - 1).bit_length())
    coord = Coordinator(
        store, TableSpec(max_nodes=cap), PodSpec(batch=args.batch),
        Profile(node_affinity=0, topology_spread=0, interpod_affinity=0),
        chunk=1 << 10, with_constraints=False, backend="xla",
    )
    coord.bootstrap()
    sim = make_sim(store)
    sim.bootstrap(0.0)

    for i in range(args.pods):
        store.put(
            pod_key("default", f"ab-{i}"),
            encode_pod(PodInfo(f"ab-{i}", cpu_milli=10, mem_kib=1024)),
        )
    bound = coord.run_until_idle()

    now = 0.0
    while now < args.sim_seconds:
        now += 1.0
        sim.tick(now)

    stats = {
        "bound": bound,
        "writes_total": store.current_revision - rev_after_nodes,
        "num_keys": store.num_keys,
        "db_size": store.db_size,
        "events": store.range(
            EVENTS_PREFIX, prefix_end(EVENTS_PREFIX), count_only=True
        ).count,
        "leases": store.range(
            LEASES_PREFIX, prefix_end(LEASES_PREFIX), count_only=True
        ).count,
    }
    sim.close()
    coord.close()
    store.close()
    return stats


def main(argv=None):
    args = parse_args(argv)
    kwok = run_arm(args, lambda s: KwokController(s, group=0))
    kubelet = run_arm(args, lambda s: KubeletPool(s))
    print(json.dumps({
        "config": {"nodes": args.nodes, "pods": args.pods,
                   "sim_seconds": args.sim_seconds},
        "kwok": kwok,
        "kubelet_sim": kubelet,
        "ratios": {
            k: round(kubelet[k] / kwok[k], 2) if kwok[k] else None
            for k in ("writes_total", "num_keys", "db_size", "events")
        },
    }, indent=1))


if __name__ == "__main__":
    main()
