"""Latency/throughput curve: p50/p95/p99 schedule-to-bind vs offered load.

The reference's primary metric is two-headed — binds/s AND p50
schedule-to-bind (SURVEY.md:27; the fleet's ~560µs/pod at 14K/s,
reference README.adoc:783-787).  One operating point says nothing about
the shape: latency at low load shows the floor (batch formation +
device round trip), latency near saturation shows the knee.  This
driver sweeps ``sched_bench --rate`` over a list of offered loads, one
fresh subprocess per point (clean store, clean metrics, compile cache
warm per process), and writes the curve as JSONL plus a markdown table.

    python -m k8s1m_tpu.tools.latency_curve --nodes 1048576 \
        --rates 2000,4000,6000,8000,10000,12000,16000,20000 \
        --out artifacts/latency_curve.jsonl
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="p50-vs-rate curve driver")
    ap.add_argument("--nodes", type=int, default=1_048_576)
    ap.add_argument("--score-pct", type=int, default=5)
    ap.add_argument("--backend", choices=("xla", "pallas"), default="xla")
    ap.add_argument(
        "--rates", default="2000,4000,6000,8000,10000,12000,16000,20000",
        help="comma-separated offered loads (pods/s)",
    )
    ap.add_argument(
        "--seconds", type=float, default=12.0,
        help="target measured window per point (pods = rate * seconds)",
    )
    ap.add_argument("--min-pods", type=int, default=20_000)
    ap.add_argument("--out", default="artifacts/latency_curve.jsonl")
    ap.add_argument("--timeout", type=float, default=1800.0,
                    help="per-point subprocess timeout (s)")
    return ap.parse_args(argv)


def _deadline_wrapper() -> str | None:
    """Locate tools/with_deadline.py: K8S1M_WITH_DEADLINE wins (how an
    installed/wheel deployment points at it), else the repo-checkout
    layout (this file's ``parents[2]/tools/``).  None = not found; the
    caller warns and runs the point unwrapped rather than failing the
    sweep — an unwrapped point merely loses the in-process deadline."""
    import os
    import pathlib

    env = os.environ.get("K8S1M_WITH_DEADLINE")
    if env:
        if pathlib.Path(env).is_file():
            return env
        print(f"# K8S1M_WITH_DEADLINE={env!r} does not exist",
              file=sys.stderr)
        return None
    p = pathlib.Path(__file__).resolve().parents[2] / "tools" / "with_deadline.py"
    return str(p) if p.is_file() else None


def run_point(args, rate: int) -> dict | None:
    pods = max(args.min_pods, int(rate * args.seconds))
    # The point self-deadlines IN-PROCESS (tools/with_deadline.py): a
    # subprocess.run(timeout=) kill mid-TPU-op would lose the axon grant
    # and take the pool down for every later point.  The outer timeout
    # stays as a last resort, with slack so it should never fire first.
    wrapper = _deadline_wrapper()
    if wrapper is None:
        print("# with_deadline.py not found (set K8S1M_WITH_DEADLINE); "
              "running unwrapped — only the outer timeout guards this "
              "point", file=sys.stderr)
        head = [sys.executable]
    else:
        head = [sys.executable, wrapper, str(args.timeout)]
    cmd = head + [
        "-m", "k8s1m_tpu.tools.sched_bench",
        "--nodes", str(args.nodes), "--pods", str(pods),
        "--rate", str(rate), "--score-pct", str(args.score_pct),
        "--backend", args.backend,
    ]
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            cmd, stdout=subprocess.PIPE, text=True, timeout=args.timeout + 300
        )
    except subprocess.TimeoutExpired:
        # Should never fire (the in-process deadline + watchdog act
        # first); if it does, record the point as failed but keep the
        # sweep going — the remaining rates still produce a curve.
        print(f"# rate={rate}: outer timeout", file=sys.stderr)
        return None
    if proc.returncode != 0:
        print(f"# rate={rate}: rc={proc.returncode}", file=sys.stderr)
        return None
    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    det = doc["detail"]
    return {
        "rate": rate,
        "pods": pods,
        "binds_per_sec": det["binds_per_sec"],
        "p50_ms": det["p50_ms"],
        "p95_ms": det["p95_ms"],
        "p99_ms": det["p99_ms"],
        "bound": det["bound"],
        "point_wall_s": round(time.perf_counter() - t0, 1),
    }


def main(argv=None):
    args = parse_args(argv)
    rates = [int(r) for r in args.rates.split(",") if r]
    rows = []
    with open(args.out, "w") as f:
        for rate in rates:
            row = run_point(args, rate)
            if row is None:
                continue
            rows.append(row)
            f.write(json.dumps(row) + "\n")
            f.flush()
            print(f"# rate={rate}: p50={row['p50_ms']}ms "
                  f"p99={row['p99_ms']}ms ach={row['binds_per_sec']}/s",
                  file=sys.stderr)
    # Markdown table for PARITY.
    print("| offered pods/s | achieved binds/s | p50 ms | p95 ms | p99 ms |")
    print("|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['rate']} | {r['binds_per_sec']} | {r['p50_ms']} "
              f"| {r['p95_ms']} | {r['p99_ms']} |")


if __name__ == "__main__":
    main()
