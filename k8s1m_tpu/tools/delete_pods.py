"""Bulk pod deletion (the delete_pods equivalent,
reference kwok/delete_pods/main.go:80-92).

    python -m k8s1m_tpu.tools.delete_pods --namespace default --prefix bench-pod
"""

from __future__ import annotations

import argparse
import asyncio
import json

from k8s1m_tpu.store.etcd_client import EtcdClient
from k8s1m_tpu.store.native import prefix_end
from k8s1m_tpu.tools.common import (
    RateReporter,
    add_common_args,
    client_factory,
    run_sharded,
)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="bulk-delete pods")
    add_common_args(ap)
    ap.add_argument("--namespace", default="default")
    ap.add_argument("--prefix", default="", help="pod-name prefix filter")
    return ap.parse_args(argv)


async def amain(args) -> dict:
    lister = EtcdClient(args.target, ca_pem=getattr(args, 'ca_pem', None), token=getattr(args, 'token', None))
    key_prefix = f"/registry/pods/{args.namespace}/{args.prefix}".encode()
    resp = await lister.range(key_prefix, prefix_end(key_prefix), keys_only=True)
    keys = [kv.key for kv in resp.kvs]
    await lister.close()

    reporter = RateReporter("pods deleted", quiet=args.quiet)

    async def work(client, i):
        await client.delete(keys[i])

    await run_sharded(
        len(keys), args.concurrency, client_factory(args), work,
        clients=args.clients, reporter=reporter,
    )
    return reporter.summary()


def main(argv=None):
    print(json.dumps(asyncio.run(amain(parse_args(argv)))))


if __name__ == "__main__":
    main()
