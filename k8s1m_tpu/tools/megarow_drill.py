"""megarow: the 1,048,576-node cluster, end to end, on the CPU lane.

The paper's entire claim is stated *at one million nodes* — mem_etcd,
the sharded scheduler and the KWOK harness exist to make that number
real — and the repo's north-star metric is
``pod_binds_per_sec_1048576_nodes``, yet committed evidence topped out
at 131k bench rows.  This drill stands the whole loop up at the
headline shape and lands the number:

1. **Bulk registration** — make_nodes-shaped Node objects written
   through the store's BatchKV put-frame lane (the ``make_nodes
   --bulk`` wire path, in-process here), rate reported.
2. **Timed cold build** — ``Coordinator.bootstrap()``: values-only
   relist -> template bulk ingest (snapshot/bulkload.py) -> one packed
   table build, with the wall landing in ``megarow_cold_build_seconds``
   instead of a multi-minute silent stall.
3. **Comparison lane** (the acceptance proxy) — at the 131k shape,
   the same cold build through the pre-megarow per-node
   ``decode_node`` + ``upsert`` loop vs the bulk lane, on one store;
   the bulk lane must be >= 3x faster end to end (gated).  The bulk
   lane runs FIRST so process warm-up favors the baseline.
4. **Composed byte-identity differential** — the deltacache+index
   lane vs the full-recompute lane over identical stores and
   submission sequences at ``--differential-nodes`` rows: every bind
   must land byte-identically, and the index lane must actually have
   taken index waves (gated).
5. **Sustained window** — the composed steady-drill shape at full
   scale: tenant-aware weighted-fair submission, capacity-only node
   churn scattering mid-flight, a forced bind-CAS conflict cadence,
   an overload phase that must walk to SHEDDING and recover, depth-3
   pipelining, deltacache + the score-stratified candidate index on
   (full-scan waves, so all-hit waves ride the O(dirty + K*batch)
   index path instead of the O(batch x N) plane scan), packed
   layout.  Gates: zero admitted pods lost, zero structural/resync
   quiesces, SHEDDING seen + HEALTHY recovered, median in-flight
   depth at the configured depth, zero retry give-ups, zero packed
   fallbacks.

Peak host RSS is reported (and gated when ``--rss-budget-mib`` is
set — the tier-1 smoke sets it, so host-memory regressions fail
loudly).  Results land as one JSON line plus ``--out`` evidence::

    # tier-1 smoke (131,072 rows)
    python -m k8s1m_tpu.tools.megarow_drill --smoke

    # the committed artifact (SLOW: several minutes at 1M rows)
    python -m k8s1m_tpu.tools.megarow_drill \
        --out artifacts/megarow_cpu.json
"""

from __future__ import annotations

import argparse
import json
import resource
import time

IDLE_DRAIN_TICKS = 20000


def peak_rss_mib() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description="the million-node cluster end to end (CPU lane)"
    )
    ap.add_argument("--nodes", type=int, default=1 << 20)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--chunk", type=int, default=4096)
    ap.add_argument("--depth", type=int, default=3)
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--tenant-skew", type=float, default=1.0)
    ap.add_argument("--steady-ticks", type=int, default=24)
    ap.add_argument("--overload-ticks", type=int, default=12)
    ap.add_argument("--recover-ticks", type=int, default=60)
    ap.add_argument("--factor", type=int, default=4)
    ap.add_argument("--churn-per-tick", type=int, default=256,
                    help="capacity-only node updates written per tick "
                    "(scattered mid-flight; structural quiesces stay 0)")
    ap.add_argument("--conflict-every", type=int, default=53,
                    help="faultline: force a bind-CAS conflict on "
                    "average every Nth CAS attempt (seeded probability "
                    "1/N per attempt — NOT a strict period: a periodic "
                    "every_n resonates with the steady wave cadence, "
                    "and a retried pod whose requeue lands back on the "
                    "period eats the injected conflict on every attempt "
                    "until it exhausts max_attempts — a give-up "
                    "manufactured by the injection pattern, not by the "
                    "scheduler the zero-give-up gate exists to judge)")
    ap.add_argument("--sat-ticks", type=int, default=24,
                    help="saturated-throughput phase: steps measured "
                    "with the queue held at ~2x batch via store-put "
                    "intake (no admission involvement, HEALTHY "
                    "throughout) — the headline binds/s is "
                    "scheduler-bound, not producer-bound")
    ap.add_argument("--bulk", type=int, default=8192,
                    help="nodes per BatchKV put-frame during "
                    "registration (the make_nodes --bulk lane)")
    ap.add_argument("--compare-nodes", type=int, default=131072,
                    help="cold-build comparison shape (bulk lane vs "
                    "the pre-megarow per-node loop; 0 skips the lane)")
    ap.add_argument("--rss-budget-mib", type=int, default=0,
                    help="gate peak host RSS at this budget "
                    "(0 = report only; the tier-1 smoke sets it)")
    ap.add_argument("--deltacache", choices=("off", "on"), default="on")
    ap.add_argument(
        "--score-pct", type=int, default=100,
        help="scored-window fraction.  100 (the default since the "
        "candidate index landed) keeps waves on the full-scan shape "
        "the delta cache requires — sampled windows compute different "
        "planes than the cache holds, so any score_pct < 100 disables "
        "the delta/index path entirely (the pre-index drill ran 50)",
    )
    ap.add_argument(
        "--delta-index-k", type=int, default=64,
        help="per-resident-plane top-K candidate index: all-hit waves "
        "derive candidates from the index + dirty set and skip the "
        "O(N) plane scan (0 disables; requires --deltacache on).  64 "
        "spans ~two default-width strata, so the eviction floor cuts "
        "BELOW the whole top class instead of through it",
    )
    ap.add_argument(
        "--stratum-bits", type=int, default=None,
        help="high jitter bits drawn from a wave-invariant per-column "
        "hash stratum: KWOK nodes are homogeneous, so ~every row ties "
        "at one score and an unstratified index floor fails closed "
        "every wave.  Default derives from the shape — "
        "log2(nodes) - 5, i.e. ~32 tied rows per (score, stratum) "
        "class (see stratum_bits_for).  Too coarse and the K-deep "
        "floor cannot cut inside the top class (permanent underflow); "
        "too FINE and the class order becomes a near-total "
        "wave-invariant ranking shared by every pod — each wave then "
        "converges on the same few rows, the per-row pod cap starves "
        "it, and retried pods march to give-up (0 pins the historical "
        "seeded jitter bit-for-bit)",
    )
    ap.add_argument(
        "--differential-nodes", type=int, default=131072,
        help="composed byte-identity differential shape: the "
        "deltacache+index lane vs full recompute over identical "
        "stores/submissions, every bind compared (0 skips the lane)",
    )
    ap.add_argument("--packing", choices=("off", "packed"),
                    default="packed")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 shape: 131,072 rows, same gates "
                    "(including the >= 3x cold-build proxy and an RSS "
                    "budget)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.smoke:
        args.nodes = 131072
        args.batch, args.chunk = 128, 1024
        args.steady_ticks, args.overload_ticks = 8, 6
        args.recover_ticks = 40
        args.churn_per_tick = 128
        args.bulk = 4096
        args.sat_ticks = 16
        args.differential_nodes = min(args.differential_nodes, 32768)
        if args.rss_budget_mib == 0:
            args.rss_budget_mib = 4096
    if args.nodes % args.chunk:
        ap.error(f"--nodes {args.nodes} not divisible by --chunk {args.chunk}")
    if args.differential_nodes % args.chunk:
        ap.error(
            f"--differential-nodes {args.differential_nodes} not "
            f"divisible by --chunk {args.chunk}"
        )
    if args.delta_index_k and args.deltacache != "on":
        ap.error("--delta-index-k requires --deltacache on")
    args.stratum_auto = args.stratum_bits is None
    if args.stratum_auto:
        args.stratum_bits = stratum_bits_for(args.nodes)
    return args


def stratum_bits_for(nodes: int) -> int:
    """Stratum width targeting ~2^5 tied rows per (score, stratum)
    class: log2(nodes) - 5, clamped to [1, 18].

    The class width is the placement-diversity budget.  Per-pod jitter
    only varies WITHIN a class (the stratum occupies the high tie-break
    bits so the index floor argument holds), so a wave of B pods
    spreads over roughly one class worth of rows; at ~32 rows x the
    110-pod row cap that is ~3,500 pods of headroom per wave against a
    512-pod batch and depth-3 pipelining.  Widths that leave <= a few
    rows per class collapse every wave onto the same near-full rows —
    the give-up march the zero-lost gate exists to catch."""
    return max(1, min(18, max(nodes, 2).bit_length() - 1 - 5))


def _node_bytes(i: int, gen: int) -> bytes:
    """make_nodes-shaped node; ``gen`` varies capacity only (the churn
    lane must never be structural)."""
    from k8s1m_tpu.control.objects import encode_node
    from k8s1m_tpu.tools.make_nodes import build_node

    node = build_node(i)
    if gen >= 0:
        node.cpu_milli = 32000 + (gen % 16)
    return encode_node(node)


def register_nodes(store, n: int, bulk: int) -> dict:
    """Phase 1: the bulk registration lane (store put-frames)."""
    from k8s1m_tpu.control.objects import node_key
    from k8s1m_tpu.tools.make_nodes import build_node

    from k8s1m_tpu.tools.common import RateReporter

    reporter = RateReporter("nodes registered", quiet=True,
                            milestone=100_000)
    t0 = time.perf_counter()
    batch: list = []
    done = 0
    for i in range(n):
        name = build_node(i).name
        batch.append((node_key(name), _node_bytes(i, -1)))
        if len(batch) >= bulk:
            store.put_batch(batch)
            done += len(batch)
            reporter.add(len(batch))
            batch = []
    if batch:
        store.put_batch(batch)
        done += len(batch)
        reporter.add(len(batch))
    dt = time.perf_counter() - t0
    return {
        "nodes": done,
        "seconds": round(dt, 3),
        "rate_per_sec": round(done / dt, 1) if dt > 0 else 0.0,
        "bulk": bulk,
    }


def cold_build_compare(n: int, packing: str) -> dict:
    """Phase 3: the >= 3x acceptance proxy at the 131k shape — one
    store, both cold-build lanes, identical layouts.  Bulk runs first
    so any process warm-up (numpy, jit caches) favors the baseline."""
    import numpy as np

    from k8s1m_tpu.config import TableSpec
    from k8s1m_tpu.control.objects import decode_node, node_key
    from k8s1m_tpu.snapshot.bulkload import BulkNodeLoader
    from k8s1m_tpu.snapshot.node_table import NodeTableHost
    from k8s1m_tpu.snapshot.packing import pack_table_auto
    from k8s1m_tpu.store.native import (
        MemStore,
        list_prefix,
        list_prefix_values,
    )
    import jax

    prefix = b"/registry/minions/"
    store = MemStore()
    batch: list = []
    for i in range(n):
        batch.append((node_key(f"kwok-node-{i}"), _node_bytes(i, -1)))
        if len(batch) >= 8192:
            store.put_batch(batch)
            batch = []
    if batch:
        store.put_batch(batch)
    spec = TableSpec(max_nodes=n, max_zones=16, max_regions=8)

    def build(table_host):
        if packing == "packed":
            table = pack_table_auto(table_host, spec)
        else:
            table = table_host.to_device()
        jax.block_until_ready(table.cpu_alloc)
        return table

    t0 = time.perf_counter()
    values, _rev = list_prefix_values(store, prefix)
    host_new = NodeTableHost(spec)
    BulkNodeLoader(host_new).ingest(values)
    del values
    build(host_new)
    bulk_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    kvs, _rev = list_prefix(store, prefix)
    host_old = NodeTableHost(spec)
    for kv in kvs:
        host_old.upsert(decode_node(kv.value))
    del kvs
    build(host_old)
    loop_s = time.perf_counter() - t0

    identical = all(
        np.array_equal(getattr(host_old, c), getattr(host_new, c))
        for c in ("valid", "cpu_alloc", "mem_alloc", "pods_alloc",
                  "label_key", "label_val", "label_num",
                  "taint_id", "taint_effect", "zone", "region", "name_id")
    ) and host_old._row_of == host_new._row_of
    store.close()
    return {
        "nodes": n,
        "per_node_loop_seconds": round(loop_s, 3),
        "bulk_lane_seconds": round(bulk_s, 3),
        "speedup": round(loop_s / bulk_s, 2) if bulk_s > 0 else None,
        "byte_identical": bool(identical),
    }


def index_differential(n: int, args) -> dict | None:
    """Phase 4: composed byte-identity differential — the
    deltacache+index lane vs the full-recompute lane over identical
    stores and submission sequences.  Both lanes run the SAME
    stratum_bits (stratified jitter changes tie-breaks, so the
    differential isolates the index, not the algebra); every bound
    pod's stored bytes must match exactly, and the index lane must
    have taken at least one index wave or the comparison is vacuous.

    Both lanes run a ZERO-DELAY retry policy.  The default policy
    parks a CAS-rolled-back pod behind ``perf_counter() + ~10-20ms``
    of jittered backoff, so whether it rejoins the wave after next or
    the one after depends on how the inter-step wall time raced the
    delay — batch composition (and with it every later tie-break)
    becomes a function of host speed.  Pinning the delay to zero makes
    requeued pods eligible at the very next take, and with it this
    lane has NO wall-clock input left to placement: pod/node intake is
    poll-synchronous (MemStore watch queues drain at step start, no
    pump thread), and no breaker, loadshed controller or adaptive
    bucket is configured here — the only other paths that branch on
    elapsed time.  Validated by running each lane twice at the full
    131,072-row shape and comparing every stored pod byte-for-byte:
    identical run to run, and identical across lanes.  A failure here
    is therefore a REAL index bug, never timing — do not reach for a
    backoff explanation before reproducing the divergence with this
    function standalone."""
    if not n:
        return None
    from k8s1m_tpu.config import PodSpec, TableSpec
    from k8s1m_tpu.control.coordinator import Coordinator
    from k8s1m_tpu.control.objects import encode_pod, node_key, pod_key
    from k8s1m_tpu.faultline.policy import RetryPolicy
    from k8s1m_tpu.obs.metrics import REGISTRY
    from k8s1m_tpu.plugins.registry import Profile
    from k8s1m_tpu.snapshot.pod_encoding import PodInfo
    from k8s1m_tpu.store.native import MemStore
    from k8s1m_tpu.tools.make_nodes import build_node

    b = args.batch
    waves = 6
    # The differential runs at its own (smaller) shape: a stratum width
    # tuned for the main lane's node count would leave <1 row per class
    # here — re-derive unless the caller pinned --stratum-bits.
    stratum = (
        stratum_bits_for(n) if getattr(args, "stratum_auto", False)
        else args.stratum_bits
    )
    no_backoff = RetryPolicy(
        component="coordinator.bind", base_delay_s=0.0, max_delay_s=0.0,
        jitter=0.0,
    )

    def drive(index_on: bool) -> dict[str, bytes | None]:
        store = MemStore()
        batch: list = []
        for i in range(n):
            batch.append((node_key(build_node(i).name), _node_bytes(i, -1)))
            if len(batch) >= args.bulk:
                store.put_batch(batch)
                batch = []
        if batch:
            store.put_batch(batch)
        coord = Coordinator(
            store,
            TableSpec(max_nodes=n, max_zones=16, max_regions=8),
            PodSpec(batch=b),
            Profile(topology_spread=0, interpod_affinity=0),
            chunk=min(args.chunk, n), k=4, with_constraints=False,
            seed=args.seed, score_pct=100, pipeline=True,
            depth=args.depth, mesh="none", packing=args.packing,
            deltacache="on" if index_on else "off",
            delta_index_k=args.delta_index_k if index_on else 0,
            stratum_bits=stratum,
            retry_policy=no_backoff,
        )
        try:
            coord.bootstrap()
            seq = 0
            churned = 0
            for _ in range(waves):
                for _ in range(b):
                    seq += 1
                    pod = PodInfo(f"d{seq:06d}", namespace="diff",
                                  cpu_milli=10, mem_kib=1 << 10)
                    store.put(pod_key("diff", pod.name), encode_pod(pod))
                # Capacity-only churn, identical rows in both lanes.
                for _ in range(64):
                    i = churned % n
                    store.put(
                        node_key(build_node(i).name),
                        _node_bytes(i, churned),
                    )
                    churned += 1
                coord.step()
            coord.run_until_idle()
            binds: dict[str, bytes | None] = {}
            for s in range(1, seq + 1):
                kv = store.get(pod_key("diff", f"d{s:06d}"))
                binds[f"d{s:06d}"] = kv.value if kv else None
            return binds
        finally:
            coord.close()
            store.close()

    iw = REGISTRY.get("deltasched_index_waves_total")
    iw0 = iw.value(path="index")
    with_index = drive(True)
    index_waves = int(iw.value(path="index") - iw0)
    full = drive(False)
    bound = sum(1 for v in full.values() if v and b'"nodeName"' in v)
    return {
        "nodes": n,
        "waves": waves,
        "pods": len(full),
        "bound": bound,
        "stratum_bits": stratum,
        "index_waves": index_waves,
        "byte_identical": bool(with_index == full),
    }


def run(args) -> dict:
    from k8s1m_tpu import faultline
    from k8s1m_tpu.cluster.workload import zipf_weights
    from k8s1m_tpu.config import PodSpec, TableSpec
    from k8s1m_tpu.control.coordinator import Coordinator
    from k8s1m_tpu.control.objects import encode_pod, node_key, pod_key
    from k8s1m_tpu.faultline import FaultPlan, FaultSpec, install_plan
    from k8s1m_tpu.loadshed import (
        HEALTHY,
        SHEDDING,
        STATE_NAMES,
        LoadshedConfig,
        Overloaded,
    )
    from k8s1m_tpu.obs.metrics import REGISTRY
    from k8s1m_tpu.plugins.registry import Profile
    from k8s1m_tpu.snapshot.packing import FALLBACK_REASONS
    from k8s1m_tpu.snapshot.pod_encoding import PodInfo
    from k8s1m_tpu.store.native import MemStore
    from k8s1m_tpu.tenancy import TenancyController, TenancyPolicy
    from k8s1m_tpu.tools.make_nodes import build_node

    b = args.batch
    z = zipf_weights(args.tenants, args.tenant_skew)
    weights = {
        f"tenant-{t}": max(1, round(z[t] / z[-1]))
        for t in range(args.tenants)
    }
    tenants = list(weights)
    total_w = sum(weights.values())
    cfg = LoadshedConfig(
        queue_degraded=3 * b, queue_shed=6 * b, queue_cap=64 * b,
        queue_recover=b, recover_cycles=3,
    )
    tn = TenancyController(
        TenancyPolicy(weights=weights), loadshed_config=cfg,
        name="megarow_drill",
    )
    # Seeded probability, not every_n: a strict period resonates with
    # the steady wave cadence (CAS attempts per wave are near-constant,
    # so a requeued pod can land on the period every retry and be
    # marched to give-up by the injector itself — see --conflict-every).
    plan = FaultPlan(
        [FaultSpec("coordinator.bind", "cas", kind="err5xx",
                   probability=1.0 / max(args.conflict_every, 1))],
        seed=args.seed,
    )

    quiesce = REGISTRY.get("pipeline_quiesce_total")
    q0 = {r: quiesce.value(reason=r) for r in ("structural", "resync")}
    giveups = REGISTRY.get("retry_give_ups_total")
    giveup0 = giveups.value(component="coordinator.bind")
    pack_fb = REGISTRY.get("device_packing_fallback_total")
    fb0 = {r: pack_fb.value(reason=r) for r in FALLBACK_REASONS}
    cold_gauge = REGISTRY.get("megarow_cold_build_seconds")
    mirror_gauge = REGISTRY.get("megarow_host_mirror_bytes")

    compare = (
        cold_build_compare(args.compare_nodes, args.packing)
        if args.compare_nodes else None
    )
    differential = (
        index_differential(args.differential_nodes, args)
        if args.delta_index_k else None
    )

    # Index baselines AFTER the differential lane (which takes its own
    # index waves) so the window accounting below is the window's own.
    idx_waves = REGISTRY.get("deltasched_index_waves_total")
    idx_drops = REGISTRY.get("deltasched_index_drops_total")
    iw0 = {p: idx_waves.value(path=p) for p in ("index", "plane")}
    _DROP_REASONS = ("underflow", "oversized-dirty", "fill",
                     "generation", "resync", "packing",
                     "fill-error", "dispatch-error")
    id0 = {r: idx_drops.value(reason=r) for r in _DROP_REASONS}

    store = MemStore()
    ingest = register_nodes(store, args.nodes, args.bulk)

    coord = Coordinator(
        store,
        TableSpec(max_nodes=args.nodes, max_zones=16, max_regions=8),
        PodSpec(batch=b), Profile(topology_spread=0, interpod_affinity=0),
        chunk=args.chunk, k=4, with_constraints=False, seed=args.seed,
        score_pct=args.score_pct, pipeline=True, depth=args.depth,
        tenancy=tn, mesh="none", packing=args.packing,
        deltacache=args.deltacache, delta_index_k=args.delta_index_k,
        stratum_bits=args.stratum_bits,
    )

    seq = 0
    churned = 0
    admitted: list[tuple[str, str]] = []
    rejected = 0
    bound_total = 0
    states_seen: set[int] = set()
    depth_samples: list[int] = []
    recovered_at = None

    def submit(n: int) -> None:
        nonlocal seq, rejected
        lanes = []
        for t in tenants:
            share = max(1, round(n * weights[t] / total_w))
            lanes += [(k / share, t) for k in range(share)]
        lanes.sort()
        for _, t in lanes:
            seq += 1
            pod = PodInfo(f"p{seq:07d}", namespace=t,
                          cpu_milli=10, mem_kib=1 << 10)
            obj = json.loads(encode_pod(pod))
            try:
                coord.submit_external(obj)
            except Overloaded:
                rejected += 1
                continue
            store.put(pod_key(t, pod.name), encode_pod(pod))
            admitted.append((t, pod.name))

    def sat_submit(n: int) -> None:
        """Store-put intake (the watch path): no admission draw, so the
        saturation phase measures the scheduler, not the shedder."""
        nonlocal seq
        for _ in range(n):
            seq += 1
            t = tenants[seq % len(tenants)]
            pod = PodInfo(f"p{seq:07d}", namespace=t,
                          cpu_milli=10, mem_kib=1 << 10)
            store.put(pod_key(t, pod.name), encode_pod(pod))
            admitted.append((t, pod.name))

    def churn_tick() -> None:
        nonlocal churned
        for _ in range(args.churn_per_tick):
            i = churned % args.nodes
            store.put(
                node_key(build_node(i).name), _node_bytes(i, churned)
            )
            churned += 1

    def tick(n: int, producing: bool) -> None:
        nonlocal bound_total
        submit(n)
        churn_tick()
        bound_total += coord.step()
        states_seen.add(tn.controller.current_state())
        if producing:
            depth_samples.append(len(coord._inflights))

    try:
        t0 = time.perf_counter()
        coord.bootstrap()
        cold_build_s = time.perf_counter() - t0
        print(
            f"cold build: {cold_build_s:,.1f}s at {args.nodes:,} rows",
            flush=True,
        )
        # Warm the compile caches outside the measured window.
        submit(b)
        coord.run_until_idle()
        bound_warm = len(admitted)
        install_plan(plan)
        t_win = time.perf_counter()
        for _ in range(args.steady_ticks):
            tick(b, True)
        for _ in range(args.overload_ticks):
            tick(args.factor * b, True)
        for t in range(args.recover_ticks):
            tick(b // 2, False)
            if (
                tn.controller.current_state() == HEALTHY
                and recovered_at is None
            ):
                recovered_at = t + 1
        # Saturated-throughput phase: backlog held near 2x batch (below
        # the 3x degraded watermark, so the production mode is what is
        # measured), churn still landing every tick.
        sat_submit(2 * b)
        sat_bound = 0
        t_sat = time.perf_counter()
        for _ in range(args.sat_ticks):
            churn_tick()
            done = coord.step()
            sat_bound += done
            bound_total += done
            states_seen.add(tn.controller.current_state())
            sat_submit(done)
        sat_s = time.perf_counter() - t_sat
        for _ in range(IDLE_DRAIN_TICKS):
            if (
                not coord.queue and not coord._backoff
                and not coord._external_pending() and not coord._inflights
            ):
                break
            bound_total += coord.step()
            w = coord.backoff_wait_s()
            if w:
                time.sleep(min(w, 0.05))
        bound_total += coord.flush()
        window_s = time.perf_counter() - t_win
        install_plan(None)
        lost = 0
        for t, name in admitted:
            kv = store.get(pod_key(t, name))
            if kv is None or b'"nodeName"' not in kv.value:
                lost += 1
        host_mirror_bytes = int(coord.host.mirror_nbytes())
        delta_on = coord.delta_enabled
    finally:
        install_plan(None)
        coord.close()
        store.close()

    import numpy as np

    samples = np.asarray(depth_samples or [0])
    qd = {r: int(quiesce.value(reason=r) - q0[r]) for r in q0}
    give_ups = giveups.value(component="coordinator.bind") - giveup0
    packing_fallbacks = sum(
        int(pack_fb.value(reason=r) - fb0[r]) for r in fb0
    )
    window_bound = len(admitted) - bound_warm - lost
    binds_per_sec = round(window_bound / window_s, 1) if window_s else 0.0
    sat_rate = round(sat_bound / sat_s, 1) if sat_s else 0.0
    rss = round(peak_rss_mib(), 1)
    return {
        "nodes": args.nodes,
        "weights": weights,
        "packing": args.packing,
        "deltacache": "on" if delta_on else "off",
        "score_pct": args.score_pct,
        "delta_index_k": args.delta_index_k,
        "stratum_bits": args.stratum_bits,
        "index_waves": {
            p: int(idx_waves.value(path=p) - iw0[p]) for p in iw0
        },
        "index_drops": {
            r: int(idx_drops.value(reason=r) - id0[r])
            for r in id0 if idx_drops.value(reason=r) - id0[r]
        },
        "index_differential": differential,
        "bulk_ingest": ingest,
        "cold_build_seconds": round(cold_build_s, 3),
        "cold_build_metric_seconds": round(cold_gauge.value(), 3),
        "cold_build_compare": compare,
        "host_mirror_bytes": host_mirror_bytes,
        "host_mirror_bytes_metric": int(mirror_gauge.value()),
        "peak_rss_mib": rss,
        "rss_budget_mib": args.rss_budget_mib or None,
        "window_seconds": round(window_s, 3),
        "window_bound": window_bound,
        "binds_per_sec_composed": binds_per_sec,
        "saturated_seconds": round(sat_s, 3),
        "saturated_bound": sat_bound,
        "binds_per_sec": sat_rate,
        "admitted": len(admitted),
        "rejected": rejected,
        "lost": lost,
        "node_churn_events": churned,
        "pipeline_quiesce": qd,
        "sustained_inflight_depth": int(np.median(samples)),
        "max_inflight_depth": int(samples.max()),
        "states_seen": sorted(STATE_NAMES[s] for s in states_seen),
        "recovered_at_tick": recovered_at,
        "retry_give_ups": int(give_ups),
        "packing_fallbacks": packing_fallbacks,
        "passed": bool(
            lost == 0
            and qd["structural"] == 0
            and qd["resync"] == 0
            and int(np.median(samples)) >= args.depth
            and SHEDDING in states_seen
            and recovered_at is not None
            and give_ups == 0
            and (args.packing != "packed" or packing_fallbacks == 0)
            and (
                compare is None
                or (compare["byte_identical"] and compare["speedup"] >= 3.0)
            )
            and (
                differential is None
                or (
                    differential["byte_identical"]
                    and differential["index_waves"] > 0
                    and differential["bound"] > 0
                )
            )
            and (not args.rss_budget_mib or rss <= args.rss_budget_mib)
        ),
    }


def main(argv=None) -> dict:
    args = parse_args(argv)
    evidence = run(args)
    result = {
        "metric": f"pod_binds_per_sec_{args.nodes}_nodes",
        "value": evidence["binds_per_sec"],
        "unit": "binds/s, saturated phase under sustained churn "
                "(CPU lane; the TPU number is a backend swap)",
        "vs_baseline": None,
        "passed": evidence["passed"],
        "seed": args.seed,
        "shape": {
            "nodes": args.nodes, "batch": args.batch,
            "chunk": args.chunk, "depth": args.depth,
            "tenants": args.tenants, "factor": args.factor,
            "churn_per_tick": args.churn_per_tick,
            "packing": args.packing, "deltacache": args.deltacache,
            "score_pct": args.score_pct,
            "delta_index_k": args.delta_index_k,
            "stratum_bits": args.stratum_bits,
            "smoke": bool(args.smoke),
        },
        "evidence": evidence,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
