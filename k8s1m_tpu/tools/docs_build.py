"""Docs pipeline: render the repo's markdown docs to a static HTML site.

The reference publishes its asciidoc docs through an asciidoctor->HTML
pipeline (SURVEY §2 item 16); this is the same role for this repo's
markdown set, dependency-free: ``python -m k8s1m_tpu.tools.docs_build
--out docs/site`` renders README.md, PARITY.md, and friends with an
index page.  The converter covers the subset these docs use — headings,
fenced code, tables, lists, links, emphasis — not all of markdown.
"""

from __future__ import annotations

import argparse
import html
import pathlib
import re
import sys

DEFAULT_DOCS = ["README.md", "MIGRATION.md", "PARITY.md", "SURVEY.md", "BASELINE.md"]

_STYLE = """
body { max-width: 60rem; margin: 2rem auto; padding: 0 1rem;
       font: 16px/1.55 system-ui, sans-serif; color: #1a1a1a; }
pre { background: #f6f8fa; padding: .8rem; overflow-x: auto;
      border-radius: 6px; font-size: 85%; }
code { background: #f6f8fa; padding: .1em .3em; border-radius: 4px;
       font-size: 90%; }
pre code { background: none; padding: 0; }
table { border-collapse: collapse; margin: 1rem 0; display: block;
        overflow-x: auto; }
th, td { border: 1px solid #d0d7de; padding: .35rem .7rem;
         text-align: left; vertical-align: top; }
th { background: #f6f8fa; }
h1, h2, h3 { line-height: 1.25; }
a { color: #0969da; text-decoration: none; }
a:hover { text-decoration: underline; }
nav { border-bottom: 1px solid #d0d7de; padding-bottom: .5rem;
      margin-bottom: 1.5rem; }
"""


def _inline(text: str) -> str:
    text = html.escape(text, quote=False)
    text = re.sub(r"`([^`]+)`", r"<code>\1</code>", text)
    text = re.sub(r"\*\*([^*]+)\*\*", r"<strong>\1</strong>", text)
    text = re.sub(r"(?<!\w)\*([^*]+)\*(?!\w)", r"<em>\1</em>", text)
    text = re.sub(
        r"\[([^\]]+)\]\(([^)\s]+)\)",
        lambda m: f'<a href="{re.sub(r"[.]md$", ".html", m.group(2))}">'
        f"{m.group(1)}</a>",
        text,
    )
    return text


def md_to_html(src: str) -> str:
    out: list[str] = []
    lines = src.splitlines()
    i = 0
    in_list = False

    def close_list():
        nonlocal in_list
        if in_list:
            out.append("</ul>")
            in_list = False

    while i < len(lines):
        line = lines[i]
        if line.startswith("```"):
            close_list()
            i += 1
            block = []
            while i < len(lines) and not lines[i].startswith("```"):
                block.append(lines[i])
                i += 1
            i += 1
            out.append(
                "<pre><code>" + html.escape("\n".join(block)) + "</code></pre>"
            )
            continue
        if line.startswith("|") and i + 1 < len(lines) and re.match(
            r"^\|[\s:|-]+\|?\s*$", lines[i + 1]
        ):
            close_list()
            cells = [c.strip() for c in line.strip().strip("|").split("|")]
            out.append("<table><thead><tr>")
            out += [f"<th>{_inline(c)}</th>" for c in cells]
            out.append("</tr></thead><tbody>")
            i += 2
            while i < len(lines) and lines[i].startswith("|"):
                row = [c.strip() for c in lines[i].strip().strip("|").split("|")]
                out.append(
                    "<tr>" + "".join(f"<td>{_inline(c)}</td>" for c in row)
                    + "</tr>"
                )
                i += 1
            out.append("</tbody></table>")
            continue
        m = re.match(r"^(#{1,6})\s+(.*)$", line)
        if m:
            close_list()
            n = len(m.group(1))
            out.append(f"<h{n}>{_inline(m.group(2))}</h{n}>")
        elif re.match(r"^\s*[-*]\s+", line):
            if not in_list:
                out.append("<ul>")
                in_list = True
            out.append(
                "<li>" + _inline(re.sub(r"^\s*[-*]\s+", "", line)) + "</li>"
            )
        elif line.strip() == "":
            close_list()
        else:
            close_list()
            out.append(f"<p>{_inline(line)}</p>")
        i += 1
    close_list()
    return "\n".join(out)


def _page(title: str, nav: str, body: str) -> str:
    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        f"<title>{html.escape(title)}</title><style>{_STYLE}</style></head>"
        f"<body><nav>{nav}</nav>{body}</body></html>"
    )


def build(repo: pathlib.Path, out: pathlib.Path, docs: list[str]) -> list[str]:
    out.mkdir(parents=True, exist_ok=True)
    present = [d for d in docs if (repo / d).exists()]
    nav = " | ".join(
        f'<a href="{pathlib.Path(d).stem.lower()}.html">'
        f"{pathlib.Path(d).stem}</a>"
        for d in ["index.md"] + present
    ).replace("index.html\">Index", "index.html\">Home")
    written = []
    for d in present:
        body = md_to_html((repo / d).read_text())
        name = pathlib.Path(d).stem.lower() + ".html"
        (out / name).write_text(_page(d, nav, body))
        written.append(name)
    index = "<h1>k8s1m-tpu documentation</h1><ul>" + "".join(
        f'<li><a href="{pathlib.Path(d).stem.lower()}.html">{d}</a></li>'
        for d in present
    ) + "</ul>"
    (out / "index.html").write_text(_page("k8s1m-tpu docs", nav, index))
    written.append("index.html")
    return written


def main(argv=None):
    ap = argparse.ArgumentParser(description="build the HTML doc site")
    ap.add_argument("--repo", default=".")
    ap.add_argument("--out", default="docs/site")
    ap.add_argument("--docs", nargs="*", default=DEFAULT_DOCS)
    args = ap.parse_args(argv)
    written = build(
        pathlib.Path(args.repo), pathlib.Path(args.out), args.docs
    )
    print(f"wrote {len(written)} pages to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
