"""Put/range throughput benchmark against the store (the stress-client
equivalent, reference mem_etcd/stress-client/src/main.rs:42-107).

    python -m k8s1m_tpu.tools.store_stress --puts 50000 --ranges 1000
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import time

from k8s1m_tpu import faultline
from k8s1m_tpu.store.native import prefix_end
from k8s1m_tpu.tools.common import (
    RateReporter,
    add_common_args,
    apply_fault_plan,
    client_factory,
    run_sharded,
)

PREFIX = b"/stress/keys/"


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="store put/range stress")
    add_common_args(ap)
    ap.add_argument("--puts", type=int, default=10000)
    ap.add_argument("--ranges", type=int, default=100)
    ap.add_argument("--value-size", type=int, default=256)
    ap.add_argument("--range-limit", type=int, default=100)
    ap.add_argument(
        "--native-client", action="store_true",
        help="drive the standard per-RPC Put path with the native "
        "pipelined client (wf_stress_put) instead of Python grpcio — "
        "with one host core, Python saturates near 20K RPC/s while the "
        "server can serve 400K+; this measures the SERVER (the "
        "reference's stress-client is native for the same reason)",
    )
    ap.add_argument("--key-count", type=int, default=10000,
                    help="distinct keys cycled by --native-client")
    return ap.parse_args(argv)


async def amain(args) -> dict:
    apply_fault_plan(args)
    if args.native_client:
        from k8s1m_tpu.store.native import wire_stress_put

        host, _, port = args.target.rpartition(":")
        n, elapsed = await asyncio.get_running_loop().run_in_executor(
            None, lambda: wire_stress_put(
                host or "127.0.0.1", int(port), args.puts,
                concurrency=args.concurrency,
                prefix=PREFIX.decode(), key_count=args.key_count,
                val_len=args.value_size,
            )
        )
        return {
            "puts": n,
            "puts_per_sec": round(n / elapsed, 1),
            "client": "native-per-rpc",
        }
    value = os.urandom(args.value_size)
    put_rep = RateReporter("puts", quiet=args.quiet)

    async def put_work(client, i):
        # Faultline hook: the asyncio client's wire edge (the sync
        # RemoteStore carries its own hooks; this one makes --fault-plan
        # meaningful for the load generators too).
        await faultline.acheck("store.wire", "put")
        await client.put(PREFIX + b"%012d" % i, value)

    t0 = time.perf_counter()
    await run_sharded(
        args.puts, args.concurrency, client_factory(args), put_work,
        clients=args.clients, reporter=put_rep,
    )
    put_s = time.perf_counter() - t0

    range_rep = RateReporter("ranges", quiet=args.quiet)

    async def range_work(client, i):
        await faultline.acheck("store.wire", "range")
        start = PREFIX + b"%012d" % ((i * 37) % max(1, args.puts))
        await client.range(start, prefix_end(PREFIX), limit=args.range_limit)

    t1 = time.perf_counter()
    await run_sharded(
        args.ranges, args.concurrency, client_factory(args), range_work,
        clients=args.clients, reporter=range_rep,
    )
    range_s = time.perf_counter() - t1

    out = {
        "puts": args.puts,
        "puts_per_sec": round(args.puts / put_s, 1),
        "put_errors": put_rep.errors,
        "ranges": args.ranges,
        "ranges_per_sec": round(args.ranges / range_s, 1) if args.ranges else 0,
        "range_errors": range_rep.errors,
    }
    fired = faultline.active_injector().fire_counts()
    if fired:
        out["faults_injected"] = fired
    return out


def main(argv=None):
    print(json.dumps(asyncio.run(amain(parse_args(argv)))))


if __name__ == "__main__":
    main()
