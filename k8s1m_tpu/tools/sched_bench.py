"""End-to-end scheduling benchmark: store -> watch -> TPU -> CAS binds.

The reference's headline is end-to-end pods/s through the whole control
plane (~14K/s at 1M nodes on 256 shards, reference README.adoc:730,783-787).
bench.py measures the device cycle alone; this tool measures the full
loop the coordinator runs in production: pods enter the store, arrive by
watch, are encoded, scheduled on the TPU, and bound back via Txn CAS —
with the pipelined coordinator overlapping device work and store writes.

    python -m k8s1m_tpu.tools.sched_bench --nodes 100000 --pods 50000

Runs against an in-process store by default (the store and scheduler
colocated, like the reference's mem_etcd benchmarks); --target uses a
remote store server instead, adding the gRPC hop to every operation.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time

from k8s1m_tpu.config import PodSpec, TableSpec
from k8s1m_tpu.control.coordinator import Coordinator
from k8s1m_tpu.envboot import tune_gc
from k8s1m_tpu.control.objects import encode_node, encode_pod, node_key, pod_key
from k8s1m_tpu.plugins.registry import Profile
from k8s1m_tpu.snapshot.pod_encoding import PodInfo
from k8s1m_tpu.store.native import MemStore
from k8s1m_tpu.tools.make_nodes import build_node

REFERENCE_E2E = 14_000.0


def _print_stage_stats(window_s: float) -> None:
    """Per-stage coordinator time totals over the measured window."""
    import sys

    from k8s1m_tpu.obs.metrics import REGISTRY

    cyc = REGISTRY.get("coordinator_cycle_seconds")
    for key in sorted(cyc.label_keys()):
        stage = dict(zip(cyc.labelnames, key)).get("stage", "?")
        print(
            f"# stage {stage:10s} {cyc.sum(stage=stage)*1e3:9.1f} ms "
            f"total ({cyc.sum(stage=stage)/window_s*100:5.1f}% of window)",
            file=sys.stderr,
        )


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="end-to-end scheduling bench")
    ap.add_argument("--nodes", type=int, default=100_000)
    ap.add_argument("--pods", type=int, default=50_000)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--chunk", type=int, default=None)
    ap.add_argument(
        "--backend", choices=("auto", "xla", "pallas"), default="auto",
        help="filter+score+top-k backend.  'auto' (default) picks the "
        "fused pallas kernel only when the jax backend is a real TPU "
        "and the XLA scan path otherwise — on CPU envs the kernel runs "
        "INTERPRETED, orders of magnitude slower, so an unconditional "
        "pallas default silently produced misleading numbers",
    )
    ap.add_argument("--target", default=None,
                    help="remote store addr (default: in-process store)")
    ap.add_argument("--ca-pem", default=None,
                    help="TLS: trust this CA for --target (a secured tier)")
    ap.add_argument("--token", default=None,
                    help="bearer token for --target")
    ap.add_argument(
        "--rate", type=int, default=0,
        help="offered load in pods/s (paced producer + adaptive batch "
        "buckets; reports p50/p95/p99 schedule-to-bind latency).  0 = "
        "max-throughput fill",
    )
    ap.add_argument(
        "--score-pct", type=int, default=100,
        help="percentageOfNodesToScore (the reference's 1M-node production "
        "config uses 5, terraform tfvars percentageOfNodesToScore: 5)",
    )
    ap.add_argument("--no-pipeline", action="store_true")
    ap.add_argument(
        "--stats", action="store_true",
        help="after the run, print per-stage coordinator time totals "
        "(drain/encode/device/sync_out/bind) to stderr",
    )
    ap.add_argument(
        "--encode-profile", action="store_true",
        help="add host-feed evidence to the report detail: host-encode "
        "seconds by path (inline vs hidden in the feed worker), encode "
        "template-cache hit rate, staged-batch use and stale-discard "
        "counts (snapshot/hotfeed.py)",
    )
    ap.add_argument(
        "--deltacache", choices=("off", "on"), default=None,
        help="incremental scheduling (engine/deltacache.py): cache each "
        "pod shape's feasibility/score plane in HBM and recompute only "
        "dirty rows on a shape hit — byte-identical binds, O(batch x "
        "dirty) steady-state device work.  Unset defers to "
        "K8S1M_DELTASCHED ('off' default)",
    )
    ap.add_argument(
        "--delta-profile", action="store_true",
        help="add delta-plane-cache evidence to the report detail: "
        "delta vs full wave split, shape hit rate, mean dirty "
        "fraction, planes resident, fills and LRU evictions, and (with "
        "--delta-index-k) the candidate-index wave/touched-rows/drop "
        "accounting (engine/deltacache.py)",
    )
    ap.add_argument(
        "--delta-index-k", type=int, default=0, metavar="K",
        help="per-resident-plane top-K candidate index (requires "
        "--deltacache on): all-hit waves derive candidates from the "
        "index + dirty set and skip the O(N) plane scan entirely — "
        "byte-identical binds, fail-closed on floor underflow.  0 "
        "disables the index",
    )
    ap.add_argument(
        "--stratum-bits", type=int, default=0, metavar="B",
        help="high jitter bits drawn from a wave-invariant per-(node, "
        "column) hash stratum instead of the seeded draw: splits tied "
        "score levels so the candidate-index floor can cut inside them "
        "(homogeneous clusters tie ~all rows at one score, which "
        "otherwise fails the index closed every wave).  Scale it with "
        "the cluster — per-pod spread only exists WITHIN a class, so "
        "target ~32 tied rows per class (log2(nodes) - 5, the "
        "megarow_drill.stratum_bits_for rule); 2^B >= nodes collapses "
        "every wave onto the same few rows.  0 keeps the historical "
        "jitter bit-for-bit",
    )
    ap.add_argument(
        "--shape-pool", type=int, default=0, metavar="N",
        help="pods draw structural shapes (nodeAffinity required + "
        "preferred terms, Deployment-template style) from a pool of N "
        "specs instead of the plain uniform pod — the paper's "
        "template-shaped firehose.  Enables the node-affinity plugin. "
        "0 = plain pods (default)",
    )
    ap.add_argument(
        "--shape-share", type=float, default=1.0, metavar="F",
        help="with --shape-pool: fraction of pods drawing from the hot "
        "pool; the rest draw from a bounded 4N-spec tail (the 90%%-hot "
        "regime of artifacts/hostpath_bench.json)",
    )
    ap.add_argument(
        "--shape-cold", action="store_true",
        help="every pod is its OWN shape (unique request scalars): the "
        "shape cache can never hit — the deltasched overhead lane",
    )
    ap.add_argument(
        "--depth", type=int, default=2,
        help="scheduling pipeline depth (in-flight waves; >2 helps when "
        "the device round trip dominates the wave, e.g. a remote relay)",
    )
    ap.add_argument(
        "--churn", action="store_true",
        help="BASELINE config 5 shape: delete the pods bound two waves "
        "ago while new waves arrive — sustained create+delete churn "
        "instead of a fill-up",
    )
    ap.add_argument(
        "--node-churn", type=float, default=0.0, metavar="RATE",
        help="steady capacity-only node-update traffic (updates/s) "
        "during the measured window — KWOK heartbeats / capacity "
        "updates at wall-clock rate.  The quiesce-free pipeline must "
        "hold its depth through this (pipeline_quiesce_total "
        "{reason=structural} stays 0; quiesce and sustained-depth "
        "evidence lands in the report detail)",
    )
    ap.add_argument(
        "--out", default=None, metavar="PATH",
        help="also write the report JSON to PATH (tier-1 smoke artifact)",
    )
    ap.add_argument(
        "--stress-watchers", type=int, default=0,
        help="run the apiserver-stress equivalent (tools/watch_stress) "
        "as a subprocess against the same --target for the whole "
        "measured window — config 5's full shape is churn UNDER watch "
        "stress.  Requires --target.",
    )
    ap.add_argument(
        "--stress-write-concurrency", type=int, default=1,
        help="stressor's concurrent writers (keep low on a single-core "
        "host or the stressor starves the scheduler it is stressing)",
    )
    ap.add_argument(
        "--mesh", default=None, metavar="DPxSP",
        help="drive the wave through the sharded step over a dp x sp "
        "device mesh (parallel/sharded_cycle.make_sharded_packed_step) — "
        "the reference's multi-replica fan-out as mesh devices.  "
        "Accepts DPxSP or DP,SP (dp*sp <= len(jax.devices())), or "
        "'auto' (largest workload-valid split).  Unset defers to "
        "K8S1M_MESH; the sharded run is byte-identical to single-device "
        "at score-pct 100, so every churn/overload/encode-profile lane "
        "composes with it.  Mesh evidence (per-shard staged feed depth, "
        "sharded-scatter counts) lands in the report detail.",
    )
    ap.add_argument(
        "--trace", type=int, default=0, metavar="N",
        help="podtrace (obs/podtrace.py): trace 1-in-N pods through "
        "the whole lifecycle (head-sampled, deterministic by pod-key "
        "hash); the stage-attribution waterfall lands in the report's "
        "latency_attribution detail.  0 = off (the null tracer — free)",
    )
    ap.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="with --trace: write the Chrome/Perfetto trace-event JSON "
        "export to PATH (stages as tracks, pods as flow events; load "
        "in ui.perfetto.dev or chrome://tracing)",
    )
    ap.add_argument(
        "--profile", metavar="PATH", default=None,
        help="sample the measured window with obs/profiler.py, write "
        "the collapsed-stack artifact to PATH, and print the self-time "
        "top table to stderr (the pprof/Parca role)",
    )
    ap.add_argument(
        "--fault-plan", default=None,
        help="faultline plan: inline JSON or @path "
        "(k8s1m_tpu/faultline — deterministic drop/delay/disconnect/"
        "conflict injection across the store wire and the coordinator; "
        "injected-fault and retry counts land in the output detail)",
    )
    ap.add_argument(
        "--overload-at", type=float, default=0.0,
        help="seconds into the paced window to start an overload phase "
        "(requires --rate; the producer jumps to rate x "
        "--overload-factor for --overload-seconds, then drops back — "
        "the shed-and-recover shape of tools/overload_drill.py at "
        "wall-clock scale)",
    )
    ap.add_argument("--overload-seconds", type=float, default=300.0)
    ap.add_argument("--overload-factor", type=float, default=5.0)
    ap.add_argument(
        "--tenants", type=int, default=0,
        help="spread the pod population over N tenant namespaces with "
        "zipf-skewed tenant sizes (cluster/workload.py tenant_assignments"
        "; seed-deterministic).  0 = the historical single-namespace "
        "load",
    )
    ap.add_argument("--tenant-skew", type=float, default=1.0,
                    help="zipf skew of tenant sizes (0 = uniform)")
    ap.add_argument(
        "--tenant-schedule", default="steady",
        choices=("steady", "diurnal", "flash"),
        help="tenant-mix arrival shape along the emission sequence "
        "(diurnal: phase-shifted day curves; flash: tenant-0 crowds "
        "10x in the middle fifth — pair with --rate for wall-clock "
        "arrival schedules)",
    )
    ap.add_argument("--seed", type=int, default=0,
                    help="tenant-assignment seed")
    ap.add_argument(
        "--packing", choices=("off", "packed"), default=None,
        help="device-snapshot layout (snapshot/packing.py): 'packed' "
        "holds the cold node-table columns bit/byte-packed in HBM "
        "(byte-identical binds, >=2x less cold-column HBM).  Unset "
        "defers to K8S1M_PACKING.  Layout + donation evidence lands in "
        "the report's device_state detail",
    )
    ap.add_argument(
        "--kernel-profile", action="store_true",
        help="after the measured window, decompose the device step via "
        "the plugin-knockout DCE trick (tools/kernel_probe.py): per-"
        "stage ms/batch and bytes/node land in the report's "
        "kernel_profile detail (each variant compiles once — budget "
        "seconds on CPU, tens of seconds on TPU)",
    )
    args = ap.parse_args(argv)
    if args.overload_at and not args.rate:
        ap.error("--overload-at requires --rate (the paced producer)")
    if args.trace_out and not args.trace:
        ap.error("--trace-out requires --trace (the pod tracer)")
    return args


def offered_pods_at(args, t: float) -> float:
    """Cumulative offered pods at ``t`` seconds into the paced window —
    the integral of the (piecewise-constant) offered rate, so the
    overload phase is a rate *step*, not a one-off burst."""
    if not args.overload_at or args.overload_factor <= 1.0:
        return args.rate * t
    t1 = args.overload_at
    t2 = t1 + args.overload_seconds
    total = args.rate * min(t, t1)
    if t > t1:
        total += args.rate * args.overload_factor * (min(t, t2) - t1)
    if t > t2:
        total += args.rate * (t - t2)
    return total


def _encode_profile_detail(enabled: bool) -> dict:
    """Host-feed evidence for the report (empty unless --encode-profile)."""
    if not enabled:
        return {}
    from k8s1m_tpu.obs.metrics import REGISTRY

    enc = REGISTRY.get("hotfeed_encode_seconds_total")
    hits = REGISTRY.get("hotfeed_cache_hits_total").value()
    misses = REGISTRY.get("hotfeed_cache_misses_total").value()
    stale = REGISTRY.get("hotfeed_stale_batches_total")
    cyc = REGISTRY.get("coordinator_cycle_seconds")
    return {"encode_profile": {
        # Worker-path seconds ran OFF the cycle critical path; the
        # encode stage below is what the cycle actually waited on
        # (claim hits make it ~the staged-batch handoff cost).
        "host_encode_seconds": {
            "inline": round(enc.value(path="inline"), 4),
            "feed": round(enc.value(path="feed"), 4),
        },
        "encode_stage_seconds": round(cyc.sum(stage="encode"), 4),
        "cache_hit_rate": (
            round(hits / (hits + misses), 4) if hits + misses else None
        ),
        "staged_used": int(
            REGISTRY.get("hotfeed_staged_used_total").value()
        ),
        "staged_stale": {
            r: int(stale.value(reason=r))
            for r in ("vocab", "reordered", "error", "merge")
        },
        "staged_depth": int(
            REGISTRY.get("hotfeed_staged_depth").value()
        ),
    }}


def _delta_profile_detail(args, coord) -> dict:
    """Delta-plane-cache evidence for the report (ISSUE 12 deltasched;
    empty unless --delta-profile)."""
    if not args.delta_profile:
        return {}
    from k8s1m_tpu.obs.metrics import REGISTRY

    waves = REGISTRY.get("deltasched_waves_total")
    delta_waves = waves.value(path="delta")
    full_waves = waves.value(path="full")
    hits = REGISTRY.get("deltasched_shape_hits_total").value()
    misses = REGISTRY.get("deltasched_shape_misses_total").value()
    dirty = REGISTRY.get("deltasched_dirty_rows_total").value()
    rows = coord.table_spec.max_nodes
    detail = {"delta_profile": {
        "enabled": coord.delta_enabled,
        "delta_waves": int(delta_waves),
        "full_waves": int(full_waves),
        "shape_hit_rate": (
            round(hits / (hits + misses), 4) if hits + misses else None
        ),
        # Journaled dirty rows actually recomputed, as a fraction of the
        # full-recompute work the delta waves displaced.
        "mean_dirty_fraction": (
            round(dirty / (delta_waves * rows), 6) if delta_waves else None
        ),
        "planes_resident": int(
            REGISTRY.get("deltasched_planes_resident").value()
        ),
        "fills": int(REGISTRY.get("deltasched_fills_total").value()),
        "evictions": int(
            REGISTRY.get("deltasched_evictions_total").value()
        ),
    }}
    cache = getattr(coord, "_delta", None)
    index_k = getattr(cache, "index_k", 0) if cache is not None else 0
    if index_k:
        iw = REGISTRY.get("deltasched_index_waves_total")
        touched = REGISTRY.get("deltasched_index_touched_rows_total")
        drops = REGISTRY.get("deltasched_index_drops_total")
        idx_waves = iw.value(path="index")
        plane_waves = iw.value(path="plane")
        t_idx = touched.value(path="index")
        t_plane = touched.value(path="plane")
        detail["delta_profile"]["index"] = {
            "index_k": int(index_k),
            "stratum_bits": int(cache.stratum_bits),
            "index_waves": int(idx_waves),
            "plane_waves": int(plane_waves),
            # Mean rows visited per wave on each tail — the index path
            # touches dirty + k*batch candidate rows; the plane path is
            # the N-row chunk scan plus the dirty slice.  The fraction
            # is the index tail's visit cost against the N rows each
            # such wave would otherwise have scanned.
            "mean_touched_rows": {
                "index": (
                    round(t_idx / idx_waves, 1) if idx_waves else None
                ),
                "plane": (
                    round(t_plane / plane_waves, 1)
                    if plane_waves else None
                ),
            },
            "index_touched_fraction_of_n": (
                round(t_idx / (idx_waves * rows), 6)
                if idx_waves else None
            ),
            # Why index-eligible waves fell back to the plane scan —
            # floor underflows vs oversized dirty sets vs wholesale
            # invalidations (fill / generation / resync / packing).
            "drops": {
                r: int(drops.value(reason=r))
                for r in (
                    "underflow", "oversized-dirty", "fill",
                    "generation", "resync", "packing",
                    "fill-error", "dispatch-error",
                )
                if drops.value(reason=r)
            },
        }
    return detail


def _trace_detail(args, tracer) -> dict:
    """Stage-attribution waterfall for the report (empty without
    --trace): per-stage p50/p99 + share of the end-to-end total,
    coverage, and the optional Perfetto export."""
    from k8s1m_tpu.obs.podtrace import trace_report_detail

    return trace_report_detail(tracer, args.trace_out)


def _tenant_detail(args) -> dict:
    """Tenant-load shape for the report (empty without --tenants)."""
    if not args.tenants:
        return {}
    return {"tenant_load": {
        "tenants": args.tenants,
        "skew": args.tenant_skew,
        "schedule": args.tenant_schedule,
        "seed": args.seed,
    }}


def _device_state_detail(coord) -> dict:
    """Device-snapshot layout + donation evidence (ISSUE 10): table
    layout, HBM bytes/node (total and cold-column, with the reduction
    ratio vs the plain i32 layout), whether per-wave commit donation ran
    in place, and any fail-closed layout rebuilds."""
    if coord.table is None:
        return {}
    from k8s1m_tpu.obs.metrics import REGISTRY
    from k8s1m_tpu.snapshot.packing import FALLBACK_REASONS, bytes_report

    fb = REGISTRY.get("device_packing_fallback_total")
    return {"device_state": {
        **bytes_report(coord.table, coord.table_spec),
        "donation_inplace": coord.donation_inplace,
        "packing_fallbacks": {
            r: int(fb.value(reason=r))
            for r in FALLBACK_REASONS if fb.value(reason=r)
        },
    }}


def _shard_local_table(coord):
    """Single-device copy of ONE sp shard's slice of the live table
    (the first row block), keeping the live layout — packed tables stay
    packed, so the profile includes the production per-chunk decode.
    profile_stages runs the single-device step; this view makes it time
    exactly the program each shard executes per stage (same rows/chunk
    shape as one shard's scan), instead of an unintended
    resharded/gathered run over the whole sharded table.  Built from
    each leaf's first ADDRESSABLE shard, not a global np.asarray: on a
    multi-host mesh the global array spans non-addressable devices (the
    gather would raise and lose the whole report), and even single-host
    it would fetch the full table only to keep 1/sp of it."""
    import jax
    import numpy as np

    sp = int(coord.mesh.shape["sp"])
    local_rows = coord.table_spec.max_nodes // sp
    dev = jax.local_devices()[0]

    def local(a):
        shards = getattr(a, "addressable_shards", None)
        if shards:
            # The shard holding the FIRST row block (deterministic
            # across dp replicas: all dp copies of block 0 are equal).
            s = min(
                shards,
                key=lambda s: tuple(sl.start or 0 for sl in s.index),
            )
            return jax.device_put(np.asarray(s.data), dev)
        return jax.device_put(np.asarray(a)[:local_rows], dev)

    return jax.tree.map(local, coord.table)


def _kernel_profile_detail(args, coord) -> dict:
    """Per-stage device-step decomposition for the report (opt-in:
    --kernel-profile; each plugin-knockout variant is its own compile).
    Runs over the coordinator's LIVE table — layout, request columns and
    vocab exactly as the measured window left them.  Under --mesh the
    probe times the SHARD-LOCAL step (one sp shard's row slice, live
    layout) and records dp/sp + rows_per_shard so the ms/batch numbers
    read as per-shard stage costs."""
    if not args.kernel_profile or coord.table is None:
        return {}
    from k8s1m_tpu.snapshot.packing import bytes_report
    from k8s1m_tpu.tools.kernel_probe import profile_stages

    if coord.mesh is not None:
        table = _shard_local_table(coord)
    else:
        table = coord.table
    prof = profile_stages(
        table, coord.encoder, chunk=args.chunk, k=coord.k,
        steps=3, backend=args.backend,
    )
    if coord.mesh is not None:
        prof["mesh"] = {
            "dp": int(coord.mesh.shape["dp"]),
            "sp": int(coord.mesh.shape["sp"]),
            "rows_per_shard": int(table.num_rows),
        }
    prof["bytes_per_node"] = bytes_report(table, coord.table_spec)
    prof["batch"] = coord.pod_spec.batch
    return {"kernel_profile": prof}


def _resilience_detail() -> dict:
    """Injected-fault + retry evidence for the output JSON (empty when
    no fault plan is active)."""
    from k8s1m_tpu import faultline

    fired = faultline.active_injector().fire_counts()
    if not fired:
        return {}
    return {
        "faults_injected": fired,
        "retry_attempts": faultline.retry_counts(),
        "give_ups": faultline.give_up_counts(),
        "recovery": faultline.recovery_stats(),
    }


class _NodeChurn:
    """Paced capacity-only node updates (same name, same labels, wiggled
    allocatable) — the steady heartbeat/capacity traffic the 1M full-
    churn config never stops emitting.  Capacity-only by construction:
    every update targets a node the table already holds, so the
    pipelined coordinator scatters it mid-flight without a quiesce."""

    def __init__(self, store, nodes: int, rate: float):
        self._store = store
        self._nodes = nodes
        self._rate = rate
        self.emitted = 0

    def advance(self, elapsed_s: float) -> None:
        due = int(self._rate * elapsed_s)
        # Bound one burst so a long device wave can't turn catch-up into
        # a giant synchronous write (which would itself stall the cycle).
        due = min(due, self.emitted + 4096)
        if due <= self.emitted:
            return
        items = []
        for j in range(self.emitted, due):
            i = j % self._nodes
            items.append((
                node_key(f"kwok-node-{i}"),
                encode_node(build_node(
                    i, cpu_milli=32000 + (j // self._nodes) % 16
                )),
            ))
        write_wave(self._store, items)
        self.emitted = due


_QUIESCE_REASONS = ("structural", "resync", "breaker", "adaptive")


def _quiesce_counts() -> dict:
    from k8s1m_tpu.obs.metrics import REGISTRY

    q = REGISTRY.get("pipeline_quiesce_total")
    return {r: q.value(reason=r) for r in _QUIESCE_REASONS}


def _overlap_totals() -> tuple[float, float]:
    """(hidden, exposed) host-stage seconds so far."""
    from k8s1m_tpu.control.coordinator import _OVERLAP_STAGES
    from k8s1m_tpu.obs.metrics import REGISTRY

    ov = REGISTRY.get("pipeline_stage_overlap_seconds_total")
    return (
        sum(ov.value(stage=s, inflight="yes") for s in _OVERLAP_STAGES),
        sum(ov.value(stage=s, inflight="no") for s in _OVERLAP_STAGES),
    )


def _pipeline_detail(
    coord, quiesce_base, overlap_base, depth_samples, churn
) -> dict:
    """Quiesce / in-flight-depth / overlap evidence for the report."""
    import numpy as np

    hid, exposed = _overlap_totals()
    hid -= overlap_base[0]
    exposed -= overlap_base[1]
    samples = np.asarray(depth_samples or [0])
    return {
        "node_churn_rate": churn._rate if churn else 0.0,
        "node_churn_events": churn.emitted if churn else 0,
        "pipeline_quiesce": {
            r: int(_quiesce_counts()[r] - quiesce_base[r])
            for r in _QUIESCE_REASONS
        },
        # Depth sampled after every step while the producer was live:
        # the pipeline holds --depth iff the median sits there.
        "sustained_inflight_depth": int(np.median(samples)),
        "max_inflight_depth": int(samples.max()),
        "depth_seconds": {
            str(k): round(v, 4) for k, v in coord.depth_timer.seconds().items()
        },
        # Share of instrumented host-stage time that ran while device
        # waves were in flight (i.e. cost hidden behind device work).
        "stage_overlap_ratio": round(
            hid / (hid + exposed), 4
        ) if hid + exposed else None,
    }


def _mesh_detail(coord, feed_depth_samples) -> dict:
    """dp x sp execution evidence for the report (empty when the run is
    single-device): axis sizes, sharded dirty-row scatter counts, and
    per-dp-shard staged feed depth sampled while the producer was live."""
    if coord.mesh is None:
        return {}
    import numpy as np

    from k8s1m_tpu.obs.metrics import REGISTRY

    sc = REGISTRY.get("mesh_sharded_scatter_total")
    detail = {
        "dp": int(coord.mesh.shape["dp"]),
        "sp": int(coord.mesh.shape["sp"]),
        "sharded_scatters": {
            c: int(sc.value(cols=c)) for c in ("full", "cap")
        },
    }
    if feed_depth_samples:
        per_shard = np.asarray(feed_depth_samples)   # [samples, dp]
        detail["feed_staged_depth_per_shard"] = {
            "max": per_shard.max(axis=0).tolist(),
            "mean": [round(v, 3) for v in per_shard.mean(axis=0)],
        }
    return {"mesh_exec": detail}


def _sample_mesh_feed(coord, feed_depth_samples) -> None:
    from k8s1m_tpu.snapshot.hotfeed import ShardedHostFeed

    feed = getattr(coord, "_feed", None)
    if isinstance(feed, ShardedHostFeed):
        feed_depth_samples.append(feed.depths())


def _pipeline_window_start(coord, store, args):
    """Baselines + trackers captured immediately before a measured
    window (must run AFTER warmup — warm waves count adaptive quiesces).
    Returns (quiesce_base, overlap_base, depth_samples, node_churn)."""
    coord.depth_timer.reset()
    return (
        _quiesce_counts(),
        _overlap_totals(),
        [],
        _NodeChurn(store, args.nodes, args.node_churn)
        if args.node_churn else None,
    )


def _emit_report(report: dict, out_path: str | None) -> dict:
    print(json.dumps(report), flush=True)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
    return report


def write_wave(store, items) -> None:
    """Apply (key, value|None-for-delete) pairs via the store's batched
    path when it has one, else per key."""
    put_batch = getattr(store, "put_batch", None)
    if put_batch is not None:
        put_batch(items)
        return
    for k, v in items:
        if v is None:
            store.delete(k)
        else:
            store.put(k, v)


import contextlib


@contextlib.contextmanager
def _bench_window(args, coord, store):
    """Measured-window lifecycle: optional watch stressor and sampling
    profiler for the whole window, and guaranteed teardown (stressor,
    coordinator watches, store channel) even when the window raises
    mid-run."""
    stress = (
        _start_watch_stress(
            args.target, args.stress_watchers, args.stress_write_concurrency
        )
        if args.stress_watchers else None
    )
    prof = None
    if args.profile:
        from k8s1m_tpu.obs.profiler import SamplingProfiler

        prof = SamplingProfiler().start()
        coord.profiler = prof
    try:
        yield
    finally:
        if prof is not None:
            prof.stop()
            prof.dump(args.profile)
            print(prof.format_top(), file=sys.stderr)
        if stress is not None:
            stress.terminate()
            try:
                stress.wait(timeout=10)
            except subprocess.TimeoutExpired:
                stress.kill()
        coord.close()
        if hasattr(store, "close"):
            store.close()


class _ChurnFrontier:
    """Tracks which emitted pods are safe to delete.

    Churn must only delete BOUND pods (bind order diverges from key
    order whenever pods retry), but a pod that binds *after* the delete
    frontier sweeps past must still be deleted later — otherwise any
    bind lag (retries, a backed-up run, a slow device) silently turns
    the sustained create+delete shape back into a fill-up.  Skipped
    indices stay pending and are retried on every advance.
    """

    def __init__(self, coord, key_strs, start: int = 1):
        self._coord = coord
        self._key_strs = key_strs
        self._at = start
        self._pending: list[int] = []

    def advance(self, frontier: int) -> list[int]:
        """Bound indices in [previous, frontier) plus previously-skipped
        ones that have bound since; the rest stay pending."""
        if frontier > self._at:
            self._pending.extend(range(self._at, frontier))
            self._at = frontier
        bound = self._coord._bound
        ks = self._key_strs
        dels = [i for i in self._pending if ks[i] in bound]
        if dels:
            hit = set(dels)
            self._pending = [i for i in self._pending if i not in hit]
        return dels


def _start_watch_stress(target: str, watchers: int, write_concurrency: int):
    """Spawn the apiserver-stress equivalent against ``target`` for the
    duration of the bench window (terminated by the caller)."""
    import atexit
    import sys

    proc = subprocess.Popen(
        [
            sys.executable, "-m", "k8s1m_tpu.tools.watch_stress",
            "--target", target, "--watchers", str(watchers),
            "--write-concurrency", str(write_concurrency),
            "--writes", str(1 << 30), "--quiet",
        ],
        stdout=subprocess.DEVNULL,
    )
    atexit.register(lambda: proc.poll() is None and proc.kill())
    return proc


def main(argv=None):
    from k8s1m_tpu.obs.profiler import install_signal_dump

    # Always-on on-demand stack dump (SIGUSR2 -> /tmp/stacks-<pid>.txt),
    # the py-spy-dump role: a long run that stops progressing can be
    # interrogated without being killed.
    install_signal_dump()
    args = parse_args(argv)
    if args.backend == "auto":
        # The fused kernel is only a win compiled on real TPU silicon;
        # everywhere else it runs interpreted (orders of magnitude
        # slower), so auto picks the XLA scan path off-TPU.
        import jax

        args.backend = "pallas" if jax.default_backend() == "tpu" else "xla"
    if args.chunk is None:
        args.chunk = (1 << 12) if args.backend == "pallas" else (1 << 14)
    if args.stress_watchers and not args.target:
        raise SystemExit("--stress-watchers requires --target (wire store)")
    from k8s1m_tpu import faultline

    if args.fault_plan:
        faultline.install_plan(faultline.FaultPlan.from_arg(args.fault_plan))

    if args.target:
        from k8s1m_tpu.store.remote import RemoteStore

        store = RemoteStore(
            args.target,
            ca_pem=getattr(args, 'ca_pem', None),
            token=getattr(args, 'token', None),
        )
    else:
        store = MemStore()

    t0 = time.perf_counter()
    items = []
    for i in range(args.nodes):
        items.append((node_key(f"kwok-node-{i}"), encode_node(build_node(i))))
        if len(items) == 8192:
            write_wave(store, items)
            items.clear()
    if items:
        write_wave(store, items)
    nodes_s = time.perf_counter() - t0

    cap = 1 << max(10, (args.nodes - 1).bit_length())
    # The chunked scan needs chunk <= table rows (both powers of two
    # here); the per-backend default assumes a big table.
    args.chunk = min(args.chunk, cap)
    from k8s1m_tpu.parallel import resolve_mesh

    # One resolve here (explicit --mesh, or K8S1M_MESH when unset) so
    # the chunk clamp below applies however the mesh was selected, and
    # an explicit `--mesh none` really opts out even under a rig env
    # that exports K8S1M_MESH.
    mesh = resolve_mesh(
        args.mesh, batch=args.batch, max_nodes=cap, chunk=args.chunk
    )
    if mesh is not None:
        # The chunked scan runs per shard; clamp to the shard's rows.
        args.chunk = min(args.chunk, cap // mesh.shape["sp"])
    # Template-shaped pods (--shape-pool) do real per-(pod, node)
    # selector work, so the affinity plugin is live for them — the
    # regime the delta-plane cache collapses.  Plain pods keep the
    # committed-baseline profile (affinity would contribute zeros).
    profile = (
        Profile(topology_spread=0, interpod_affinity=0)
        if args.shape_pool
        else Profile(node_affinity=0, topology_spread=0, interpod_affinity=0)
    )
    tracer = None
    if args.trace:
        from k8s1m_tpu.obs.podtrace import PodTracer

        tracer = PodTracer(sample_n=args.trace)
    coord = Coordinator(
        store, TableSpec(max_nodes=cap), PodSpec(batch=args.batch),
        profile, chunk=args.chunk, with_constraints=False,
        backend=args.backend, pipeline=not args.no_pipeline, depth=args.depth,
        score_pct=args.score_pct, adaptive_batch=bool(args.rate),
        # Already resolved above (env included): a built Mesh, or
        # "none" so the Coordinator does NOT re-read K8S1M_MESH.
        mesh=mesh if mesh is not None else "none",
        packing=args.packing,
        deltacache=args.deltacache,
        delta_index_k=args.delta_index_k,
        stratum_bits=args.stratum_bits,
        tracer=tracer,
    )
    t0 = time.perf_counter()
    coord.bootstrap()
    bootstrap_s = time.perf_counter() - t0

    # Pre-encode pod values (the writer's cost, not the scheduler's).
    # With --tenants the population spreads over tenant namespaces
    # (zipf sizes, scheduled mix) — emission is in index order, so the
    # paced producer below turns the index axis into arrival time.
    if args.tenants > 0:
        from k8s1m_tpu.cluster.workload import tenant_assignments

        tenant_ids = tenant_assignments(
            args.pods, args.tenants, skew=args.tenant_skew,
            seed=args.seed, schedule=args.tenant_schedule,
        )
        namespaces = [f"tenant-{t}" for t in tenant_ids]
    else:
        namespaces = ["default"] * args.pods
    shape_templates = []
    if args.shape_pool:
        # Deployment-template shapes doing real per-(pod, node) selector
        # work against the KWOK zone/region labels: a required In over
        # two zones + a region NotIn, plus a preferred zone — the
        # node_affinity_pods structure (sized to build_node's 8 zones /
        # 4 regions), made key-distinct beyond the 8 structural combos
        # by the request scalar the shape key also covers.  Pods draw
        # from a HOT pool of N specs or, for the (1 - share) slice, a
        # bounded 4N-spec tail — real pools' tails repeat too
        # (hotfeed's hit rate is 1.0 at 90%-hot pools,
        # artifacts/hostpath_bench.json).
        from k8s1m_tpu.cluster.workload import node_affinity_pods

        pool = node_affinity_pods(5 * args.shape_pool, zones=8, regions=4)
        for j, t in enumerate(pool):
            t.cpu_milli = 10 + j
            shape_templates.append(t)

    def bench_pod(i: int) -> PodInfo:
        p = PodInfo(
            f"bench-{i}", namespace=namespaces[i],
            cpu_milli=10, mem_kib=1024,
        )
        if args.shape_cold:
            # Every pod its own shape (the key includes the request
            # scalars): identical device work, zero possible cache hits
            # — isolates the deltasched host overhead.
            p.cpu_milli = 10 + i
            return p
        if shape_templates:
            import random as _random

            rng = _random.Random((args.seed << 20) | i)
            hot = rng.random() < args.shape_share
            j = (
                rng.randrange(args.shape_pool) if hot
                else args.shape_pool + rng.randrange(4 * args.shape_pool)
            )
            t = shape_templates[j]
            p.cpu_milli = t.cpu_milli
            p.required_terms = t.required_terms
            p.preferred_terms = t.preferred_terms
        return p

    values = [encode_pod(bench_pod(i)) for i in range(args.pods)]
    keys = [
        pod_key(namespaces[i], f"bench-{i}") for i in range(args.pods)
    ]
    key_strs = [f"{namespaces[i]}/bench-{i}" for i in range(args.pods)]

    # Warm the compile cache outside the measured window.
    store.put(keys[0], values[0])
    while coord.run_until_idle() == 0:
        pass
    if args.churn:
        # Churn also exercises the dirty-row scatter (delete -> row
        # re-upload) at full wave-sized buckets; compile those now too.
        wk = [pod_key("warm", f"w-{i}") for i in range(4096)]
        write_wave(store, [
            (k, encode_pod(PodInfo(f"w-{i}", cpu_milli=1, mem_kib=1)))
            for i, k in enumerate(wk)
        ])
        coord.run_until_idle()
        write_wave(store, [(k, None) for k in wk])
        coord.run_until_idle()

    # Producer interleaved with scheduling, like make_pods running against
    # a live scheduler; wave pacing keeps the 10K-deep watch buffer from
    # overflowing (the reference's webhook intake exists for the same
    # burst-arrival reason, README.adoc:684-695).  Interleaved, not
    # threaded: on a single-core host a producer thread only adds GIL
    # contention and queue backlog.
    from k8s1m_tpu.obs.metrics import REGISTRY, quantile_report_ms

    if args.rate:
        # Warm the adaptive buckets the paced run will actually use
        # (each bucket is its own compiled executable).
        # Every bucket must be compiled up front: a mid-run compile stall
        # (tens of seconds) while the queue is growing destroys the tail.
        b = coord.min_batch
        warm = {coord.pod_spec.batch}   # overload bucket (may be non-pow2)
        while b <= coord.pod_spec.batch:
            warm.add(b)
            b <<= 1
        woff = 0
        for b in sorted(warm):
            ks = [pod_key("warm2", f"r-{woff+i}") for i in range(b)]
            vs = [encode_pod(PodInfo(f"r-{woff+i}", cpu_milli=1, mem_kib=1))
                  for i in range(b)]
            woff += b
            write_wave(store, list(zip(ks, vs)))
            coord.run_until_idle()
        # Over a remote target the warm pods' watch events may still be
        # in flight when run_until_idle sees an empty queue — any warm
        # pod binding INSIDE the measured window inflates binds/s.
        # Drain until the whole warm population is accounted for.
        warm_deadline = time.perf_counter() + 30.0
        while (
            sum(1 for k in coord._bound if k.startswith("warm2/")) < woff
            and time.perf_counter() < warm_deadline
        ):
            coord.run_until_idle()
            time.sleep(0.05)
        REGISTRY.get("coordinator_schedule_to_bind_seconds").reset()
        if args.stats:
            REGISTRY.get("coordinator_cycle_seconds").reset()
        tune_gc()

        # Paced producer: emit pods on the offered-load schedule, step
        # the coordinator continuously, measure intake-to-bind latency.
        # --churn deletes BOUND pods a lag behind the emission point
        # (config 5's sustained create+delete shape at a rate); the lag
        # is capped at a quarter of the run so short runs still delete.
        lag = min(3 * coord.pod_spec.batch, max(args.pods // 4, 64))
        quiesce_base, overlap_base, depth_samples, node_churn = (
            _pipeline_window_start(coord, store, args)
        )
        feed_depth_samples: list = []
        t0 = time.perf_counter()
        bound = 0
        emitted = 1
        churn = _ChurnFrontier(coord, key_strs)
        deleted = 0
        with _bench_window(args, coord, store):
            while (
                emitted < args.pods or coord.queue or coord._inflights
                or coord._backoff
            ):
                due = min(
                    args.pods,
                    1 + int(offered_pods_at(args, time.perf_counter() - t0)),
                )
                if due > emitted:
                    write_wave(
                        store, list(zip(keys[emitted:due], values[emitted:due]))
                    )
                    emitted = due
                if node_churn is not None:
                    node_churn.advance(time.perf_counter() - t0)
                if args.churn:
                    # Advance on EVERY cycle, not only on emission: when
                    # binds lag the producer (CPU), most land after
                    # emission finished, and a frontier advanced only on
                    # emission would leave them pending forever —
                    # config 5 is a sustained create+DELETE shape, so
                    # deletions must keep executing through the drain.
                    dels = churn.advance(emitted - lag)
                    if dels:
                        write_wave(store, [(keys[i], None) for i in dels])
                        deleted += len(dels)
                bound += coord.step()
                if emitted < args.pods:
                    # Depth evidence only while the producer is live —
                    # the tail drain legitimately winds the pipeline down.
                    depth_samples.append(len(coord._inflights))
                    _sample_mesh_feed(coord, feed_depth_samples)
                if (
                    emitted >= args.pods
                    and not coord.queue
                    and not coord._inflights
                    and not coord._backoff
                ):
                    bound += coord.run_until_idle()
                    if args.churn:
                        dels = churn.advance(emitted - lag)
                        if dels:
                            write_wave(
                                store, [(keys[i], None) for i in dels]
                            )
                            deleted += len(dels)
                    break
            sched_s = time.perf_counter() - t0
            lat = REGISTRY.get("coordinator_schedule_to_bind_seconds")
        e2e = bound / sched_s if sched_s else 0.0
        if args.stats:
            _print_stage_stats(sched_s)
        q = quantile_report_ms(lat)
        return _emit_report({
            "metric": f"e2e_p50_bind_ms_{args.nodes}_nodes_at_{args.rate}",
            "value": q["p50_ms"],
            "unit": "ms",
            "vs_baseline": None,
            "detail": {
                "rate": args.rate,
                "mesh": args.mesh,
                "backend": args.backend,
                "score_pct": args.score_pct,
                "overload": (
                    {"at_s": args.overload_at,
                     "seconds": args.overload_seconds,
                     "factor": args.overload_factor}
                    if args.overload_at else None
                ),
                "binds_per_sec": round(e2e, 1),
                "bound": bound,
                "unbound": args.pods - 1 - bound,
                "deleted": deleted,
                "stress_watchers": args.stress_watchers,
                **q,
                **_pipeline_detail(
                    coord, quiesce_base, overlap_base, depth_samples,
                    node_churn,
                ),
                **_mesh_detail(coord, feed_depth_samples),
                **_tenant_detail(args),
                **_trace_detail(args, tracer),
                **_encode_profile_detail(args.encode_profile),
                **_delta_profile_detail(args, coord),
                **_device_state_detail(coord),
                **_kernel_profile_detail(args, coord),
                **_resilience_detail(),
            },
        }, args.out)

    wave = args.batch
    if args.stats:
        REGISTRY.get("coordinator_cycle_seconds").reset()
    tune_gc()
    quiesce_base, overlap_base, depth_samples, node_churn = (
        _pipeline_window_start(coord, store, args)
    )
    feed_depth_samples: list = []
    t0 = time.perf_counter()
    bound = 0
    off = 1
    deleted = 0
    churn = _ChurnFrontier(coord, key_strs)
    with _bench_window(args, coord, store):
        while off < args.pods:
            write_wave(
                store, list(zip(keys[off:off + wave], values[off:off + wave]))
            )
            if node_churn is not None:
                node_churn.advance(time.perf_counter() - t0)
            if args.churn:
                # Delete BOUND pods behind the emission lag — the
                # scheduler keeps binding into capacity that deletions
                # keep freeing; pods not yet bound stay pending in the
                # frontier and are deleted once they bind.
                dels = churn.advance(off - 2 * wave)
                write_wave(store, [(keys[i], None) for i in dels])
                deleted += len(dels)
            off += wave
            bound += coord.step()
            if off < args.pods:
                depth_samples.append(len(coord._inflights))
                _sample_mesh_feed(coord, feed_depth_samples)
        if args.churn:
            # Drain with the frontier still advancing (same lag): on CPU
            # most binds land here, after the producer finished, and the
            # sustained-delete shape must hold through the drain.
            # Cycle-bounded like run_until_idle: unschedulable pods
            # retry forever and would otherwise spin this loop forever.
            idle = 0
            for _ in range(10_000):
                n = coord.step()
                bound += n
                dels = churn.advance(args.pods - 2 * wave)
                if dels:
                    write_wave(store, [(keys[i], None) for i in dels])
                    deleted += len(dels)
                if not coord.queue and not coord._inflights and not coord._backoff:
                    idle += 1
                    if idle > 1 and coord.drain_watches() == 0:
                        break
                else:
                    idle = 0
            bound += coord.flush()
        else:
            bound += coord.run_until_idle()
        sched_s = time.perf_counter() - t0
    create_s = sched_s  # creation is inside the measured window
    e2e = bound / sched_s if sched_s else 0.0

    lat = REGISTRY.get("coordinator_schedule_to_bind_seconds")
    p50_ms = quantile_report_ms(lat, (0.5,))["p50_ms"] if lat else None

    if args.stats:
        _print_stage_stats(sched_s)

    suffix = f"_pct{args.score_pct}" if args.score_pct != 100 else ""
    return _emit_report({
        "metric": f"e2e_binds_per_sec_{args.nodes}_nodes{suffix}",
        "value": round(e2e, 1),
        "unit": "binds/s",
        "vs_baseline": round(e2e / REFERENCE_E2E, 3),
        "detail": {
            "score_pct": args.score_pct,
            "mesh": args.mesh,
            "backend": args.backend,
            "pods": args.pods,
            "bound": bound,
            "deleted": deleted,
            "node_create_s": round(nodes_s, 2),
            "bootstrap_s": round(bootstrap_s, 2),
            "pod_create_per_sec": round(args.pods / create_s, 1),
            "schedule_s": round(sched_s, 2),
            "stress_watchers": args.stress_watchers,
            "p50_bind_ms": p50_ms,
            **_pipeline_detail(
                coord, quiesce_base, overlap_base, depth_samples, node_churn,
            ),
            **_mesh_detail(coord, feed_depth_samples),
            **_tenant_detail(args),
            **_trace_detail(args, tracer),
            **_encode_profile_detail(args.encode_profile),
            **_delta_profile_detail(args, coord),
            **_device_state_detail(coord),
            **_kernel_profile_detail(args, coord),
            **_resilience_detail(),
        },
    }, args.out)


if __name__ == "__main__":
    main()
