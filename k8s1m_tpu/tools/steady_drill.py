"""benchtrue part 2: the composed steady-state drill.

Every subsystem has its own proof — hotfeed's encode overlap
(hostpath_bench), pipedream's quiesce-free churn (churn_pipeline),
loadshed's shed-and-recover (overload_drill), faultline's
injected-fault recovery (soak_faultline), tenancy's weighted-fair
shares (tenantfair_drill).  This drill proves them **together**, at
steady state, in one tick-driven run:

- the coordinator runs the production shape: ``pipeline=True`` depth 3
  with the host feed staging batches behind in-flight waves;
- a **tenant-aware producer** (zipf-skewed tenant namespaces,
  cluster/workload.py) submits through the weighted-fair admission
  chain every tick;
- **capacity-only node churn** lands every tick — the pipeline must
  scatter it mid-flight without a single structural quiesce;
- a **faultline plan** forces bind-CAS conflicts on a deterministic
  cadence — every one must be absorbed by the shared RetryPolicy with
  zero give-ups;
- mid-run the producer steps to ``--factor`` x capacity (the
  **loadshed overload phase**): the controller must walk to SHEDDING,
  per-tenant buckets must shed the flooders, and recovery must walk
  back to HEALTHY once the rate drops.

Gates (one JSON line; full evidence in ``--out``): zero admitted pods
lost, zero structural/resync quiesces, sustained in-flight depth at the
configured 3, SHEDDING seen and HEALTHY recovered, every injected
fault retried with zero give-ups, and the host feed actually staging
(``staged_used`` grew) — the individually-proven subsystems proven
*simultaneously*.

**benchtrue part 3** (``--mesh DPxSP``): the same composed shape over
the dp x sp sharded cycle — the table's rows shard over ``sp`` devices
and the pod batch over ``dp`` (parallel/sharded_cycle), with the
per-dp-shard host feed staging behind in-flight sharded waves.  Since
meshpack the mesh drill defaults to ``--packing packed``, so the gates
cover the full production composition (packed planes sharded over sp,
donating sharded step/scatter) and additionally assert
``device_packing_fallback_total`` stayed zero over the window.  Run on
CPU with the virtual device mesh::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python -m k8s1m_tpu.tools.steady_drill --smoke --mesh 2x4

    python -m k8s1m_tpu.tools.steady_drill --smoke \
        --out artifacts/steady_state_drill.json

**The failover lane** (``--failover``, ISSUE 15: the failover drill's
kill scenarios folded into the composed drill — the benchtrue-part-3
remainder): the coordinator runs as an HA pair (alpha leading, beta a
warm standby following the watch stream), the watch-cache TIER runs
over the same store (native wire front, one client watch on the pods
prefix) on a sidecar loop, and the installed fault plan lands BOTH
storm legs mid-drill: a ``kill_process`` SIGKILLs alpha late in the
overload phase (beta must take over on lease expiry and drain
everything — still 0 lost), and an upstream watch break hits the tier
(which must RESUME its client in place: resumes +1, invalidations 0,
zero client cancels).  Composes with ``--mesh``/``--packing``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import threading
import time

IDLE_DRAIN_TICKS = 4000


class _WatchTierLane:
    """The composed lane's watch-tier leg: the fan-out tier over the
    SAME store (served through a native wire front), with one client
    watch on the pods prefix counting deliveries, on a private asyncio
    loop in a worker thread.  The installed fault plan breaks its
    upstream stream mid-drill; the lane's gates are a diff-replay
    resume (client kept, ``watchcache_resumes_total`` +1, zero
    invalidations) and zero client cancels."""

    def __init__(self, store):
        self.events = 0
        self.cancels = 0
        self.errors = 0
        self._stop = False
        self._store = store
        self._ready = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="watch-tier-lane", daemon=True
        )
        self._thread.start()
        if not self._ready.wait(timeout=60):
            raise RuntimeError("watch-tier lane failed to come up")

    def _run(self) -> None:
        asyncio.run(self._main())

    async def _main(self) -> None:
        from k8s1m_tpu.control.coordinator import PODS_PREFIX
        from k8s1m_tpu.store.etcd_client import EtcdClient
        from k8s1m_tpu.store.native import WireFront, prefix_end
        from k8s1m_tpu.store.watch_cache import serve_watch_cache

        wf = WireFront(self._store)
        tier = await serve_watch_cache(
            f"127.0.0.1:{wf.port}", [PODS_PREFIX], port=0
        )
        client = EtcdClient(f"127.0.0.1:{tier.port}")
        s = client.watch(PODS_PREFIX, prefix_end(PODS_PREFIX))
        await s.__aenter__()
        self._ready.set()
        try:
            while not self._stop:
                try:
                    b = await s.next(timeout=0.2)
                except asyncio.TimeoutError:
                    continue
                # Counted, not logged: errors fail the lane's gate.
                except Exception:  # graftlint: disable=broad-except
                    self.errors += 1
                    break
                if b.canceled:
                    # The cancel-everyone hammer reached the client:
                    # exactly what the resume path must prevent.
                    self.cancels += 1
                    break
                self.events += len(b.events)
            await s.cancel()
        finally:
            await client.close()
            await tier.close()
            wf.close()

    def stop(self) -> None:
        self._stop = True
        self._thread.join(timeout=30)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="composed steady-state drill")
    ap.add_argument("--nodes", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument("--depth", type=int, default=3)
    ap.add_argument("--tenants", type=int, default=6)
    ap.add_argument("--tenant-skew", type=float, default=1.0)
    ap.add_argument("--steady-ticks", type=int, default=24)
    ap.add_argument("--overload-ticks", type=int, default=16)
    ap.add_argument("--recover-ticks", type=int, default=60)
    ap.add_argument("--factor", type=int, default=5)
    ap.add_argument("--churn-per-tick", type=int, default=64,
                    help="capacity-only node updates written per tick")
    ap.add_argument("--conflict-every", type=int, default=37,
                    help="faultline: force a bind-CAS conflict every Nth "
                    "CAS attempt")
    ap.add_argument("--seed", type=int, default=5)
    ap.add_argument("--mesh", default=None,
                    help="run the composed drill over the dp x sp "
                    "sharded cycle (benchtrue part 3), e.g. '2x4' on "
                    "the 8-device CPU mesh; default: single-device.  "
                    "A mesh drill defaults --packing to 'packed' so the "
                    "composed packed x sharded x donated production "
                    "path is what the gates exercise")
    ap.add_argument("--packing", choices=("off", "packed"), default=None,
                    help="device-snapshot layout (snapshot/packing.py); "
                    "default: 'packed' when --mesh is set (the meshpack "
                    "production path), else 'off'.  A packed drill "
                    "additionally gates device_packing_fallback_total "
                    "== 0 over the window")
    ap.add_argument("--failover", action="store_true",
                    help="compose the failover-drill kill scenarios "
                    "into this run: HA coordinator pair with a "
                    "mid-overload SIGKILL of the leader (warm standby "
                    "takes over, still 0 lost) plus a watch-cache tier "
                    "sidecar whose upstream stream is broken mid-drill "
                    "(must resume, not relist-storm)")
    ap.add_argument("--trace", type=int, default=0, metavar="N",
                    help="podtrace (obs/podtrace.py): trace 1-in-N "
                    "pods through the composed drill; the stage-"
                    "attribution waterfall lands in the evidence as "
                    "latency_attribution.  0 = off (the null tracer)")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="with --trace: write the Chrome/Perfetto "
                    "trace-event export of the drill to PATH")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 shape: tiny cluster, same gates")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.smoke:
        args.nodes, args.batch, args.chunk = 128, 64, 64
        args.tenants = 4
        args.steady_ticks, args.overload_ticks = 8, 8
        args.recover_ticks = 40
        args.churn_per_tick = 16
        if args.mesh:
            # Mesh divisibility at smoke scale: rows-per-sp-shard must
            # be a chunk multiple (256/4 = 64, chunk 32).
            args.nodes, args.chunk = 256, 32
    if args.trace_out and not args.trace:
        ap.error("--trace-out requires --trace (the pod tracer)")
    if args.packing is None:
        # Same resolution chain as every other entry point: an explicit
        # K8S1M_PACKING keeps the whole evidence pipeline on one layout
        # (resolve_packing also rejects typo'd values loudly).  Only
        # when the env var is ALSO unset does the mesh drill default to
        # the composed production path — packed x sharded x donated
        # gated together (meshpack).
        if os.environ.get("K8S1M_PACKING") is not None:
            from k8s1m_tpu.snapshot.packing import resolve_packing

            args.packing = resolve_packing(None)
        else:
            args.packing = "packed" if args.mesh else "off"
    return args


def run(args) -> dict:
    from k8s1m_tpu import faultline
    from k8s1m_tpu.cluster.workload import zipf_weights
    from k8s1m_tpu.config import PodSpec, TableSpec
    from k8s1m_tpu.control.coordinator import Coordinator
    from k8s1m_tpu.control.objects import (
        encode_node,
        encode_pod,
        node_key,
        pod_key,
    )
    from k8s1m_tpu.faultline import FaultPlan, FaultSpec, install_plan
    from k8s1m_tpu.loadshed import (
        HEALTHY,
        SHEDDING,
        STATE_NAMES,
        LoadshedConfig,
        Overloaded,
    )
    from k8s1m_tpu.obs.metrics import REGISTRY
    from k8s1m_tpu.plugins.registry import Profile
    from k8s1m_tpu.snapshot.node_table import NodeInfo
    from k8s1m_tpu.snapshot.pod_encoding import PodInfo
    from k8s1m_tpu.store.native import MemStore
    from k8s1m_tpu.store import watch_cache as _wc  # noqa: F401  (register watchcache_* metrics for the failover lane's deltas)
    from k8s1m_tpu.tenancy import TenancyController, TenancyPolicy

    b = args.batch
    z = zipf_weights(args.tenants, args.tenant_skew)
    weights = {
        f"tenant-{t}": max(1, round(z[t] / z[-1]))
        for t in range(args.tenants)
    }
    tenants = list(weights)
    total_w = sum(weights.values())
    cfg = LoadshedConfig(
        queue_degraded=3 * b, queue_shed=6 * b, queue_cap=64 * b,
        queue_recover=b, recover_cycles=3,
    )
    controllers: list = []

    def make_tn():
        tn = TenancyController(
            TenancyPolicy(weights=weights), loadshed_config=cfg,
            name=f"steady_drill-{len(controllers)}",
        )
        controllers.append(tn)
        return tn

    specs = [FaultSpec("coordinator.bind", "cas", kind="err5xx",
                       every_n=args.conflict_every)]
    # The failover lane's two storm legs, by schedule: SIGKILL alpha on
    # its lease tick 3/4 into the overload phase (counters start at
    # install, after warmup), and break the tier's upstream stream at
    # its 31st post-install batch.
    kill_tick = args.steady_ticks + (3 * args.overload_ticks) // 4
    if args.failover:
        specs += [
            FaultSpec("coordinator.lease", "tick/alpha",
                      kind="kill_process", after=kill_tick, every_n=1,
                      max_fires=1),
            FaultSpec("watch.tier", "upstream.recv", kind="disconnect",
                      after=30, every_n=1, max_fires=1),
        ]
    plan = FaultPlan(specs, seed=args.seed)

    quiesce = REGISTRY.get("pipeline_quiesce_total")
    q0 = {r: quiesce.value(reason=r) for r in ("structural", "resync")}
    staged0 = REGISTRY.get("hotfeed_staged_used_total").value()
    mesh_scatter = REGISTRY.get("mesh_sharded_scatter_total")
    ms0 = {c: mesh_scatter.value(cols=c) for c in ("full", "cap")}
    giveups = REGISTRY.get("retry_give_ups_total")
    giveup0 = giveups.value(component="coordinator.bind")
    from k8s1m_tpu.snapshot.packing import FALLBACK_REASONS

    pack_fb = REGISTRY.get("device_packing_fallback_total")
    fb0 = {r: pack_fb.value(reason=r) for r in FALLBACK_REASONS}
    wc_resumes = REGISTRY.get("watchcache_resumes_total")
    wc_invals = REGISTRY.get("watchcache_invalidations_total")
    wr0, wi0 = wc_resumes.value(), wc_invals.value()

    store = MemStore()

    def node_bytes(i: int, gen: int) -> bytes:
        # pods stays inside the packed int16 plane (snapshot/packing.py)
        # — the old 1<<20 "never the binding constraint" value would
        # fail-closed every packed drill to unpacked at bootstrap, which
        # is exactly the fallback the packed gate asserts never fires.
        return encode_node(NodeInfo(
            name=f"n{i:05d}", cpu_milli=1 << 22 if gen < 0 else
            (1 << 22) + (gen % 16), mem_kib=1 << 30, pods=(1 << 15) - 1,
        ))

    for i in range(args.nodes):
        store.put(node_key(f"n{i:05d}"), node_bytes(i, -1))
    tracer = None
    if args.trace:
        from k8s1m_tpu.obs.podtrace import PodTracer

        tracer = PodTracer(sample_n=args.trace)

    def make_coord():
        return Coordinator(
            store,
            TableSpec(max_nodes=args.nodes, max_zones=16, max_regions=8),
            PodSpec(batch=b), Profile(topology_spread=0, interpod_affinity=0),
            chunk=args.chunk, k=4, with_constraints=False, seed=args.seed,
            score_pct=50, pipeline=True, depth=args.depth, tenancy=make_tn(),
            mesh=args.mesh or "none", packing=args.packing, tracer=tracer,
        )

    alpha = beta = coord = None
    if args.failover:
        from k8s1m_tpu.control.leader import HACoordinator, LeaderElector

        alpha = HACoordinator(LeaderElector(store, "alpha"), make_coord)
        beta = HACoordinator(
            LeaderElector(store, "beta", retry_period_s=1.0),
            make_coord, warm_standby=True,
        )
    else:
        coord = make_coord()

    now = 0.0

    def active_coord():
        """The live scheduling coordinator (post-kill: the standby's)."""
        if not args.failover:
            return coord
        if alpha.elector.is_leader and not alpha._killed:
            return alpha.coord
        return beta.coord

    def step_once() -> None:
        nonlocal now
        if not args.failover:
            coord.step()
            return
        now += 1.0
        if not alpha._killed:
            alpha.tick(now)
        beta.tick(now)

    seq = 0
    churned = 0
    admitted: list[tuple[str, str]] = []
    rejected = 0
    states_seen: set[int] = set()
    depth_samples: list[int] = []
    recovered_at = None

    def submit(n: int) -> None:
        nonlocal seq, rejected
        lanes = []
        for t in tenants:
            share = max(1, round(n * weights[t] / total_w))
            lanes += [(k / share, t) for k in range(share)]
        lanes.sort()
        for _, t in lanes:
            seq += 1
            pod = PodInfo(f"p{seq:07d}", namespace=t,
                          cpu_milli=10, mem_kib=1 << 10)
            obj = json.loads(encode_pod(pod))
            try:
                if args.failover:
                    # The live replica's sink (queue-or-429 while no
                    # leader holds the lease).
                    ha = alpha if (
                        alpha.elector.is_leader and not alpha._killed
                    ) else beta
                    ha.submit_external(obj)
                else:
                    coord.submit_external(obj)
            except Overloaded:
                rejected += 1
                continue
            store.put(pod_key(t, pod.name), encode_pod(pod))
            admitted.append((t, pod.name))

    def churn_tick() -> None:
        nonlocal churned
        for j in range(args.churn_per_tick):
            i = churned % args.nodes
            store.put(node_key(f"n{i:05d}"), node_bytes(i, churned))
            churned += 1

    def tick(phase: str, n: int, producing: bool) -> None:
        submit(n)
        churn_tick()
        step_once()
        c = active_coord()
        if c is not None:
            states_seen.add(c.tenancy.controller.current_state())
        if producing:
            depth_samples.append(
                len(c._inflights) if c is not None else 0
            )

    lane = _WatchTierLane(store) if args.failover else None
    try:
        if args.failover:
            now += 1.0
            alpha.tick(now)      # alpha cold-boots and leads
            assert alpha.elector.is_leader
        else:
            coord.bootstrap()
        # Warm the compile caches outside the gated window.
        submit(b)
        if args.failover:
            for _ in range(IDLE_DRAIN_TICKS):
                c = active_coord()
                if c is not None and (
                    not c.queue and not c._backoff
                    and not c._external_pending() and not c._inflights
                ):
                    break
                step_once()
                w = c.backoff_wait_s() if c is not None else 0
                if w:
                    time.sleep(min(w, 0.05))
        else:
            coord.run_until_idle()
        install_plan(plan)
        for _ in range(args.steady_ticks):
            tick("steady", b, True)
        for _ in range(args.overload_ticks):
            tick("overload", args.factor * b, True)
        for t in range(args.recover_ticks):
            tick("recovery", b // 2, False)
            c = active_coord()
            if (
                c is not None
                and c.tenancy.controller.current_state() == HEALTHY
                and recovered_at is None
            ):
                recovered_at = t + 1
        for dt in range(IDLE_DRAIN_TICKS):
            c = active_coord()
            if c is not None and (
                not c.queue and not c._backoff
                and not c._external_pending() and not c._inflights
            ):
                break
            step_once()
            if c is not None:
                # A mid-overload leader kill pushes the takeover
                # backlog past the recovery window; the autonomous
                # walk-back to HEALTHY is still the gate — it just
                # completes during the drain.
                if (
                    args.failover and recovered_at is None
                    and c.tenancy.controller.current_state() == HEALTHY
                ):
                    recovered_at = args.recover_ticks + dt + 1
                w = c.backoff_wait_s()
                if w:
                    time.sleep(min(w, 0.05))
        c = active_coord()
        if c is not None:
            c.flush()
        fired = faultline.active_injector().fire_counts()
        install_plan(None)
        # Leadership read BEFORE the finally's stop() releases the
        # lease (a post-stop read is always False).
        beta_led = bool(args.failover and beta.elector.is_leader)
        lost = 0
        for t, name in admitted:
            kv = store.get(pod_key(t, name))
            if kv is None or b'"nodeName"' not in kv.value:
                lost += 1
        counters = {"admitted": {}, "rejected": {}}
        for tn in controllers:
            for side, per in tn.admission.counters().items():
                if side not in counters:
                    continue
                for tenant, v in per.items():
                    counters[side][tenant] = (
                        counters[side].get(tenant, 0) + v
                    )
    finally:
        install_plan(None)
        if lane is not None:
            lane.stop()
        if args.failover:
            for ha in (alpha, beta):
                try:
                    ha.stop()
                except Exception:  # graftlint: disable=broad-except (drill teardown must reach store.close)
                    pass
        else:
            coord.close()
        store.close()

    import numpy as np

    samples = np.asarray(depth_samples or [0])
    qd = {r: int(quiesce.value(reason=r) - q0[r]) for r in q0}
    staged_used = int(
        REGISTRY.get("hotfeed_staged_used_total").value() - staged0
    )
    give_ups = giveups.value(component="coordinator.bind") - giveup0
    faults = sum(fired.values()) if fired else 0
    mesh_scatters = {
        c: int(mesh_scatter.value(cols=c) - ms0[c]) for c in ms0
    }
    packing_fallbacks = sum(
        int(pack_fb.value(reason=r) - fb0[r]) for r in fb0
    )
    from k8s1m_tpu.obs.podtrace import trace_report_detail

    trace_detail = trace_report_detail(tracer, args.trace_out)
    failover_ev = None
    failover_ok = True
    if args.failover:
        resumes_d = int(wc_resumes.value() - wr0)
        invals_d = int(wc_invals.value() - wi0)
        failover_ev = {
            "kill_fired": fired.get("kill_process", 0),
            "kill_after_tick": kill_tick,
            "beta_leader": beta_led,
            "takeover_mode": beta.takeover_mode,
            "recovery_s": beta.last_recovery_s,
            "watch_tier": {
                "events": lane.events,
                "client_cancels": lane.cancels,
                "client_errors": lane.errors,
                "resumes": resumes_d,
                "invalidations": invals_d,
            },
        }
        # The lane's gates: the SIGKILL actually fired and the warm
        # standby leads; the tier's upstream break resolved by resume
        # (client watch kept — zero cancels/invalidations) and the
        # sidecar actually observed traffic.
        failover_ok = bool(
            failover_ev["kill_fired"] == 1
            and failover_ev["beta_leader"]
            and resumes_d >= 1
            and invals_d == 0
            and lane.cancels == 0
            and lane.errors == 0
            and lane.events > 0
        )
    return {
        "weights": weights,
        "mesh": args.mesh,
        "packing": args.packing,
        "failover": failover_ev,
        **trace_detail,
        "packing_fallbacks": packing_fallbacks,
        "mesh_sharded_scatters": mesh_scatters,
        "admitted": len(admitted),
        "rejected": rejected,
        "admitted_by_tenant": counters["admitted"],
        "lost": lost,
        "states_seen": sorted(STATE_NAMES[s] for s in states_seen),
        "recovered_at_tick": recovered_at,
        "node_churn_events": churned,
        "pipeline_quiesce": qd,
        "sustained_inflight_depth": int(np.median(samples)),
        "max_inflight_depth": int(samples.max()),
        "hotfeed_staged_used": staged_used,
        "faults_injected": faults,
        "retry_give_ups": int(give_ups),
        "passed": bool(
            lost == 0
            and qd["structural"] == 0
            and qd["resync"] == 0
            and int(np.median(samples)) >= args.depth
            and SHEDDING in states_seen
            and recovered_at is not None
            and faults > 0
            and give_ups == 0
            and staged_used > 0
            # Mesh lane (benchtrue part 3): the capacity churn must
            # actually have flowed through the sharded mid-flight
            # scatter, not a fallen-back single-device path.
            and (not args.mesh or mesh_scatters["cap"] > 0)
            # Packed lane (meshpack): the composed window must hold the
            # packed layout end to end — zero fail-closed rebuilds.
            and (args.packing != "packed" or packing_fallbacks == 0)
            # Failover lane (watchplane): leader SIGKILL absorbed by
            # the warm standby AND the tier's upstream break absorbed
            # by resume, inside the same composed window.
            and failover_ok
        ),
    }


def main(argv=None) -> dict:
    args = parse_args(argv)
    evidence = run(args)
    result = {
        "metric": "steady_state_drill"
        + ("_mesh" if args.mesh else "")
        + ("_failover" if args.failover else "")
        + ("_smoke" if args.smoke else ""),
        "value": evidence["sustained_inflight_depth"],
        "unit": "sustained in-flight depth under composed load",
        "vs_baseline": None,
        "passed": evidence["passed"],
        "seed": args.seed,
        "shape": {
            "nodes": args.nodes, "batch": args.batch, "depth": args.depth,
            "tenants": args.tenants, "tenant_skew": args.tenant_skew,
            "factor": args.factor, "churn_per_tick": args.churn_per_tick,
            "conflict_every": args.conflict_every, "mesh": args.mesh,
            "packing": args.packing, "failover": args.failover,
        },
        "evidence": evidence,
    }
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
