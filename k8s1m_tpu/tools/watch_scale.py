"""Watch-cache tier SCALE proof: hold >=100K concurrent client watches
on one core and measure what they cost.

The reference's finding is 18 apiserver watches per node -> 18M client
watches at 1M nodes, none reaching etcd (reference README.adoc:410-416).
`watch_fanout_ab.py` proves the amplification economics at bench scale;
this tool proves the TIER ITSELF holds six figures of concurrent
watches: creation rate, resident memory per watch, store-side watcher
count (constant), and live fan-out throughput with the idle population
attached.

Watches are MULTIPLEXED over a few bidi streams with explicit watch ids
— exactly how kube-apiserver talks to etcd (one stream, many watches),
and the only honest way to hold 100K watches from one client core.

With ``--replicas N`` the tier grows into a FLEET: hot keys pin to
replicas through the wiretier's consistent-hash ``SubscriptionMap``
(not round-robin slicing), and the ``--kill-one`` drill becomes a WARM
RESTART — the victim is relaunched with ``--resume-floor`` and its
watches re-attach to it from their own revisions (reprime diff replay),
instead of 100K clients relisting through the survivors.  When the
environment actually has >= 2 effective CPUs the fleet must also scale:
aggregate fan-out throughput is gated against a single-replica
calibration window; on a 1-core box the gate degrades to
correctness-only (zero loss + warm resume), reported as such.

    python -m k8s1m_tpu.tools.watch_scale --idle 100000 --active 2000
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import time

import grpc
from grpc import aio

from k8s1m_tpu.store.etcd_client import EtcdClient
from k8s1m_tpu.store.native import MemStore, decode_shared_tail
from k8s1m_tpu.store.proto import rpc_pb2
from k8s1m_tpu.store.watch_cache import serve_watch_cache
from k8s1m_tpu.store.wiretier import SubscriptionMap

IDLE_PREFIX = b"/registry/configmaps/scale/"
HOT_PREFIX = b"/registry/leases/scale/"


def _rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def _effective_cpus() -> int:
    """CPUs this process can actually burn (cgroup quota wins over the
    host count): the knob that decides whether the replica fleet can
    honestly be gated on SCALING or only on correctness."""
    try:
        with open("/sys/fs/cgroup/cpu.max") as f:
            quota, period = f.read().split()
        if quota != "max":
            return max(1, int(int(quota) // int(period)))
    except (OSError, ValueError):
        pass
    return os.cpu_count() or 1


def _tier_rss_mb(pid: int) -> float:
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024.0
    return 0.0


class MuxWatch:
    """One bidi Watch stream carrying many watches (client side)."""

    def __init__(self, channel: aio.Channel, replica: int = 0):
        self.replica = replica      # which tier replica this stream rides
        self._call = channel.stream_stream(
            "/etcdserverpb.Watch/Watch",
            request_serializer=rpc_pb2.WatchRequest.SerializeToString,
            # Raw frames: the reader decodes the wiretier shared-frame
            # tail itself and fans one frame's events to every watch id
            # riding it (index selection over shared bytes).
            response_deserializer=lambda b: b,
        )()
        self.created = 0
        self.delivered = 0
        self.canceled = 0
        self.last_rev = 0           # highest event revision seen (any watch)
        self.create_rev = 0         # revision at watch registration
        # Per-watch-id resume point: the stream-level max would SKIP
        # events for a watch whose delivery lagged the stream max.
        self.watch_rev: dict[int, int] = {}
        self._created_ev = asyncio.Event()
        self._reader = asyncio.create_task(self._read())

    async def create(
        self, keys: list[bytes], first_id: int,
        start_revision: int | list[int] = 0,
    ) -> None:
        for i, key in enumerate(keys):
            await self._call.write(
                rpc_pb2.WatchRequest(
                    create_request=rpc_pb2.WatchCreateRequest(
                        key=key, watch_id=first_id + i,
                        start_revision=(
                            start_revision[i]
                            if isinstance(start_revision, list)
                            else start_revision
                        ),
                    )
                )
            )

    async def wait_created(self, n: int, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while self.created < n:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {self.created}/{n} watches created"
                )
            await asyncio.sleep(0.05)

    async def _read(self) -> None:
        try:
            async for raw in self._call:
                extra, _from_rev, _core = decode_shared_tail(raw)
                resp = rpc_pb2.WatchResponse.FromString(raw)
                if resp.canceled:
                    self.canceled += 1
                elif resp.created:
                    self.created += 1
                    if resp.header.revision > self.create_rev:
                        self.create_rev = resp.header.revision
                else:
                    wids = (resp.watch_id, *extra)
                    self.delivered += len(resp.events) * len(wids)
                    for ev in resp.events:
                        if ev.kv.mod_revision > self.last_rev:
                            self.last_rev = ev.kv.mod_revision
                    if resp.events:
                        r = resp.events[-1].kv.mod_revision
                        for wid in wids:
                            if r > self.watch_rev.get(wid, 0):
                                self.watch_rev[wid] = r
        except (asyncio.CancelledError, grpc.RpcError):
            pass

    async def close(self) -> None:
        self._reader.cancel()
        try:
            await self._reader
        # Close-path cancel: the reader is being torn down either way.
        except (asyncio.CancelledError, Exception):  # graftlint: disable=broad-except
            pass


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="tier watch-scale proof")
    ap.add_argument("--idle", type=int, default=100_000)
    ap.add_argument("--active", type=int, default=2_000)
    ap.add_argument("--streams", type=int, default=8,
                    help="bidi streams the watches multiplex over")
    ap.add_argument("--writes", type=int, default=20_000)
    ap.add_argument("--index", choices=("hash", "btree"), default="hash")
    ap.add_argument("--lag-budget", type=int, default=0,
                    help="tier per-subscriber FIFO budget before "
                    "latest-only coalescing (watchplane; 0 = tier "
                    "default)")
    ap.add_argument("--pumps", type=int, default=0,
                    help="tier fan-out pump lanes per Watch stream "
                    "(watchplane; 0 = tier default)")
    ap.add_argument(
        "--replicas", type=int, default=1,
        help="tier replica processes over the ONE store; client streams "
        "round-robin across them — the reference's 11-apiserver fleet "
        "behind haproxy SRV round-robin (reference README.adoc:721-723, "
        "terraform/k8s-server/server.tf:230-251)",
    )
    ap.add_argument(
        "--kill-one", action="store_true",
        help="crash drill: SIGKILL the last replica halfway through the "
        "fan-out window, relaunch it with --resume-floor (warm restart) "
        "and re-attach its watches to it from their own revisions — "
        "no relist, no subscription reshuffle, zero event loss",
    )
    return ap.parse_args(argv)


async def amain(args) -> dict:
    import subprocess
    import sys

    from k8s1m_tpu.store.native import WireFront

    store = MemStore()
    # Native wire server: keeps the store off this event loop entirely
    # (the asyncio server would contend with the mux readers for it).
    wf = WireFront(store)
    store_port = wf.port
    seed = EtcdClient(f"127.0.0.1:{store_port}")
    # Idle objects exist but never change after creation.
    wave = []
    for i in range(args.idle):
        wave.append((IDLE_PREFIX + b"cm-%07d" % i, b'{"data":{}}'))
        if len(wave) == 8192:
            await seed.put_batch(wave)
            wave.clear()
    for i in range(args.active):
        wave.append((HOT_PREFIX + b"lease-%05d" % i, b"0"))
    if wave:
        await seed.put_batch(wave)

    # Tier replicas as SUBPROCESSES so their RSS is attributable.  N
    # replicas share the ONE store upstream (each holds its own cache +
    # upstream watch); client streams round-robin across them — the
    # reference's 11-apiserver fleet behind haproxy SRV round-robin
    # (reference README.adoc:721-723, server.tf:230-251).
    from k8s1m_tpu.cluster.harness import _free_port

    n_rep = max(1, args.replicas)
    if args.streams < n_rep:
        args.streams = n_rep        # at least one stream per replica
    tier_ports = [_free_port() for _ in range(n_rep)]
    tier_flags = []
    if args.lag_budget:
        tier_flags += ["--lag-budget", str(args.lag_budget)]
    if args.pumps:
        tier_flags += ["--pumps", str(args.pumps)]
    _env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}

    def _tier_cmd(port: int, extra=()) -> list:
        return [
            sys.executable, "-m", "k8s1m_tpu.store.watch_cache",
            "--upstream", f"127.0.0.1:{store_port}",
            "--host", "127.0.0.1", "--port", str(port),
            "--prefix", IDLE_PREFIX.decode(),
            "--prefix", HOT_PREFIX.decode(),
            "--index", args.index,
            *tier_flags, *extra,
        ]

    tier_procs = [
        subprocess.Popen(_tier_cmd(port), env=_env) for port in tier_ports
    ]
    channels = []
    try:
        # The in-process store server shares THIS event loop; a blocking
        # wait_for_port would starve it and deadlock the tier's priming.
        import socket as _socket

        deadline = time.monotonic() + 120 + n_rep * args.idle / 2000
        for proc, port in zip(tier_procs, tier_ports):
            while True:
                if proc.poll() is not None:
                    raise RuntimeError(f"tier exited rc={proc.returncode}")
                try:
                    with _socket.create_connection(
                        ("127.0.0.1", port), timeout=0.2
                    ):
                        break
                except OSError:
                    if time.monotonic() > deadline:
                        raise TimeoutError("tier did not bind")
                    # Deadline-bounded readiness poll, not an op retry.
                    await asyncio.sleep(0.05)  # graftlint: disable=retry-through-policy
        rss0 = sum(_tier_rss_mb(p.pid) for p in tier_procs)

        channels = [
            aio.insecure_channel(
                f"127.0.0.1:{port}",
                options=[("grpc.max_receive_message_length", 64 << 20)],
            )
            for port in tier_ports
        ]
        muxes = [
            MuxWatch(channels[i % n_rep], replica=i % n_rep)
            for i in range(args.streams)
        ]

        # Create idle watches round-robin over the streams.
        t0 = time.perf_counter()
        per = (args.idle + args.streams - 1) // args.streams
        next_id = 1
        creates = []
        for m in muxes:
            lo = next_id - 1
            keys = [
                IDLE_PREFIX + b"cm-%07d" % (lo + i)
                for i in range(min(per, args.idle - lo))
            ]
            creates.append((m, keys, next_id))
            next_id += len(keys)
        await asyncio.gather(
            *(m.create(keys, fid) for m, keys, fid in creates)
        )
        for m, keys, _ in creates:
            await m.wait_created(len(keys), timeout=240)
        create_s = time.perf_counter() - t0

        # Active watches on the hot keys, placed by the wiretier's
        # consistent-hash SubscriptionMap — each hot key subscribes to
        # exactly ONE replica, and the map is what makes a replica
        # restart a LOCAL event: survivors' subscriptions provably
        # never move (no fleet-wide reshuffle, no relist storm).
        hot_keys = [HOT_PREFIX + b"lease-%05d" % i for i in range(args.active)]
        smap = SubscriptionMap(range(n_rep))
        rep_keys: list[list[bytes]] = [[] for _ in range(n_rep)]
        for k in hot_keys:
            rep_keys[smap.replica_for(k)].append(k)

        async def attach_hot(r: int):
            nonlocal next_id
            keys = rep_keys[r]
            if not keys:
                return None
            first, m = next_id, muxes[r]
            next_id += len(keys)
            base = m.created
            await m.create(keys, first)
            await m.wait_created(base + len(keys), timeout=120)
            return (m, keys, first)

        async def burst_window(keys: list, writes: int) -> float:
            """Unpaced writes over ``keys``; returns delivered/s once
            every write's event has fanned out."""
            base = sum(m.delivered for m in muxes)
            t0 = time.perf_counter()
            written = 0
            while written < writes:
                n = min(2000, writes - written)
                await seed.put_batch([
                    (keys[(written + i) % len(keys)], b"c%d" % (written + i))
                    for i in range(n)
                ])
                written += n
            deadline = time.monotonic() + 120
            while (
                sum(m.delivered for m in muxes) - base < writes
                and time.monotonic() < deadline
            ):
                await asyncio.sleep(0.05)
            return round(
                (sum(m.delivered for m in muxes) - base)
                / (time.perf_counter() - t0), 1,
            )

        hot_slices = []             # (mux, keys, first_id) per replica
        calib_rate = None
        cpus = _effective_cpus()
        if n_rep > 1 and cpus >= 2:
            # SCALING lane — only honest with real parallelism: one
            # replica's fan-out alone first, the fleet's aggregate
            # after, gated on the ratio.  On a 1-core box the fleet
            # still runs (correctness-only) but no linearity is
            # claimed.
            s0 = await attach_hot(0)
            if s0 is not None:
                hot_slices.append(s0)
                calib_rate = await burst_window(
                    rep_keys[0], max(500, args.writes // 4)
                )
        for r in range(n_rep):
            if calib_rate is not None and r == 0:
                continue            # already attached for calibration
            s = await attach_hot(r)
            if s is not None:
                hot_slices.append(s)

        rss1 = sum(_tier_rss_mb(p.pid) for p in tier_procs)
        store_watchers = store.stats()["watchers"]

        # Live fan-out: write the hot keys while the idle watches sit
        # attached; every write fans to exactly one active watch.  With
        # --kill-one, SIGKILL the last replica halfway and re-attach its
        # hot watches to a survivor from the last delivered revision —
        # the haproxy-pulls-a-dead-backend drill.
        t0 = time.perf_counter()
        written = 0
        killed_at = None
        warm_restart = None
        victim_mport = 0
        base_delivered = sum(m.delivered for m in muxes)
        while written < args.writes:
            # Batch bounded by writes/4 so a --kill-one drill always
            # lands MID-stream, even on small smoke runs.
            n = min(2000, max(1, args.writes // 4), args.writes - written)
            await seed.put_batch([
                (hot_keys[(written + i) % args.active], b"%d" % (written + i))
                for i in range(n)
            ])
            written += n
            if (
                args.kill_one and n_rep > 1 and killed_at is None
                and written >= args.writes // 2
            ):
                killed_at = written
                victim = n_rep - 1
                t_kill = time.perf_counter()
                tier_procs[victim].kill()
                tier_procs[victim].wait()
                dead_muxes = [m for m in muxes if m.replica == victim]
                # Join the dead streams' readers BEFORE reading their
                # resume revisions: grpc may still hold buffered
                # responses the reader task hasn't processed — a
                # snapshot taken early would replay revisions the dead
                # stream then also counts (duplicates).
                for dm in dead_muxes:
                    await dm.close()
                # WARM RESTART (the fleet contract): relaunch the
                # victim on its own port with --resume-floor at the
                # weakest proven position of its hot watches.  The
                # SubscriptionMap is untouched — no key moves, no
                # survivor reshuffles — and every watch re-attaches to
                # the relaunched replica from its OWN revision (the
                # watch's last delivered revision, or its registration
                # revision when it never delivered; a stream-level max
                # would skip the laggards' events).  Resume is a diff
                # replay out of the rebuilt history window — not a
                # relist.
                hot = next(
                    (s for s in hot_slices if s[0].replica == victim),
                    None,
                )
                floor = 0
                resume_at: list[int] = []
                if hot is not None:
                    hot_m, rkeys, first = hot
                    resume_at = [
                        max(hot_m.watch_rev.get(first + i, 0),
                            hot_m.create_rev)
                        for i in range(len(rkeys))
                    ]
                    floor = min(resume_at)
                victim_mport = _free_port()
                tier_procs[victim] = subprocess.Popen(
                    _tier_cmd(
                        tier_ports[victim],
                        ["--resume-floor", str(floor),
                         "--metrics-port", str(victim_mport)],
                    ),
                    env=_env,
                )
                bind_by = time.monotonic() + 240
                while True:
                    if tier_procs[victim].poll() is not None:
                        raise RuntimeError(
                            "relaunched replica exited rc="
                            f"{tier_procs[victim].returncode}"
                        )
                    try:
                        with _socket.create_connection(
                            ("127.0.0.1", tier_ports[victim]), timeout=0.2
                        ):
                            break
                    except OSError:
                        if time.monotonic() > bind_by:
                            raise TimeoutError(
                                "relaunched replica did not bind"
                            )
                        # Deadline-bounded readiness poll, not an op retry.
                        await asyncio.sleep(0.05)  # graftlint: disable=retry-through-policy
                chan = aio.insecure_channel(
                    f"127.0.0.1:{tier_ports[victim]}",
                    options=[("grpc.max_receive_message_length", 64 << 20)],
                )
                channels.append(chan)
                if hot is not None:
                    resume = MuxWatch(chan, replica=victim)
                    await resume.create(
                        rkeys, first,
                        start_revision=[r + 1 for r in resume_at],
                    )
                    try:
                        await resume.wait_created(len(rkeys), timeout=120)
                    except TimeoutError as e:
                        raise TimeoutError(
                            f"{e}; canceled={resume.canceled} "
                            f"floor={floor}"
                        ) from None
                    muxes.append(resume)
                # The victim's idle watches re-register plain: their
                # keys never changed, so they carry no resume
                # obligation (nothing to replay, nothing to relist).
                reattached_idle = 0
                for mm, ikeys, ifirst in creates:
                    if mm not in dead_muxes:
                        continue
                    im = MuxWatch(chan, replica=victim)
                    await im.create(ikeys, ifirst)
                    await im.wait_created(len(ikeys), timeout=240)
                    muxes.append(im)
                    reattached_idle += len(ikeys)
                warm_restart = {
                    "resume_floor": floor,
                    "restart_seconds": round(
                        time.perf_counter() - t_kill, 2
                    ),
                    "reattached_hot": len(resume_at),
                    "reattached_idle": reattached_idle,
                }
        # Wait for deliveries to drain.
        deadline = time.monotonic() + 120
        while (
            sum(m.delivered for m in muxes) - base_delivered < args.writes
            and time.monotonic() < deadline
        ):
            await asyncio.sleep(0.05)
        window = time.perf_counter() - t0
        delivered = sum(m.delivered for m in muxes) - base_delivered

        if warm_restart is not None:
            # The relaunched replica's own counters are the warm-restart
            # receipt: resumes (reprime diff replay) moved, invalidations
            # (the relist-everyone path) did not.
            import urllib.request

            def _scrape():
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{victim_mport}/metrics", timeout=10
                ) as r:
                    return r.read().decode()

            counts: dict = {}
            for line in (await asyncio.to_thread(_scrape)).splitlines():
                if line.startswith("#") or not line.strip():
                    continue
                name, _, val = line.rpartition(" ")
                base_name = name.split("{", 1)[0]
                try:
                    counts[base_name] = counts.get(base_name, 0.0) + float(val)
                except ValueError:
                    continue
            warm_restart["resumes"] = int(
                counts.get("watchcache_resumes_total", 0)
            )
            warm_restart["invalidations"] = int(
                counts.get("watchcache_invalidations_total", 0)
            )

        for m in muxes:
            await m.close()
        for channel in channels:
            await channel.close()
    finally:
        for p in tier_procs:
            p.terminate()
        for p in tier_procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        await seed.close()
        wf.close()
        store.close()

    total_watches = args.idle + args.active
    out = {
        "metric": "tier_concurrent_watches",
        "value": total_watches,
        "unit": "watches",
        "vs_baseline": round(total_watches / 18_000_000, 4),
        "replicas": n_rep,
        "create_per_sec": round(args.idle / create_s, 1),
        "tier_rss_mb": round(rss1, 1),
        "kb_per_watch": round((rss1 - rss0) * 1024.0 / total_watches, 2),
        "store_watchers": store_watchers,
        "delivered": delivered,
        "delivered_per_sec": round(delivered / window, 1),
        "canceled": sum(m.canceled for m in muxes),
    }
    if n_rep > 1:
        agg = round(delivered / window, 1)
        if calib_rate is not None:
            out["scaling"] = {
                "effective_cpus": cpus,
                "single_replica_delivered_per_sec": calib_rate,
                "aggregate_delivered_per_sec": agg,
                "speedup": round(agg / max(1e-9, calib_rate), 2),
                # Linear-ish: the fleet must beat one replica by 1.5x
                # before we call the replicas a scaling story.
                "gate_linear_scaling": agg >= 1.5 * calib_rate,
            }
        else:
            out["scaling"] = {
                "effective_cpus": cpus,
                "mode": (
                    "correctness-only: <2 effective cpus, the replicas "
                    "timeshare one core so no linearity is claimed"
                ),
            }
    if killed_at is not None:
        out["kill_one"] = {
            "killed_after_writes": killed_at,
            "no_event_loss": delivered >= args.writes,
            "warm_restart": warm_restart,
        }
    return out


def main(argv=None):
    args = parse_args(argv)
    print(json.dumps(asyncio.run(amain(args))))


if __name__ == "__main__":
    main()
