"""Watch-cache tier SCALE proof: hold >=100K concurrent client watches
on one core and measure what they cost.

The reference's finding is 18 apiserver watches per node -> 18M client
watches at 1M nodes, none reaching etcd (reference README.adoc:410-416).
`watch_fanout_ab.py` proves the amplification economics at bench scale;
this tool proves the TIER ITSELF holds six figures of concurrent
watches: creation rate, resident memory per watch, store-side watcher
count (constant), and live fan-out throughput with the idle population
attached.

Watches are MULTIPLEXED over a few bidi streams with explicit watch ids
— exactly how kube-apiserver talks to etcd (one stream, many watches),
and the only honest way to hold 100K watches from one client core.

    python -m k8s1m_tpu.tools.watch_scale --idle 100000 --active 2000
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import time

import grpc
from grpc import aio

from k8s1m_tpu.store.etcd_client import EtcdClient
from k8s1m_tpu.store.native import MemStore
from k8s1m_tpu.store.proto import rpc_pb2
from k8s1m_tpu.store.watch_cache import serve_watch_cache

IDLE_PREFIX = b"/registry/configmaps/scale/"
HOT_PREFIX = b"/registry/leases/scale/"


def _rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def _tier_rss_mb(pid: int) -> float:
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024.0
    return 0.0


class MuxWatch:
    """One bidi Watch stream carrying many watches (client side)."""

    def __init__(self, channel: aio.Channel):
        self._call = channel.stream_stream(
            "/etcdserverpb.Watch/Watch",
            request_serializer=rpc_pb2.WatchRequest.SerializeToString,
            response_deserializer=rpc_pb2.WatchResponse.FromString,
        )()
        self.created = 0
        self.delivered = 0
        self.canceled = 0
        self._created_ev = asyncio.Event()
        self._reader = asyncio.create_task(self._read())

    async def create(self, keys: list[bytes], first_id: int) -> None:
        for i, key in enumerate(keys):
            await self._call.write(
                rpc_pb2.WatchRequest(
                    create_request=rpc_pb2.WatchCreateRequest(
                        key=key, watch_id=first_id + i
                    )
                )
            )

    async def wait_created(self, n: int, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while self.created < n:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {self.created}/{n} watches created"
                )
            await asyncio.sleep(0.05)

    async def _read(self) -> None:
        try:
            async for resp in self._call:
                if resp.canceled:
                    self.canceled += 1
                elif resp.created:
                    self.created += 1
                else:
                    self.delivered += len(resp.events)
        except (asyncio.CancelledError, grpc.RpcError):
            pass

    async def close(self) -> None:
        self._reader.cancel()
        try:
            await self._reader
        except (asyncio.CancelledError, Exception):
            pass


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="tier watch-scale proof")
    ap.add_argument("--idle", type=int, default=100_000)
    ap.add_argument("--active", type=int, default=2_000)
    ap.add_argument("--streams", type=int, default=8,
                    help="bidi streams the watches multiplex over")
    ap.add_argument("--writes", type=int, default=20_000)
    ap.add_argument("--index", choices=("hash", "btree"), default="hash")
    return ap.parse_args(argv)


async def amain(args) -> dict:
    import subprocess
    import sys

    from k8s1m_tpu.store.native import WireFront

    store = MemStore()
    # Native wire server: keeps the store off this event loop entirely
    # (the asyncio server would contend with the mux readers for it).
    wf = WireFront(store)
    store_port = wf.port
    seed = EtcdClient(f"127.0.0.1:{store_port}")
    # Idle objects exist but never change after creation.
    wave = []
    for i in range(args.idle):
        wave.append((IDLE_PREFIX + b"cm-%07d" % i, b'{"data":{}}'))
        if len(wave) == 8192:
            await seed.put_batch(wave)
            wave.clear()
    for i in range(args.active):
        wave.append((HOT_PREFIX + b"lease-%05d" % i, b"0"))
    if wave:
        await seed.put_batch(wave)

    # Tier as a SUBPROCESS so its RSS is attributable.
    from k8s1m_tpu.cluster.harness import _free_port

    tier_port = _free_port()
    tier_proc = subprocess.Popen(
        [
            sys.executable, "-m", "k8s1m_tpu.store.watch_cache",
            "--upstream", f"127.0.0.1:{store_port}",
            "--host", "127.0.0.1", "--port", str(tier_port),
            "--prefix", IDLE_PREFIX.decode(),
            "--prefix", HOT_PREFIX.decode(),
            "--index", args.index,
        ],
        env={**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"},
    )
    try:
        # The in-process store server shares THIS event loop; a blocking
        # wait_for_port would starve it and deadlock the tier's priming.
        import socket as _socket

        deadline = time.monotonic() + 120 + args.idle / 2000
        while True:
            if tier_proc.poll() is not None:
                raise RuntimeError(f"tier exited rc={tier_proc.returncode}")
            try:
                with _socket.create_connection(
                    ("127.0.0.1", tier_port), timeout=0.2
                ):
                    break
            except OSError:
                if time.monotonic() > deadline:
                    raise TimeoutError("tier did not bind")
                await asyncio.sleep(0.05)
        rss0 = _tier_rss_mb(tier_proc.pid)

        channel = aio.insecure_channel(
            f"127.0.0.1:{tier_port}",
            options=[("grpc.max_receive_message_length", 64 << 20)],
        )
        muxes = [MuxWatch(channel) for _ in range(args.streams)]

        # Create idle watches round-robin over the streams.
        t0 = time.perf_counter()
        per = (args.idle + args.streams - 1) // args.streams
        next_id = 1
        creates = []
        for m in muxes:
            lo = next_id - 1
            keys = [
                IDLE_PREFIX + b"cm-%07d" % (lo + i)
                for i in range(min(per, args.idle - lo))
            ]
            creates.append((m, keys, next_id))
            next_id += len(keys)
        await asyncio.gather(
            *(m.create(keys, fid) for m, keys, fid in creates)
        )
        for m, keys, _ in creates:
            await m.wait_created(len(keys), timeout=240)
        create_s = time.perf_counter() - t0

        # Active watches on the hot keys, on stream 0.
        hot_first = next_id
        hot_keys = [HOT_PREFIX + b"lease-%05d" % i for i in range(args.active)]
        await muxes[0].create(hot_keys, hot_first)
        await muxes[0].wait_created(per + args.active, timeout=120)

        rss1 = _tier_rss_mb(tier_proc.pid)
        store_watchers = store.stats()["watchers"]

        # Live fan-out: write the hot keys while 100K idle watches sit
        # attached; every write fans to exactly one active watch.
        t0 = time.perf_counter()
        written = 0
        base_delivered = sum(m.delivered for m in muxes)
        while written < args.writes:
            n = min(2000, args.writes - written)
            await seed.put_batch([
                (hot_keys[(written + i) % args.active], b"%d" % (written + i))
                for i in range(n)
            ])
            written += n
        # Wait for deliveries to drain.
        deadline = time.monotonic() + 120
        while (
            sum(m.delivered for m in muxes) - base_delivered < args.writes
            and time.monotonic() < deadline
        ):
            await asyncio.sleep(0.05)
        window = time.perf_counter() - t0
        delivered = sum(m.delivered for m in muxes) - base_delivered

        for m in muxes:
            await m.close()
        await channel.close()
    finally:
        tier_proc.terminate()
        try:
            tier_proc.wait(timeout=10)
        except Exception:
            tier_proc.kill()
        await seed.close()
        wf.close()
        store.close()

    total_watches = args.idle + args.active
    return {
        "metric": "tier_concurrent_watches",
        "value": total_watches,
        "unit": "watches",
        "vs_baseline": round(total_watches / 18_000_000, 4),
        "create_per_sec": round(args.idle / create_s, 1),
        "tier_rss_mb": round(rss1, 1),
        "kb_per_watch": round((rss1 - rss0) * 1024.0 / total_watches, 2),
        "store_watchers": store_watchers,
        "delivered": delivered,
        "delivered_per_sec": round(delivered / window, 1),
        "canceled": sum(m.canceled for m in muxes),
    }


def main(argv=None):
    args = parse_args(argv)
    print(json.dumps(asyncio.run(amain(args))))


if __name__ == "__main__":
    main()
