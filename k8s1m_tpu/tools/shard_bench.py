"""Multi-shard end-to-end benchmark: N coordinator PROCESSES against one
store at scale — the process topology of the reference's 256-replica
fleet (reference README.adoc:697-730: near-linear scaling to 256
replicas, 14K binds/s at 1M nodes on 8,670 cores).

Each worker process runs a full ShardMember (control/shardset.py): FNV
pod-hash intake split, node space owned via group masks, CAS binds —
the same machinery the in-process harness tests pin, here across real
process + wire boundaries.  The parent populates nodes, spawns workers,
paces the pod load, and aggregates binds/s + latency from worker status
heartbeats written through the store (the same channel the shard set's
own heartbeats use).

    python -m k8s1m_tpu.tools.shard_bench --nodes 1048576 --pods 200000 \
        --shards 4 --score-pct 5

Device note: this host exposes ONE TPU chip behind a serial-use relay,
so at most one worker may take the TPU (--tpu-worker 0); the rest run
the identical XLA program on the CPU backend.  On a pod slice each
worker would own its chips; the process/wire machinery measured here is
what that deployment adds on top of bench.py's device numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

from k8s1m_tpu.control.objects import encode_node, encode_pod, node_key, pod_key
from k8s1m_tpu.snapshot.pod_encoding import PodInfo
from k8s1m_tpu.tools.make_nodes import build_node

STATUS_PREFIX = b"/bench/shard-status/"
START_KEY = b"/bench/start"
END_KEY = b"/bench/end"

REFERENCE_E2E = 14_000.0


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="multi-shard e2e bench")
    ap.add_argument("--nodes", type=int, default=262_144)
    ap.add_argument("--pods", type=int, default=100_000)
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--chunk", type=int, default=None)
    ap.add_argument("--backend", choices=("xla", "pallas"), default="xla")
    ap.add_argument("--score-pct", type=int, default=5)
    ap.add_argument("--rate", type=int, default=0,
                    help="offered pods/s (0 = max-throughput fill)")
    ap.add_argument("--target", default=None,
                    help="existing store addr (default: spawn one)")
    ap.add_argument(
        "--tpu-worker", type=int, default=-1,
        help="worker index allowed on the real TPU (-1: all workers CPU; "
        "the axon relay serializes chip use, so at most one)",
    )
    ap.add_argument("--worker", type=int, default=None, help=argparse.SUPPRESS)
    ap.add_argument("--json", action="store_true",
                    help="print one machine-readable result line")
    return ap.parse_args(argv)


# ---------------------------------------------------------------------------
# Worker process
# ---------------------------------------------------------------------------


def run_worker(args) -> None:
    from k8s1m_tpu.config import PodSpec, TableSpec
    from k8s1m_tpu.control.coordinator import Coordinator
    from k8s1m_tpu.control.shardset import ShardMember, pod_shard
    from k8s1m_tpu.envboot import tune_gc
    from k8s1m_tpu.obs.metrics import REGISTRY, quantile_report_ms
    from k8s1m_tpu.plugins.registry import Profile
    from k8s1m_tpu.store.remote import RemoteStore

    store = RemoteStore(args.target)
    cap = 1 << max(10, (args.nodes - 1).bit_length())
    coord = Coordinator(
        store, TableSpec(max_nodes=cap), PodSpec(batch=args.batch),
        Profile(node_affinity=0, topology_spread=0, interpod_affinity=0),
        chunk=min(args.chunk, cap), with_constraints=False,
        backend=args.backend, score_pct=args.score_pct,
    )
    member = ShardMember(store, coord, args.worker, args.shards)
    member.start(now=time.monotonic())

    # Warm the compile cache before reporting ready (a mid-window compile
    # stall would look like a straggler shard).  The warm pod's name must
    # HASH to this shard or the intake filter drops it.
    n = 0
    while pod_shard(f"warm/w{args.worker}-{n}", args.shards) != args.worker:
        n += 1
    warm_name = f"w{args.worker}-{n}"
    store.put(
        pod_key("warm", warm_name),
        encode_pod(PodInfo(warm_name, namespace="warm",
                           cpu_milli=1, mem_kib=1)),
    )
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        member.tick(time.monotonic())
        if f"warm/{warm_name}" in coord._bound:
            break
    tune_gc()
    hist = REGISTRY.get("coordinator_schedule_to_bind_seconds")
    hist.reset()
    # Own binds only: coord._bound also tracks binds OBSERVED from other
    # shards via the pod watch (cluster-wide churn accounting), so the
    # shard's throughput stat must come from its bind counter.
    sched = REGISTRY.get("coordinator_pods_scheduled_total")
    warm_bound = int(sched.value(outcome="bound"))

    def post_status(done: bool) -> None:
        doc = {
            "worker": args.worker,
            "bound": int(sched.value(outcome="bound")) - warm_bound,
            "conflicts": int(sched.value(outcome="conflict")),
            **quantile_report_ms(hist, (0.5, 0.99)),
            "done": done,
        }
        store.put(STATUS_PREFIX + str(args.worker).encode(),
                  json.dumps(doc).encode())

    print(json.dumps({"ready": args.worker}), flush=True)
    while store.get(START_KEY) is None:
        time.sleep(0.05)

    last_beat = 0.0
    idle_ticks = 0
    ended = False
    while idle_ticks < 40:
        n = member.tick(time.monotonic())
        if (
            n == 0 and not coord.queue and not coord._inflights
            and not coord._backoff
        ):
            # Only start counting down once the producer declared done —
            # a rate-paced load has idle gaps longer than the countdown.
            if ended or (ended := store.get(END_KEY) is not None):
                idle_ticks += 1
            time.sleep(0.005)
        else:
            idle_ticks = 0
        now = time.monotonic()
        if now - last_beat > 0.25:
            post_status(False)
            last_beat = now
    post_status(True)
    member.close()
    store.close()


# ---------------------------------------------------------------------------
# Parent: populate, spawn, pace, aggregate
# ---------------------------------------------------------------------------


def _spawn_store(args):
    from k8s1m_tpu.cluster.harness import _free_port, wait_for_port

    port = _free_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "k8s1m_tpu.store.server_main",
            "--host", "127.0.0.1", "--port", str(port),
            "--metrics-port", "0",
        ],
        env={**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"},
    )
    wait_for_port(port, proc=proc)
    return proc, f"127.0.0.1:{port}"


def _spawn_worker(args, idx: int):
    env = {**os.environ}
    if idx != args.tpu_worker:
        env["PYTHONPATH"] = ""
        env["JAX_PLATFORMS"] = "cpu"
    cmd = [
        sys.executable, "-m", "k8s1m_tpu.tools.shard_bench",
        "--worker", str(idx), "--shards", str(args.shards),
        "--target", args.target, "--nodes", str(args.nodes),
        "--pods", str(args.pods), "--batch", str(args.batch),
        "--backend", args.backend, "--score-pct", str(args.score_pct),
    ]
    if args.chunk:
        cmd += ["--chunk", str(args.chunk)]
    return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE, text=True)


def main(argv=None):
    args = parse_args(argv)
    if args.chunk is None:
        args.chunk = (1 << 12) if args.backend == "pallas" else (1 << 14)
    if args.worker is not None:
        run_worker(args)
        return

    from k8s1m_tpu.control.shardset import init_assignment, pod_shard
    from k8s1m_tpu.store.remote import RemoteStore

    store_proc = None
    if not args.target:
        store_proc, args.target = _spawn_store(args)
    store = RemoteStore(args.target)

    t0 = time.perf_counter()
    wave = []
    for i in range(args.nodes):
        wave.append((node_key(f"kwok-node-{i}"), encode_node(build_node(i))))
        if len(wave) == 8192:
            store.put_batch(wave)
            wave.clear()
    if wave:
        store.put_batch(wave)
    init_assignment(store, args.shards)
    populate_s = time.perf_counter() - t0
    print(f"# {args.nodes} nodes in {populate_s:.1f}s", file=sys.stderr)

    workers = [_spawn_worker(args, i) for i in range(args.shards)]
    try:
        for w in workers:
            line = w.stdout.readline()
            if not line or "ready" not in line:
                raise RuntimeError(f"worker failed to start: {line!r}")
        print("# workers ready", file=sys.stderr)

        # Pre-encode pods; split stats for the report.
        values = [
            encode_pod(PodInfo(f"bench-{i}", cpu_milli=10, mem_kib=1024))
            for i in range(args.pods)
        ]
        keys = [pod_key("default", f"bench-{i}") for i in range(args.pods)]
        share = [0] * args.shards
        for i in range(args.pods):
            share[pod_shard(f"default/bench-{i}", args.shards)] += 1

        store.put(START_KEY, b"go")
        t0 = time.perf_counter()
        emitted = 0
        while emitted < args.pods:
            if args.rate:
                due = min(args.pods,
                          1 + int(args.rate * (time.perf_counter() - t0)))
            else:
                due = min(args.pods, emitted + 8192)
            if due > emitted:
                store.put_batch(list(zip(keys[emitted:due],
                                         values[emitted:due])))
                emitted = due
            else:
                time.sleep(0.002)
        store.put(END_KEY, b"done")

        # Aggregate from status heartbeats until every pod is bound.
        from k8s1m_tpu.store.native import prefix_end

        stats = {}
        while True:
            res = store.range(STATUS_PREFIX, prefix_end(STATUS_PREFIX))
            total = 0
            for kv in res.kvs:
                doc = json.loads(kv.value)
                stats[doc["worker"]] = doc
                total += doc["bound"]
            if total >= args.pods:
                break
            # A worker that drained its share posts done:true and EXITS
            # (rc=0) while slower shards are still binding — on a
            # one-core host the tails spread by tens of seconds.  Only a
            # non-zero exit is a death.
            if any(w.poll() not in (None, 0) for w in workers):
                rcs = [w.poll() for w in workers]
                raise RuntimeError(f"a shard worker died mid-run: rcs={rcs}")
            if all(w.poll() is not None for w in workers):
                # Everyone exited cleanly; one final refresh already ran
                # this iteration — if the total still comes up short,
                # pods were lost, which IS an error.
                if total < args.pods:
                    raise RuntimeError(
                        f"workers exited with {total}/{args.pods} bound"
                    )
                break
            time.sleep(0.1)
        window = time.perf_counter() - t0
        # The window closed at the last bind; workers post their final
        # done:true status only after their idle countdown, so give them
        # a moment — otherwise per_worker reports a stale done:false.
        deadline = time.monotonic() + 10.0
        while (not all(s.get("done") for s in stats.values())
               and time.monotonic() < deadline):
            time.sleep(0.05)
            res = store.range(STATUS_PREFIX, prefix_end(STATUS_PREFIX))
            for kv in res.kvs:
                doc = json.loads(kv.value)
                stats[doc["worker"]] = doc
            if all(w.poll() is not None for w in workers):
                # Every worker exited and the refresh above ran after
                # that: a normal exit's done:true is in; a crashed
                # worker's done:false surfaces in the report instead of
                # spinning out the deadline.
                break
    finally:
        for w in workers:
            if w.poll() is None:
                w.terminate()
        for w in workers:
            try:
                w.wait(timeout=15)
            except subprocess.TimeoutExpired:
                w.kill()
        store.close()
        if store_proc is not None:
            store_proc.terminate()
            store_proc.wait(timeout=10)

    binds_s = args.pods / window
    result = {
        "metric": "shard_e2e_binds_per_sec",
        "value": round(binds_s, 1),
        "unit": "binds/s",
        "vs_baseline": round(binds_s / REFERENCE_E2E, 3),
        "shards": args.shards,
        "nodes": args.nodes,
        "pods": args.pods,
        "window_s": round(window, 2),
        "pod_share": share,
        "per_worker": [stats.get(i) for i in range(args.shards)],
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
