"""Deterministic tenantfair drill: weighted-fair shares, preemption,
gangs — by seed (the ISSUE 8 acceptance evidence, as one reproducible
run, in the overload_drill mold).

Phase 1 — **fairness** (run under ``lint.guards.audit()``): N tenants
with zipf-skewed integer weights submit at a sustained 5x aggregate
overload through ``Coordinator.submit_external`` + the weighted-fair
admission (tenancy/admission.py), tick-driven on a virtual clock.
Gates: every *saturating* tenant's admitted throughput lands within 10%
of its weight share over the enforcement window; the one deliberately
non-saturating tenant gets essentially everything it offered; the queue
stays under the hard cap; after the drain every admitted pod is bound
in the store (zero-loss ledger); zero ``@guarded_by`` violations.

Phase 2 — **preemption + gang** (fresh store): low-priority filler pods
saturate every node's pod slots, then a high-priority GANG (labels
``k8s1m.io/gang``/``gang-size``) arrives.  No feasible row exists, so
each member preempts: victims are selected by the documented order
(lowest priority, other-tenant first, newest bind first), evicted via
the store CAS (stored bytes return EXACTLY to their pre-bind encoding —
the unsplice identity) and requeued; the gang binds all-or-none inside
one wave-epoch window.  Gates: the gang settles ``bound`` (never
partial), every eviction is logged and every victim requeued, zero pods
lost in the ledger, and the whole evict+rebind is **byte-identical to a
replay**: ``select_preemption`` re-run offline on each event's logged
pre-state picks the same node and victims, and the stored bytes equal
``splice_node_name(raw, that node)`` for the preemptor and the original
``raw`` for each still-pending victim.

    python -m k8s1m_tpu.tools.tenantfair_drill --smoke \
        --out artifacts/tenantfair_drill.json

``--smoke`` is the tier-1 shape (seconds on CPU); the default shape is
the same drill bigger.  One JSON line (``passed``) prints; the full
evidence lands in ``--out``.
"""

from __future__ import annotations

import argparse
import json
import os

IDLE_DRAIN_TICKS = 2000


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="deterministic tenantfair drill")
    ap.add_argument("--nodes", type=int, default=256)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument("--tenants", type=int, default=8)
    ap.add_argument("--tenant-skew", type=float, default=1.0)
    ap.add_argument("--factor", type=int, default=5,
                    help="aggregate overload, in multiples of one batch "
                    "per tick")
    ap.add_argument("--warm-ticks", type=int, default=4)
    ap.add_argument("--measure-ticks", type=int, default=40)
    ap.add_argument("--gang-size", type=int, default=4)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 shape: tiny cluster, same gates")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.smoke:
        args.nodes, args.batch, args.chunk = 64, 64, 32
        args.tenants = 4
        args.measure_ticks = 24
    return args


def _weights(args) -> dict[str, int]:
    """Zipf-skewed integer weights, tenant-0 heaviest."""
    from k8s1m_tpu.cluster.workload import zipf_weights

    z = zipf_weights(args.tenants, args.tenant_skew)
    return {
        f"tenant-{t}": max(1, round(z[t] / z[-1]))
        for t in range(args.tenants)
    }


def run_fairness(args) -> dict:
    """Phase 1: weighted-fair admission under 5x aggregate overload,
    with the guard audit live for the whole phase."""
    from k8s1m_tpu.config import PodSpec, TableSpec
    from k8s1m_tpu.control.coordinator import Coordinator
    from k8s1m_tpu.control.objects import (
        encode_node,
        encode_pod,
        node_key,
        pod_key,
    )
    from k8s1m_tpu.lint import guards
    from k8s1m_tpu.loadshed import HEALTHY, LoadshedConfig, Overloaded
    from k8s1m_tpu.plugins.registry import Profile
    from k8s1m_tpu.snapshot.node_table import NodeInfo
    from k8s1m_tpu.snapshot.pod_encoding import PodInfo
    from k8s1m_tpu.store.native import MemStore
    from k8s1m_tpu.tenancy import TenancyController, TenancyPolicy

    b = args.batch
    weights = _weights(args)
    total_w = sum(weights.values())
    tenants = sorted(weights, key=lambda t: int(t.split("-")[1]))
    # Offered profile: every tenant floods at `factor` x its weight
    # share — except the LAST (lightest) tenant, deliberately offered
    # under its share: the non-saturating gate (it must get ~everything
    # it asks for while the flooders are clamped to their shares).
    offered = {}
    for t in tenants:
        share = b * weights[t] / total_w
        offered[t] = max(1, int(args.factor * share))
    lightest = tenants[-1]
    offered[lightest] = max(1, int(0.4 * b * weights[lightest] / total_w))

    cfg = LoadshedConfig(
        queue_degraded=2 * b, queue_shed=4 * b, queue_cap=64 * b,
        queue_recover=b // 2, recover_cycles=3,
    )
    tn = TenancyController(
        TenancyPolicy(weights=weights), loadshed_config=cfg,
        name="tenantfair_drill",
    )
    store = MemStore()
    for i in range(args.nodes):
        store.put(node_key(f"n{i:05d}"), encode_node(NodeInfo(
            name=f"n{i:05d}", cpu_milli=1 << 22, mem_kib=1 << 30,
            pods=1 << 20,
        )))
    coord = Coordinator(
        store, TableSpec(max_nodes=args.nodes, max_zones=16, max_regions=8),
        PodSpec(batch=b), Profile(topology_spread=0, interpod_affinity=0),
        chunk=args.chunk, k=4, with_constraints=False, seed=args.seed,
        score_pct=50, tenancy=tn,
    )
    seq = 0
    max_load = 0
    admitted_keys: list[tuple[str, str]] = []
    enforce_base = None
    enforce_offered: dict[str, int] = {t: 0 for t in tenants}

    def submit_tick(tick: int) -> None:
        nonlocal seq
        # Deterministic proportional interleave: tenants emit on evenly
        # spaced phases so arrival order never biases the cap.
        lanes = [
            (k / offered[t], t, k)
            for t in tenants for k in range(offered[t])
        ]
        lanes.sort()
        enforcing = tn.controller.current_state() != HEALTHY
        for _, t, _k in lanes:
            seq += 1
            pod = PodInfo(f"p{seq:07d}", namespace=t,
                          cpu_milli=10, mem_kib=1 << 10)
            obj = json.loads(encode_pod(pod))
            if enforcing:
                enforce_offered[t] += 1
            try:
                coord.submit_external(obj)
            except Overloaded:
                continue
            store.put(pod_key(t, pod.name), encode_pod(pod))
            admitted_keys.append((t, pod.name))

    violations = None
    try:
        coord.bootstrap()
        with guards.audit():
            measured = 0
            for tick in range(args.warm_ticks + 10 * args.measure_ticks):
                submit_tick(tick)
                coord.step()
                max_load = max(
                    max_load, len(coord.queue) + len(coord._backoff)
                )
                if tn.controller.current_state() != HEALTHY:
                    if enforce_base is None:
                        enforce_base = tn.admission.counters()["admitted"]
                        enforce_offered = {t: 0 for t in tenants}
                    else:
                        measured += 1
                        if measured >= args.measure_ticks:
                            break
            counters = tn.admission.counters()
            # Drain: every admitted pod must bind (zero-loss ledger).
            for _ in range(IDLE_DRAIN_TICKS):
                if (
                    not coord.queue and not coord._backoff
                    and not coord._external_pending()
                    and not coord._inflights
                ):
                    break
                coord.step()
            coord.flush()
            lost = 0
            for t, name in admitted_keys:
                kv = store.get(pod_key(t, name))
                if kv is None or b'"nodeName"' not in kv.value:
                    lost += 1
        violations = guards.violations()
    finally:
        coord.close()
        store.close()

    base = enforce_base or {}
    adm = {
        t: counters["admitted"].get(t, 0) - base.get(t, 0) for t in tenants
    }
    total_adm = sum(adm.values()) or 1
    shares = {t: adm[t] / total_adm for t in tenants}
    # Weight shares among the SATURATING tenants only: the lightest
    # tenant's unused entitlement is not redistributed by the buckets,
    # so flooders are judged against the full weight split while the
    # light tenant is judged on offered-vs-admitted.
    per_tenant = {}
    fair_ok = True
    light_ok = True
    for t in tenants:
        w_share = weights[t] / total_w
        sat = offered[t] >= 1.1 * b * w_share
        rec = {
            "weight": weights[t],
            "weight_share": round(w_share, 4),
            "offered_per_tick": offered[t],
            "admitted": adm[t],
            "admitted_share": round(shares[t], 4),
            "saturating": sat,
        }
        if sat:
            ok = abs(shares[t] - w_share) <= 0.10 * w_share
            rec["within_10pct"] = ok
            fair_ok = fair_ok and ok
        else:
            off = enforce_offered.get(t, 0)
            ok = off == 0 or adm[t] >= 0.9 * off
            rec["admitted_vs_offered"] = round(adm[t] / off, 4) if off else None
            rec["non_saturating_ok"] = ok
            light_ok = light_ok and ok
        per_tenant[t] = rec
    return {
        "weights": weights,
        "queue_cap": cfg.queue_cap,
        "max_load": max_load,
        "per_tenant": per_tenant,
        "admitted_total": len(admitted_keys),
        "lost": lost,
        "guard_violations": violations,
        "passed": bool(
            fair_ok and light_ok
            and max_load <= cfg.queue_cap
            and lost == 0
            and not violations
        ),
    }


def run_preempt_gang(args) -> dict:
    """Phase 2: a starved high-priority gang preempts, binds
    all-or-none, victims requeue, and the whole thing replays
    byte-identically."""
    from k8s1m_tpu.config import PodSpec, TableSpec
    from k8s1m_tpu.control.coordinator import Coordinator, splice_node_name
    from k8s1m_tpu.control.objects import (
        decode_node,
        encode_node,
        encode_pod,
        node_key,
        pod_key,
    )
    from k8s1m_tpu.obs.metrics import REGISTRY
    from k8s1m_tpu.plugins.registry import Profile
    from k8s1m_tpu.snapshot.node_table import NodeInfo
    from k8s1m_tpu.snapshot.pod_encoding import PodInfo
    from k8s1m_tpu.store.native import MemStore, list_prefix
    from k8s1m_tpu.tenancy import TenancyController, TenancyPolicy
    from k8s1m_tpu.tenancy.preempt import Victim, select_preemption

    nodes_n = min(args.nodes, 16)
    slots = 60
    fillers = nodes_n * slots
    gang_n = args.gang_size
    ev0 = REGISTRY.get("preemption_evictions_total").value()
    g0 = {
        o: REGISTRY.get("gang_admit_total").value(outcome=o)
        for o in ("bound", "requeued", "parked", "oversize")
    }

    store = MemStore()
    raws: dict[str, bytes] = {}
    for i in range(nodes_n):
        store.put(node_key(f"n{i:03d}"), encode_node(NodeInfo(
            name=f"n{i:03d}", cpu_milli=70_000, mem_kib=1 << 20, pods=slots,
        )))
    tn = TenancyController(TenancyPolicy(log_preemptions=True))
    coord = Coordinator(
        store, TableSpec(max_nodes=32, max_zones=4, max_regions=2),
        PodSpec(batch=args.batch), Profile(topology_spread=0, interpod_affinity=0),
        chunk=32, k=4, with_constraints=False, seed=args.seed, tenancy=tn,
    )
    mismatches: list = []
    try:
        coord.bootstrap()
        # Fill every pod slot with low-priority filler (pod-count
        # saturation is deterministic regardless of score spread).
        for i in range(fillers):
            pod = PodInfo(f"f-{i:05d}", namespace="fill",
                          cpu_milli=1000, mem_kib=1 << 10)
            raws[pod.key] = encode_pod(pod)
            store.put(pod_key("fill", pod.name), raws[pod.key])
        filler_bound = coord.run_until_idle()
        # The starved high-priority gang.
        for j in range(gang_n):
            pod = PodInfo(
                f"g-{j}", namespace="tenant-a", cpu_milli=3000,
                mem_kib=1 << 10, priority=10,
                labels={"k8s1m.io/gang": "burst",
                        "k8s1m.io/gang-size": str(gang_n)},
            )
            raws[pod.key] = encode_pod(pod)
            store.put(pod_key("tenant-a", pod.name), raws[pod.key])
        gang_bound = coord.run_until_idle()
        events = list(coord.preempt_log)
        evictions = REGISTRY.get("preemption_evictions_total").value() - ev0
        gangs = {
            o: REGISTRY.get("gang_admit_total").value(outcome=o) - g0[o]
            for o in g0
        }

        # ---- replay: selection identical, bytes identical -----------
        kvs, _ = list_prefix(store, b"/registry/minions/")
        node_infos = {}
        for kv in kvs:
            nd = decode_node(kv.value)
            node_infos[nd.name] = nd
        victim_keys: set[str] = set()
        for e in events:
            nodes_list = sorted(
                (coord.host.row_of(n), nd) for n, nd in node_infos.items()
            )
            usage = {int(r): tuple(u) for r, u in e["usage"].items()}
            victims_by_row = {
                int(r): [Victim(*v) for v in vs]
                for r, vs in e["candidates"].items()
            }
            ns, name = e["pod"].split("/", 1)
            pod = PodInfo(name, namespace=ns, cpu_milli=3000,
                          mem_kib=1 << 10, priority=e["priority"])
            choice = select_preemption(
                pod, e["tenant"], e["priority"], nodes_list, usage,
                victims_by_row,
            )
            if (
                choice is None
                or choice.node != e["node"]
                or [v.key for v in choice.victims] != e["victims"]
            ):
                mismatches.append((e["pod"], "selection replay diverged"))
                continue
            got = store.get(pod_key(ns, name))
            want = splice_node_name(raws[e["pod"]], e["node"])
            if got is None or got.value != want:
                mismatches.append((e["pod"], "preemptor bytes"))
            victim_keys.update(e["victims"])
        # Victims: requeued, and their stored bytes are their EXACT
        # pre-bind encodings while pending (or a valid re-bind).
        victims_pending = victims_rebound = 0
        for vk in victim_keys:
            ns, name = vk.split("/", 1)
            kv = store.get(pod_key(ns, name))
            if kv is None:
                mismatches.append((vk, "victim lost"))
                continue
            if b'"nodeName"' in kv.value:
                victims_rebound += 1
            elif kv.value == raws[vk]:
                victims_pending += 1
            else:
                mismatches.append((vk, "victim bytes"))
        # Ledger: no pod vanished; every stored bind names a live node.
        kvs, _ = list_prefix(store, b"/registry/pods/")
        lost = fillers + gang_n - len(kvs)
        gang_members_bound = sum(
            1 for kv in kvs
            if b"/tenant-a/" in kv.key and b'"nodeName"' in kv.value
        )
    finally:
        coord.close()
        store.close()
    all_or_none = gang_members_bound in (0, gang_n)
    return {
        "nodes": nodes_n,
        "filler_bound": filler_bound,
        "gang_size": gang_n,
        "gang_bound_pods": gang_bound,
        "gang_members_bound_in_store": gang_members_bound,
        "gang_outcomes": gangs,
        "preempt_events": len(events),
        "evictions": evictions,
        "victims": len(victim_keys),
        "victims_pending": victims_pending,
        "victims_rebound": victims_rebound,
        "lost": lost,
        "byte_identical": not mismatches,
        "mismatches": mismatches[:5],
        "passed": bool(
            filler_bound == fillers
            and gang_members_bound == gang_n
            and all_or_none
            and gangs["bound"] >= 1
            and evictions > 0
            and len(events) == gang_n
            and victims_pending + victims_rebound == len(victim_keys)
            and lost == 0
            and not mismatches
        ),
    }


def main(argv=None) -> dict:
    args = parse_args(argv)
    fairness = run_fairness(args)
    preempt = run_preempt_gang(args)
    result = {
        "metric": "tenantfair_drill" + ("_smoke" if args.smoke else ""),
        "value": min(
            (r["admitted_share"] / r["weight_share"]
             for r in fairness["per_tenant"].values() if r["saturating"]),
            default=0.0,
        ),
        "unit": "min saturating admitted/weight share ratio",
        "vs_baseline": None,
        "passed": bool(fairness["passed"] and preempt["passed"]),
        "seed": args.seed,
        "shape": {
            "nodes": args.nodes, "batch": args.batch,
            "tenants": args.tenants, "tenant_skew": args.tenant_skew,
            "factor": args.factor, "gang_size": args.gang_size,
        },
        "fairness": fairness,
        "preempt_gang": preempt,
    }
    result["value"] = round(result["value"], 4)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
