"""static-guarded-by: the ``@guarded_by`` contract, proven at lint time.

PR 4's ``lint/guards.py`` audit enforces lock discipline at *runtime*:
an access path no test drives is invisible to it.  This pass closes the
gap by checking the same declarations at the AST level, on every file,
with zero traffic:

- **lock-guarded fields** (``field="_lock"``): every ``self.<field>``
  read/write inside the declaring class must sit lexically inside a
  ``with self.<lock>:`` block — or in a helper method reached ONLY from
  locked call sites (one level of intra-class call-graph propagation,
  the ``HealthController._set_state`` pattern, whose docstring says
  "caller must hold _admit_lock"; this pass makes that sentence a
  checked invariant).  ``__init__`` is exempt (construction is
  single-threaded by definition — same rule the runtime auditor
  applies), and call sites *in* ``__init__`` count as satisfied for the
  helper analysis for the same reason.
- **THREAD_OWNER fields**: never touched from a method that is also a
  ``threading.Thread`` target (or a ``do_*``/``handle*`` server-handler
  entrypoint) of the same class, nor from a nested function passed as a
  Thread target — those run on a foreign thread by construction, so a
  single static hit is a guaranteed runtime violation, not a maybe.
- **unannotated-shared-state heuristic**: in a class that starts its
  own threads, a field *written* both from a thread-entrypoint method
  and from the ordinary (caller-thread) surface, with no ``@guarded_by``
  annotation covering it, is flagged — the exact shape every race PR 4's
  audit found had, caught before any test traffic exists.

Scope is ``k8s1m_tpu/`` production code (tests may legitimately poke
guarded fields cross-class to assert on them).  Condition variables
constructed over an instance lock (``self._cond =
threading.Condition(self._lock)``) alias to that lock.  The analysis is
intra-class by design: the runtime auditor remains the authority for
cross-object access, and ``racy_read`` bypasses (string field names)
never parse as attribute access in the first place — the two halves are
compared by tests/test_guards_static.py.
"""

from __future__ import annotations

import ast
import dataclasses

from k8s1m_tpu.lint.base import Finding, Rule, SourceFile, call_name
from k8s1m_tpu.lint.flow import walk_held

THREAD_OWNER_SENTINEL = "<thread-owner>"

# Server-handler entrypoints: methods the socketserver / http.server
# machinery invokes on a per-connection thread.
_HANDLER_NAMES = {
    "handle", "handle_one_request", "finish_request", "process_request",
}


def _is_self_attr(node: ast.AST) -> str | None:
    """'x' for a ``self.x`` attribute node, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _guard_map(cls: ast.ClassDef) -> dict[str, str] | None:
    """field -> guard from a ``@guarded_by(...)`` decorator, or None.

    A guard is either a lock-attribute name (string constant) or the
    THREAD_OWNER sentinel (referenced by name in source).
    """
    for deco in cls.decorator_list:
        if not isinstance(deco, ast.Call):
            continue
        if call_name(deco) != "guarded_by":
            continue
        guards: dict[str, str] = {}
        for kw in deco.keywords:
            if kw.arg is None:
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                guards[kw.arg] = v.value
            elif (
                isinstance(v, ast.Name) and v.id == "THREAD_OWNER"
            ) or (
                isinstance(v, ast.Attribute) and v.attr == "THREAD_OWNER"
            ):
                guards[kw.arg] = THREAD_OWNER_SENTINEL
        return guards
    return None


def _thread_target_of(call: ast.Call) -> ast.AST | None:
    """The ``target=`` value of a ``threading.Thread(...)`` call."""
    if call_name(call) != "Thread":
        return None
    for kw in call.keywords:
        if kw.arg == "target":
            return kw.value
    return None


@dataclasses.dataclass
class _Access:
    field: str
    line: int
    write: bool
    held: frozenset[str]       # lock attrs lexically held at the access
    scope: str                 # method name, or "method.nested" for defs


@dataclasses.dataclass
class _MethodSummary:
    name: str
    accesses: list[_Access]
    # (callee method name, locks held at the call site, in __init__?)
    calls: list[tuple[str, frozenset, bool]]
    # (field, scope, line) for every attribute Store outside __init__ —
    # scope is the method name or "<method>.<nested fn>" so Thread-target
    # closures categorize as their own entrypoint.
    writes: list[tuple[str, str, int]]


class _ClassModel:
    def __init__(self, f: SourceFile, cls: ast.ClassDef, guards: dict):
        self.f = f
        self.cls = cls
        self.guards = guards
        self.methods: dict[str, _MethodSummary] = {}
        # Lock aliasing: Condition(self._lock) -> holding the condition
        # is holding the lock.
        self.lock_alias: dict[str, str] = {}
        # Methods running on a foreign thread: Thread targets + handler
        # entrypoints; nested defs used as Thread targets get a
        # synthetic "<method>.<fn>" entry.
        self.thread_entrypoints: set[str] = set()
        self.starts_threads = False
        self._collect_aliases()
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._summarize_method(node)
                if node.name in _HANDLER_NAMES or node.name.startswith("do_"):
                    self.thread_entrypoints.add(node.name)

    def _collect_aliases(self) -> None:
        for node in ast.walk(self.cls):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt_attr = _is_self_attr(node.targets[0])
            if tgt_attr is None or not isinstance(node.value, ast.Call):
                continue
            if call_name(node.value) == "Condition" and node.value.args:
                src = _is_self_attr(node.value.args[0])
                if src is not None:
                    self.lock_alias[tgt_attr] = src

    def _resolve(self, attr: str) -> str:
        return self.lock_alias.get(attr, attr)

    def _summarize_method(self, fn: ast.FunctionDef) -> None:
        # The lexical walk (with-items acquiring left to right, nested
        # defs/lambdas inheriting NO lock context, nested classes
        # skipped) is flow.walk_held — extracted from the visitor this
        # method used to carry; only the summarizing consumer remains.
        summary = _MethodSummary(fn.name, [], [], [])
        in_init = fn.name == "__init__"
        for node, held, scope in walk_held(fn, resolve=self._resolve):
            if isinstance(node, ast.Call):
                tgt = _thread_target_of(node)
                if tgt is not None:
                    self.starts_threads = True
                    attr = _is_self_attr(tgt)
                    if attr is not None:
                        self.thread_entrypoints.add(attr)
                    elif isinstance(tgt, ast.Name):
                        self.thread_entrypoints.add(f"{fn.name}.{tgt.id}")
                callee = None
                if isinstance(node.func, ast.Attribute):
                    callee = _is_self_attr(node.func)
                if callee is not None:
                    # Construction-exempt only from __init__'s OWN scope:
                    # a call made inside a nested def defined there (a
                    # Thread-target closure) runs post-construction, so
                    # it must not launder an unguarded helper.
                    summary.calls.append(
                        (callee, held, in_init and scope == fn.name)
                    )
            attr = _is_self_attr(node)
            if attr is not None:
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                if attr in self.guards:
                    summary.accesses.append(
                        _Access(attr, node.lineno, write, held, scope)
                    )
                # __init__ writes are construction-exempt — but only in
                # __init__'s OWN scope: a nested def defined there and
                # handed to a Thread runs post-construction on a foreign
                # thread, so its writes count.
                if write and (not in_init or scope != fn.name):
                    summary.writes.append((attr, scope, node.lineno))
        self.methods[fn.name] = summary


class StaticGuardedBy(Rule):
    id = "static-guarded-by"

    def check_file(self, f: SourceFile) -> list[Finding]:
        if not f.path.startswith("k8s1m_tpu/"):
            return []
        out: list[Finding] = []
        for node in f.tree.body if isinstance(f.tree, ast.Module) else []:
            if not isinstance(node, ast.ClassDef):
                continue
            guards = _guard_map(node)
            model = _ClassModel(f, node, guards or {})
            if guards:
                out.extend(self._check_annotated(f, model))
            out.extend(self._check_unannotated(f, model))
        out.sort(key=lambda fd: fd.line)
        return out

    # -- declared guards -------------------------------------------------

    def _check_annotated(self, f: SourceFile, m: _ClassModel) -> list[Finding]:
        out: list[Finding] = []
        # Call sites per method: (held locks, from __init__).  __init__
        # call sites are INCLUDED — construction is single-threaded, so
        # they count as satisfied in the locked-helper check below (a
        # helper called only from __init__ is clean, matching the
        # runtime auditor's construction exemption).
        callers: dict[str, list[tuple[frozenset, bool]]] = {}
        for ms in m.methods.values():
            for callee, held, in_init in ms.calls:
                callers.setdefault(callee, []).append((held, in_init))
        for ms in m.methods.values():
            for acc in ms.accesses:
                # Construction is single-threaded: __init__'s OWN scope
                # is exempt.  Accesses inside a nested def defined there
                # (scope "__init__.<fn>") run later — possibly as a
                # Thread target — and are checked like any other.
                if ms.name == "__init__" and acc.scope == "__init__":
                    continue
                guard = m.guards[acc.field]
                if guard == THREAD_OWNER_SENTINEL:
                    if acc.scope in m.thread_entrypoints:
                        out.append(self.finding(
                            f, acc.line,
                            f"{m.cls.name}.{acc.field} is THREAD_OWNER but "
                            f"{acc.scope} runs on a spawned thread "
                            f"(Thread target / handler entrypoint)",
                        ))
                    continue
                if guard in acc.held:
                    continue
                if acc.scope != ms.name:
                    # Inside a nested def/lambda: runs later, no lexical
                    # lock — always a finding (pragma if deliberate).
                    out.append(self._unguarded(f, m, acc, guard))
                    continue
                sites = callers.get(ms.name, [])
                locked_helper = bool(sites) and all(
                    in_init or guard in held for held, in_init in sites
                )
                if not locked_helper:
                    out.append(self._unguarded(f, m, acc, guard))
        return out

    def _unguarded(self, f, m: _ClassModel, acc: _Access, guard: str) -> Finding:
        mode = "write" if acc.write else "read"
        return self.finding(
            f, acc.line,
            f"{m.cls.name}.{acc.field} {mode} outside 'with self.{guard}:' "
            f"(and {acc.scope} has unlocked intra-class callers); hold the "
            f"lock, make every caller hold it, or pragma with the reason",
        )

    # -- unannotated shared state heuristic --------------------------------

    def _check_unannotated(self, f: SourceFile, m: _ClassModel) -> list[Finding]:
        if not m.starts_threads:
            return []
        # Entry category per method: each thread entrypoint is its own
        # category; everything else is the (single) caller-thread surface.
        # A nested Thread-target def belongs to its synthetic scope name.
        def category(scope: str) -> str:
            return scope if scope in m.thread_entrypoints else "main"

        writes: dict[str, dict[str, int]] = {}   # field -> category -> line
        for ms in m.methods.values():
            for field, scope, line in ms.writes:
                if field in m.guards:
                    continue
                cat = category(scope)
                prev = writes.setdefault(field, {}).get(cat)
                if prev is None or line < prev:
                    writes[field][cat] = line
        out: list[Finding] = []
        for field, cats in sorted(writes.items()):
            if len(cats) < 2:
                continue
            line = min(
                ln for cat, ln in cats.items() if cat != "main"
            ) if any(c != "main" for c in cats) else min(cats.values())
            names = " and ".join(sorted(cats))
            out.append(self.finding(
                f, line,
                f"{m.cls.name}.{field} is written from {names} threads "
                f"but carries no @guarded_by annotation; annotate it "
                f"(lock or THREAD_OWNER) or pragma with the reason the "
                f"race is benign",
            ))
        return out
