"""deltacache-epoch-keyed: cached plane reads flow through the accessor.

The delta-plane cache (engine/deltacache.py) hands a wave HBM buffers
that are only meaningful against the vocab generation they were filled
at — a stale-generation plane silently encodes RETIRED interned ids
(taint sets, selector values), and a wave that consumes one produces
plausible-looking, wrong binds with no crash to point at the cause.
The module therefore exposes exactly one read path,
``DeltaPlaneCache.planes(gen)``, which raises on a generation mismatch.

This pass pins that contract statically: in device-step code —
``k8s1m_tpu/engine/`` and ``k8s1m_tpu/parallel/`` — any raw read of the
cache's plane attributes (``._mask`` / ``._score``, including their
``__dict__[...]`` / ``getattr`` spellings) is a finding.  Only
``engine/deltacache.py`` itself, where the buffers live and the
accessor is defined, may touch them directly.

Escape hatches (base.py): a ``# graftlint: disable=`` pragma carrying
the reason the raw read is generation-safe, or a baseline entry.
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint.base import Finding, Rule, SourceFile

_PLANE_ATTRS = {"_mask", "_score"}
_SCOPED_DIRS = ("k8s1m_tpu/engine/", "k8s1m_tpu/parallel/")
_OWNER_PATH = "k8s1m_tpu/engine/deltacache.py"

_MSG = (
    "raw read of cached plane attribute {attr!r} — delta planes must be "
    "obtained through the epoch-checked DeltaPlaneCache.planes(gen) "
    "accessor (engine/deltacache.py), never raw attribute access"
)


def _const_plane_name(node: ast.AST) -> str | None:
    """The plane-attribute name when ``node`` is a literal naming one."""
    if isinstance(node, ast.Constant) and node.value in _PLANE_ATTRS:
        return node.value
    return None


class DeltaCacheEpochKeyed(Rule):
    id = "deltacache-epoch-keyed"

    def check_file(self, f: SourceFile) -> list[Finding]:
        if f.path == _OWNER_PATH or not f.path.startswith(_SCOPED_DIRS):
            return []
        out: list[Finding] = []
        for node in ast.walk(f.tree):
            # cache._mask / cache._score — reads only: an Attribute in
            # Store context is the cache module's own state management,
            # which cannot exist outside deltacache.py anyway, but a
            # write through a leaked alias is equally a contract break,
            # so flag every context.
            if isinstance(node, ast.Attribute) and node.attr in _PLANE_ATTRS:
                out.append(
                    self.finding(f, node, _MSG.format(attr=node.attr))
                )
            # getattr(cache, "_mask") / cache.__dict__["_score"]: the
            # dynamic spellings of the same raw read.
            elif isinstance(node, ast.Call):
                fn = node.func
                if (
                    isinstance(fn, ast.Name)
                    and fn.id == "getattr"
                    and len(node.args) >= 2
                ):
                    attr = _const_plane_name(node.args[1])
                    if attr is not None:
                        out.append(
                            self.finding(f, node, _MSG.format(attr=attr))
                        )
            elif isinstance(node, ast.Subscript):
                v = node.value
                if (
                    isinstance(v, ast.Attribute)
                    and v.attr == "__dict__"
                ):
                    attr = _const_plane_name(node.slice)
                    if attr is not None:
                        out.append(
                            self.finding(f, node, _MSG.format(attr=attr))
                        )
        return out
