"""deltacache-epoch-keyed / deltacache-index-keyed: cached buffer reads
flow through their accessors.

The delta-plane cache (engine/deltacache.py) hands a wave HBM buffers
that are only meaningful against the vocab generation they were filled
at — a stale-generation plane silently encodes RETIRED interned ids
(taint sets, selector values), and a wave that consumes one produces
plausible-looking, wrong binds with no crash to point at the cause.
The module therefore exposes exactly one read path per buffer family:
``DeltaPlaneCache.planes(gen)`` for the feasibility/score planes and
``DeltaPlaneCache.index_state(gen)`` for the candidate-index triplet
(rows / class keys / eviction floors) — both raise on a generation
mismatch, and the index accessor is additionally the seam where the
fail-closed floor contract lives (a raw floor read can't tell
INDEX_FLOOR_UNBUILT from a real class key).

These passes pin that contract statically: in device-step code —
``k8s1m_tpu/engine/`` and ``k8s1m_tpu/parallel/`` — any raw read of the
cache's plane attributes (``._mask`` / ``._score``) or index attributes
(``._idx_row`` / ``._idx_class`` / ``._idx_floor``), including their
``__dict__[...]`` / ``getattr`` spellings, is a finding.  Only
``engine/deltacache.py`` itself, where the buffers live and the
accessors are defined, may touch them directly.

Escape hatches (base.py): a ``# graftlint: disable=`` pragma carrying
the reason the raw read is generation-safe, or a baseline entry.
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint.base import Finding, Rule, SourceFile

_PLANE_ATTRS = {"_mask", "_score"}
_INDEX_ATTRS = {"_idx_row", "_idx_class", "_idx_floor"}
_SCOPED_DIRS = ("k8s1m_tpu/engine/", "k8s1m_tpu/parallel/")
_OWNER_PATH = "k8s1m_tpu/engine/deltacache.py"

_PLANE_MSG = (
    "raw read of cached plane attribute {attr!r} — delta planes must be "
    "obtained through the epoch-checked DeltaPlaneCache.planes(gen) "
    "accessor (engine/deltacache.py), never raw attribute access"
)
_INDEX_MSG = (
    "raw read of candidate-index attribute {attr!r} — the index triplet "
    "must be obtained through the epoch-checked "
    "DeltaPlaneCache.index_state(gen) accessor (engine/deltacache.py), "
    "never raw attribute access (a raw floor read also bypasses the "
    "fail-closed INDEX_FLOOR_UNBUILT contract)"
)


def _const_name(node: ast.AST, attrs: set[str]) -> str | None:
    """The attribute name when ``node`` is a literal naming one."""
    if isinstance(node, ast.Constant) and node.value in attrs:
        return node.value
    return None


def _raw_attr_findings(
    rule: Rule, f: SourceFile, attrs: set[str], msg: str
) -> list[Finding]:
    if f.path == _OWNER_PATH or not f.path.startswith(_SCOPED_DIRS):
        return []
    out: list[Finding] = []
    for node in ast.walk(f.tree):
        # cache._mask / cache._idx_row — reads only: an Attribute in
        # Store context is the cache module's own state management,
        # which cannot exist outside deltacache.py anyway, but a
        # write through a leaked alias is equally a contract break,
        # so flag every context.
        if isinstance(node, ast.Attribute) and node.attr in attrs:
            out.append(rule.finding(f, node, msg.format(attr=node.attr)))
        # getattr(cache, "_mask") / cache.__dict__["_idx_floor"]: the
        # dynamic spellings of the same raw read.
        elif isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Name)
                and fn.id == "getattr"
                and len(node.args) >= 2
            ):
                attr = _const_name(node.args[1], attrs)
                if attr is not None:
                    out.append(rule.finding(f, node, msg.format(attr=attr)))
        elif isinstance(node, ast.Subscript):
            v = node.value
            if isinstance(v, ast.Attribute) and v.attr == "__dict__":
                attr = _const_name(node.slice, attrs)
                if attr is not None:
                    out.append(rule.finding(f, node, msg.format(attr=attr)))
    return out


class DeltaCacheEpochKeyed(Rule):
    id = "deltacache-epoch-keyed"

    def check_file(self, f: SourceFile) -> list[Finding]:
        return _raw_attr_findings(self, f, _PLANE_ATTRS, _PLANE_MSG)


class DeltaCacheIndexKeyed(Rule):
    id = "deltacache-index-keyed"

    def check_file(self, f: SourceFile) -> list[Finding]:
        return _raw_attr_findings(self, f, _INDEX_ATTRS, _INDEX_MSG)
