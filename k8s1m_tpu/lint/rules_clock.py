"""no-wall-clock: deterministic code must not read the wall clock.

Three tiers, strictest first:

1. **Virtual-clock dirs** (``faultline/``, ``loadshed/``,
   ``tools/overload_drill.py``, ``tests/``): any ``time.time()`` or
   argless ``datetime.now()``/``utcnow()`` is flagged.  Determinism by
   seed is the contract there — drills and fault plans replay the same
   trajectory from the same seed, which a wall-clock read silently
   breaks.
2. **Durations anywhere**: a subtraction whose operand came from
   ``time.time()`` (directly, via a local name, or via a ``self.``
   attribute assigned in the same class) is flagged — wall clocks step
   (NTP, leap smearing); durations must use ``time.monotonic()`` /
   ``perf_counter()``.
3. **Everything else**: a bare ``time.time()`` is still flagged, so
   every wall-clock read in the tree is either converted or carries a
   pragma naming its reason (timestamps for cross-process correlation
   are legitimate — and now auditable).
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint.base import Finding, Rule, SourceFile, dotted_name

VIRTUAL_CLOCK_PATHS = (
    "k8s1m_tpu/faultline/",
    "k8s1m_tpu/loadshed/",
    "k8s1m_tpu/tools/overload_drill.py",
    "tests/",
)

_WALL_CALLS = {"time.time"}
_DATETIME_NOW = {"datetime.now", "datetime.datetime.now",
                 "datetime.utcnow", "datetime.datetime.utcnow"}


def _is_wall_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call) and dotted_name(node.func) in _WALL_CALLS
    )


class NoWallClock(Rule):
    id = "no-wall-clock"

    def check_file(self, f: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        banned_dir = f.path.startswith(VIRTUAL_CLOCK_PATHS)

        # Names/attrs assigned from time.time(), for the duration check.
        wall_names: set[str] = set()       # local/global names
        wall_attrs: set[str] = set()       # self.<attr> within a class
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Assign) and _is_wall_call(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        wall_names.add(tgt.id)
                    elif (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        wall_attrs.add(tgt.attr)

        def is_wall_operand(n: ast.AST) -> bool:
            if _is_wall_call(n):
                return True
            if isinstance(n, ast.Name) and n.id in wall_names:
                return True
            return (
                isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == "self"
                and n.attr in wall_attrs
            )

        duration_lines: set[int] = set()
        for node in ast.walk(f.tree):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                if is_wall_operand(node.left) or is_wall_operand(node.right):
                    duration_lines.add(node.lineno)
                    out.append(self.finding(
                        f, node,
                        "duration computed from time.time(); wall clocks "
                        "step — use time.monotonic()/perf_counter()",
                    ))

        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in _WALL_CALLS:
                if node.lineno in duration_lines:
                    continue        # already reported as a duration
                if banned_dir:
                    out.append(self.finding(
                        f, node,
                        "wall-clock read in virtual-clock code "
                        "(determinism-by-seed is the contract here; use "
                        "the tick clock or an injected clock)",
                    ))
                else:
                    out.append(self.finding(
                        f, node,
                        "time.time(): use time.monotonic() for "
                        "durations, or pragma a deliberate wall-clock "
                        "timestamp with its reason",
                    ))
            elif (
                banned_dir
                and name in _DATETIME_NOW
                and not node.args
                and not node.keywords
            ):
                out.append(self.finding(
                    f, node,
                    "argless datetime.now() in virtual-clock code "
                    "(wall clock + naive tz; use the injected clock)",
                ))
        return out
