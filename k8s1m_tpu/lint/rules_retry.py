"""retry-through-policy: all retries flow through faultline.RetryPolicy.

PR 1's whole point was ONE resilience policy — capped exponential
backoff, jitter, deadline budget, give-up metrics — replacing every
hand-rolled loop.  This rule keeps it that way: a ``while``/``for``
loop whose ``except`` handler sleeps (the classic hand-rolled retry
shape) is flagged unless the sleep duration is derived from a
``RetryPolicy`` (``delay_for(...)`` taint), because an ad-hoc constant
backoff re-introduces exactly the thundering-herd and silent-give-up
bugs the policy centralizes away.

``faultline/policy.py`` itself is exempt — it IS the policy.
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint.base import (
    Finding,
    Rule,
    SourceFile,
    dotted_name,
    walk_no_nested_functions,
)

EXEMPT_PATHS = ("k8s1m_tpu/faultline/policy.py",)

_SLEEP_CALLEES = {"time.sleep", "sleep", "asyncio.sleep"}


def _sleep_calls(node: ast.AST) -> list[ast.Call]:
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and dotted_name(n.func) in _SLEEP_CALLEES:
            out.append(n)
    return out


class RetryThroughPolicy(Rule):
    id = "retry-through-policy"

    def check_file(self, f: SourceFile) -> list[Finding]:
        if f.path in EXEMPT_PATHS:
            return []
        out: list[Finding] = []
        reported: set[int] = set()
        for scope in ast.walk(f.tree):
            if not isinstance(
                scope, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)
            ):
                continue
            # Names tainted by RetryPolicy pacing within this scope.
            policy_names = self._policy_tainted(scope)
            for node in walk_no_nested_functions(scope):
                if not isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
                    continue
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Try):
                        continue
                    for handler in sub.handlers:
                        for call in _sleep_calls(handler):
                            if id(call) in reported:
                                continue            # nested-loop re-visit
                            reported.add(id(call))
                            if self._policy_paced(call, policy_names):
                                continue
                            out.append(self.finding(
                                f, call,
                                "hand-rolled retry (loop + except + "
                                "sleep); route through faultline "
                                "RetryPolicy.call / delay_for so backoff, "
                                "jitter, deadline and give-up metrics "
                                "stay centralized",
                            ))
        return out

    @staticmethod
    def _policy_tainted(scope: ast.AST) -> set[str]:
        """Names assigned from an expression mentioning ``delay_for`` or
        ``policy_for`` anywhere in this scope."""
        names: set[str] = set()
        for n in walk_no_nested_functions(scope):
            if isinstance(n, ast.Assign):
                mentions = any(
                    isinstance(m, ast.Attribute) and m.attr == "delay_for"
                    or isinstance(m, ast.Name)
                    and m.id in ("delay_for", "policy_for")
                    for m in ast.walk(n.value)
                )
                if mentions:
                    for tgt in n.targets:
                        if isinstance(tgt, ast.Name):
                            names.add(tgt.id)
        return names

    @staticmethod
    def _policy_paced(call: ast.Call, policy_names: set[str]) -> bool:
        if not call.args:
            return False
        arg = call.args[0]
        for m in ast.walk(arg):
            if isinstance(m, ast.Attribute) and m.attr == "delay_for":
                return True
            if isinstance(m, ast.Name) and (
                m.id in policy_names or m.id in ("delay_for",)
            ):
                return True
        return False
