"""undonated-device-update: jitted table updates must donate their buffers.

The devicestate contract (ISSUE 10): every per-wave mutation of the
device node table / constraint tables — commit_binds' request-column
adds, the dirty-row churn scatter, constraint-count corrections — flows
through a jitted function that RETURNS the updated table.  Without
``donate_argnums`` each such call is copy-on-write: XLA materializes a
second full table in HBM per wave, which at 1M rows is both the memory
ceiling and a per-wave bandwidth tax.  This rule keeps the donation
funnel airtight statically: inside the production device-update modules
(engine/, snapshot/, control/, parallel/), a ``jax.jit(...)`` call whose
wrapped callable (transitively, within the file) reaches one of the
table-update primitives must pass ``donate_argnums``/``donate_argnames``
— or carry the usual pragma with a reason.

Legitimate non-donating variants exist and are pragma'd where they
live: replay/differential surfaces (tests re-run one table; donation
would delete it).  The mesh executables are NOT among them since
meshpack — out_shardings pinning and donation compose (XLA aliases
shard-by-shard), so the production sharded step/scatter/adjust all
donate.  The pragma forces each remaining exception to say WHY, which
is the point.

Resolution is name-based and file-local (the graftlint house style —
see rules_fence.py): the wrapped callable is resolved through direct
names, named lambdas, aliases, and ``functools.partial``; a function is
"table-updating" when its body (or anything it calls, to a file-local
fixpoint) calls one of UPDATE_PRIMITIVES.
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint.base import (
    Finding,
    Rule,
    SourceFile,
    call_name,
    dotted_name,
)

SCOPE_PREFIXES = (
    "k8s1m_tpu/engine/",
    "k8s1m_tpu/snapshot/",
    "k8s1m_tpu/control/",
    "k8s1m_tpu/parallel/",
)

# Callables that produce an UPDATED NodeTable / constraint table.  The
# cross-module links (finalize_batch -> commit_binds etc.) are encoded
# here by name so a file that imports and jits them is still covered.
UPDATE_PRIMITIVES = {
    "commit_binds",
    "scatter_rows",
    "apply_delta",
    "commit_constraint_binds",
    "adjust_constraints_impl",
    "finalize_batch",
    "_schedule_batch_impl",
}

DONATE_KWARGS = {"donate_argnums", "donate_argnames"}


def _called_names(node: ast.AST) -> set[str]:
    out: set[str] = set()
    for c in ast.walk(node):
        if isinstance(c, ast.Call):
            n = call_name(c)
            if n is not None:
                out.add(n)
    return out


def _callable_slots(call: ast.Call) -> list[ast.expr]:
    """The argument positions that can hold a wrapped callable: first
    positional, or jit's keyword spelling (``jax.jit(fun=impl)``).
    Shared by alias resolution and jit-site detection so the slot rule
    can never desynchronize between them."""
    return list(call.args[:1]) + [
        kw.value for kw in call.keywords if kw.arg == "fun"
    ]


class UndonatedDeviceUpdate(Rule):
    id = "undonated-device-update"

    def check_file(self, f: SourceFile) -> list[Finding]:
        if not f.path.startswith(SCOPE_PREFIXES):
            return []
        # name -> names it calls (defs, named lambdas, plain aliases).
        calls_of: dict[str, set[str]] = {}
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                calls_of.setdefault(node.name, set()).update(
                    _called_names(node)
                )
            elif isinstance(node, ast.Assign):
                names = [
                    t.id for t in node.targets if isinstance(t, ast.Name)
                ]
                if not names:
                    continue
                if isinstance(node.value, ast.Lambda):
                    got = _called_names(node.value)
                elif isinstance(node.value, ast.Name):
                    got = {node.value.id}      # alias: fn = impl
                elif isinstance(node.value, ast.Call):
                    # Wrapper aliasing: fn = shard_map_compat(impl, ...)
                    # / step = jax.jit(impl, ...) — the bound name
                    # reaches the wrapped callable, so a later jit of
                    # the wrapper is still covered.  Only the callable
                    # SLOT aliases (first positional, or jit's ``fun=``
                    # spelling) — treating every argument as the
                    # wrapped callable would make plain-data uses of an
                    # updater name (`make_runner(cfg, scatter_rows)`)
                    # false-positive.
                    got = set()
                    for a in _callable_slots(node.value):
                        if isinstance(a, ast.Name):
                            got.add(a.id)
                        elif isinstance(a, ast.Lambda):
                            got |= _called_names(a)
                    if not got:
                        continue
                else:
                    continue
                for n in names:
                    calls_of.setdefault(n, set()).update(got)
        # File-local fixpoint over "reaches an update primitive".
        updaters = set(UPDATE_PRIMITIVES)
        changed = True
        while changed:
            changed = False
            for name, calls in calls_of.items():
                if name not in updaters and calls & updaters:
                    updaters.add(name)
                    changed = True

        def wraps_updater(arg: ast.AST) -> bool:
            if isinstance(arg, ast.Name):
                return arg.id in updaters
            if isinstance(arg, ast.Lambda):
                return bool(_called_names(arg) & updaters)
            if isinstance(arg, ast.Call) and call_name(arg) == "partial":
                return any(
                    isinstance(a, ast.Name) and a.id in updaters
                    for a in arg.args
                )
            return False

        MSG = (
            "jitted function returns an updated device table but "
            "does not donate its input buffers (donate_argnums): "
            "every wave pays a full copy-on-write table in HBM.  "
            "Donate (out_shardings pinning composes with donation), "
            "or pragma with the reason this call site must keep its "
            "inputs alive (replay surface)"
        )

        def jit_decorator(dec) -> tuple[bool, bool]:
            """(is_jit, donates) for a decorator node — the @jax.jit,
            @jax.jit(...), and @functools.partial(jax.jit, ...) house
            spellings all count; a bare decorator can never donate."""
            if isinstance(dec, (ast.Name, ast.Attribute)):
                return dotted_name(dec) in ("jax.jit", "jit"), False
            if isinstance(dec, ast.Call):
                donates = any(
                    kw.arg in DONATE_KWARGS for kw in dec.keywords
                )
                if dotted_name(dec.func) in ("jax.jit", "jit"):
                    return True, donates
                if call_name(dec) == "partial" and any(
                    isinstance(a, (ast.Name, ast.Attribute))
                    and dotted_name(a) in ("jax.jit", "jit")
                    for a in dec.args
                ):
                    return True, donates
            return False, False

        out: list[Finding] = []
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Call):
                if dotted_name(node.func) not in ("jax.jit", "jit"):
                    continue
                if any(kw.arg in DONATE_KWARGS for kw in node.keywords):
                    continue
                if not any(wraps_updater(a) for a in _callable_slots(node)):
                    continue
                out.append(self.finding(f, node, MSG))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Decorator spellings: @jax.jit / @functools.partial(
                # jax.jit, ...) over a table-updating def is the same
                # copy-on-write hole as the call form.
                if node.name not in updaters:
                    continue
                for dec in node.decorator_list:
                    is_jit, donates = jit_decorator(dec)
                    if is_jit and not donates:
                        out.append(self.finding(f, dec, MSG))
        return out
