"""nondet-to-placement: no nondeterministic value reaches a placement
decision.

The byte-identity contract (mesh == single-device, packed == unpacked,
delta/index == full recompute) holds because every placement input is a
deterministic function of (store state, wave seed).  mesh-purity
enforces one corner of that — axis-derived values in shard_map code —
but every regression so far entered through a DIFFERENT corner:
fold_mesh_key (PR 6), the stratum-width collapse (PR 18), wall-stamp
tie-breaks.  This pass is the general statement, on the flow.py
chassis: taint from any **nondeterminism source**

- wall/monotonic clock reads (``time.time``/``monotonic``/
  ``perf_counter`` and friends, argless ``datetime.now``),
- unseeded module-global RNG (``random.*``, ``np.random.*``,
  ``os.urandom``, ``uuid.uuid4``, ``secrets.*``),
- object identity (``id()``) and thread-timing values (``qsize()``),
- set-iteration order (a for/comprehension target over a provably-set
  value; ``sorted(...)`` launders this one, and only this one),

must not flow — through any chain of local bindings, or through an
intra-repo helper whose RETURN derives from a source — into a
**placement sink** inside ``engine/ parallel/ ops/ snapshot/
tenancy/``:

- ``filter_score_topk`` / ``pallas_candidates`` (candidate selection),
- ``hash_jitter`` / ``seed_of`` (tie-break hashing),
- ``commit_binds`` / ``bind_batch`` / ``_fenced_cas`` /
  ``_fenced_bind_batch`` (store-visible placement writes),
- ``select_preemption`` / ``victim_sort_key`` (victim selection),
- any ``seed=`` / ``key=`` keyword argument anywhere in scope.

One level of helper propagation runs on the sink side too: passing a
tainted value to an intra-repo helper that forwards that parameter
into a sink within its own body is flagged at the call site.

Blessed sources: ``mesh_offsets(...)`` (the sanctioned laundering
point — the hash *base* globalizes, the key does not vary) and seeded
draws on rng objects (``self._rng.random()`` — receiver-qualified
calls never match the module-global patterns by construction).
Timestamps kept for telemetry are fine: taint only matters when it
reaches a sink.  Escapes: ``# graftlint: disable=nondet-to-placement``
with a reason, or a baseline entry.
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint import flow
from k8s1m_tpu.lint.base import (
    Finding,
    Rule,
    SourceFile,
    call_name,
    dotted_name,
)

SCOPE_DIRS = (
    "k8s1m_tpu/engine/", "k8s1m_tpu/parallel/", "k8s1m_tpu/ops/",
    "k8s1m_tpu/snapshot/", "k8s1m_tpu/tenancy/",
)

_CLOCK_CALLS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.now", "datetime.datetime.now",
    "datetime.utcnow", "datetime.datetime.utcnow",
}
# Module-global RNG prefixes; the leaf exemptions are the *seeded*
# constructors (random.Random(s), np.random.default_rng(s)).
_RNG_PREFIXES = ("random.", "np.random.", "numpy.random.")
_RNG_EXEMPT_LEAVES = {"Random", "default_rng", "seed"}
_MISC_SOURCES = {"os.urandom", "uuid.uuid4", "uuid.uuid1"}

_SINK_CALLS = {
    "filter_score_topk", "pallas_candidates", "hash_jitter", "seed_of",
    "commit_binds", "bind_batch", "_fenced_cas", "_fenced_bind_batch",
    "select_preemption", "victim_sort_key",
}
_SINK_KWARGS = {"seed", "key"}
_BLESSED = "mesh_offsets"


def _source_kind(node: ast.AST) -> str | None:
    """The nondeterminism kind a single node introduces, else None."""
    if not isinstance(node, ast.Call):
        return None
    d = dotted_name(node.func)
    if d in _CLOCK_CALLS:
        return f"clock read {d}()"
    if d in _MISC_SOURCES:
        return f"{d}()"
    if d is not None and d.startswith(_RNG_PREFIXES):
        if d.rsplit(".", 1)[-1] not in _RNG_EXEMPT_LEAVES:
            return f"unseeded global RNG {d}()"
    if d is not None and d.startswith("secrets."):
        return f"{d}()"
    if isinstance(node.func, ast.Name) and node.func.id == "id" and (
        node.args
    ):
        return "id() (object identity varies per process)"
    if call_name(node) == "qsize":
        return "qsize() (thread-timing value)"
    return None


def _launders_value(value: ast.AST) -> bool:
    return isinstance(value, ast.Call) and call_name(value) == _BLESSED


def _launders_order(value: ast.AST) -> bool:
    if _launders_value(value):
        return True
    return isinstance(value, ast.Call) and call_name(value) == "sorted"


class NondetToPlacement(Rule):
    id = "nondet-to-placement"

    def check_tree(self, files: list[SourceFile]) -> list[Finding]:
        cg = flow.CallGraph(files)
        memo: dict[str, bool] = {}

        def node_is_source(node: ast.AST) -> bool:
            return _source_kind(node) is not None

        def contains_source(expr: ast.AST) -> bool:
            """Directly nondeterministic, or a call into an intra-repo
            helper whose return value derives from a source."""
            for sub in ast.walk(expr):
                if node_is_source(sub):
                    return True
                if isinstance(sub, ast.Call):
                    callee = cg.target_of(sub)
                    if callee is not None and cg.returns_matching(
                        callee, node_is_source, _memo=memo
                    ):
                        return True
            return False

        # One-level helper propagation on the sink side: which params
        # of a callee flow into a sink inside its own body?
        sink_params_memo: dict[str, frozenset[str]] = {}

        def sink_params(key: str) -> frozenset[str]:
            got = sink_params_memo.get(key)
            if got is not None:
                return got
            sink_params_memo[key] = frozenset()     # cycle guard
            fn = cg.funcs.get(key)
            if fn is None:
                return frozenset()
            params = [a.arg for a in fn.node.args.args
                      if a.arg not in ("self", "cls")]
            hit: set[str] = set()
            for node in flow.own_body(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                for arg in self._sink_args(node):
                    for p in params:
                        if flow.mentions(arg, {p}):
                            hit.add(p)
            out = frozenset(hit)
            sink_params_memo[key] = out
            return out

        out: list[Finding] = []
        for f in files:
            if not f.path.startswith(SCOPE_DIRS):
                continue
            for node in ast.walk(f.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.extend(self._check_func(
                        f, node, contains_source, cg, sink_params
                    ))
        out.sort(key=lambda fd: (fd.path, fd.line))
        return out

    # -- per-function analysis -------------------------------------------

    def _sink_args(self, call: ast.Call) -> list[ast.AST]:
        """The arguments of ``call`` that feed a placement decision."""
        name = call_name(call)
        if name in _SINK_CALLS:
            return list(call.args) + [kw.value for kw in call.keywords]
        return [
            kw.value for kw in call.keywords if kw.arg in _SINK_KWARGS
        ]

    def _check_func(
        self, f: SourceFile, fn, contains_source, cg, sink_params
    ) -> list[Finding]:
        out: list[Finding] = []
        bindings = flow.collect_bindings(fn)
        # Value nondeterminism: clocks, RNG, id(), thread timing.
        value_tainted = flow.taint_fixpoint(
            bindings,
            contains_source=contains_source,
            launders=_launders_value,
        )
        # Order nondeterminism: names bound by iterating a set.
        # sorted(...) launders THIS taint (a sorted set is
        # deterministic); it does not launder a clock value.
        order_seeds: set[str] = set()
        for _node, tgt in flow.iterations_over_sets(fn):
            for sub in ast.walk(tgt):
                if isinstance(sub, ast.Name):
                    order_seeds.add(sub.id)
        order_tainted = flow.taint_fixpoint(
            bindings,
            contains_source=lambda e: False,
            launders=_launders_order,
            seeds=order_seeds,
        )

        def taint_of(expr: ast.AST) -> str | None:
            if flow.expr_tainted(expr, value_tainted, contains_source):
                return "a nondeterministic value (clock/RNG/identity)"
            if flow.mentions(expr, order_tainted):
                return "set-iteration order"
            return None

        for node in flow.own_body(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            for arg in self._sink_args(node):
                why = taint_of(arg)
                if why is not None:
                    out.append(self.finding(
                        f, node,
                        f"{why} flows into {name}() — placement "
                        f"decisions must be a deterministic function of "
                        f"(store state, wave seed) or byte-identity "
                        f"dies; derive the input from seeded state, or "
                        f"pragma with the reason",
                    ))
                    break
            else:
                # One-level helper propagation: tainted value handed to
                # a helper that forwards that parameter into a sink.
                key = cg.target_of(node)
                if key is None:
                    continue
                fwd = sink_params(key)
                if not fwd:
                    continue
                callee = cg.funcs[key]
                params = [a.arg for a in callee.node.args.args
                          if a.arg not in ("self", "cls")]
                hit = None
                for i, arg in enumerate(node.args):
                    if i < len(params) and params[i] in fwd:
                        hit = taint_of(arg)
                        if hit is not None:
                            break
                if hit is None:
                    for kw in node.keywords:
                        if kw.arg in fwd:
                            hit = taint_of(kw.value)
                            if hit is not None:
                                break
                if hit is not None:
                    out.append(self.finding(
                        f, node,
                        f"{hit} flows through helper "
                        f"{callee.qual}() into a placement sink — same "
                        f"contract as a direct sink call; seed the "
                        f"input or pragma with the reason",
                    ))
        return out
