"""Device-hot-path rules: hot-path-host-sync and trace-time-branch.

Both rules need the same structural fact: *which functions are jitted
regions*.  A function counts as jitted when it is

- decorated with ``@jax.jit`` / ``@pjit`` / ``@functools.partial(jax.jit,
  ...)``, or
- passed (possibly through one local name alias) as the first argument
  of a ``jax.jit(...)`` / ``pjit(...)`` call anywhere in the module —
  the assignment-wrapped idiom this codebase favors
  (``_scatter_rows = jax.jit(scatter_rows)``, ``return jax.jit(fn)``).

``static_argnames`` from the jit call/decorator are honored: branching
on a static argument is exactly what static args are for.
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint.base import Finding, Rule, SourceFile, dotted_name

_JIT_CALLEES = {"jax.jit", "jit", "pjit", "jax.pjit"}

# Paths (repo-relative prefixes) whose code feeds compiled TPU cycles:
# a host sync here stalls the pipeline for every wave behind it.
HOT_DIRS = (
    "k8s1m_tpu/engine/",
    "k8s1m_tpu/parallel/",
    "k8s1m_tpu/plugins/",
    "k8s1m_tpu/snapshot/",
)

# The host mirror: NodeTableHost's numpy columns ARE host state by
# design (the authoritative side of the epoch-buffered snapshot), so
# host<->device staging there is the mechanism, not a leak.
HOT_ALLOWLIST = ("k8s1m_tpu/snapshot/node_table.py",)


def _is_jit_call(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    if name in _JIT_CALLEES:
        return True
    # functools.partial(jax.jit, ...)
    if name in ("functools.partial", "partial") and call.args:
        inner = dotted_name(call.args[0])
        return inner in _JIT_CALLEES
    return False


def _static_names(call: ast.Call) -> set[str]:
    out: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    out.add(n.value)
    return out


def jitted_functions(tree: ast.AST) -> list[tuple[ast.AST, set[str]]]:
    """(function node, static arg names) for every jitted region.

    Lambdas passed to jit are included (host-sync calls can hide in
    them even though they cannot hold if/while statements).
    """
    # Pass 1: name -> FunctionDef, and alias -> name (one level).
    defs: dict[str, ast.AST] = {}
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Name):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    aliases[tgt.id] = node.value.id
        elif isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Lambda
        ):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    defs.setdefault(tgt.id, node.value)

    regions: dict[int, tuple[ast.AST, set[str]]] = {}

    def add(fn: ast.AST, statics: set[str]) -> None:
        key = id(fn)
        if key in regions:
            regions[key][1].update(statics)
        else:
            regions[key] = (fn, set(statics))

    for node in ast.walk(tree):
        # Decorator form.
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and _is_jit_call(dec):
                    add(node, _static_names(dec))
                elif dotted_name(dec) in _JIT_CALLEES:
                    add(node, set())
        # Call form: jax.jit(fn_or_lambda, ...).
        if isinstance(node, ast.Call) and _is_jit_call(node) and node.args:
            target = node.args[0]
            statics = _static_names(node)
            if isinstance(target, ast.Lambda):
                add(target, statics)
            else:
                name = dotted_name(target)
                if name is not None:
                    name = aliases.get(name, name)
                    fn = defs.get(name)
                    if fn is not None:
                        add(fn, statics)
    return list(regions.values())


def _params_of(fn: ast.AST) -> list[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return [n for n in names if n != "self"]


class HotPathHostSync(Rule):
    """Forbid host-synchronizing calls where compiled cycles live.

    ``.item()``, ``jax.device_get`` and ``.block_until_ready()`` force a
    device->host round trip wherever they appear in the hot dirs; a
    single one in the cycle path silently collapses the pipelined
    scheduler to depth-1 (each wave blocks on the previous fetch).
    ``np.asarray``/``np.array`` and ``float()/int()/bool()`` coercions
    are flagged only inside jitted regions, where they would pull a
    tracer to the host at trace time.
    """

    id = "hot-path-host-sync"

    _SYNC_CALLS = {"jax.device_get"}
    _SYNC_METHODS = {"item", "block_until_ready"}
    _TRACE_COERCE_CALLS = {"np.asarray", "np.array", "numpy.asarray",
                           "numpy.array"}
    _TRACE_COERCE_BUILTINS = {"float", "int", "bool"}

    def check_file(self, f: SourceFile) -> list[Finding]:
        if not f.path.startswith(HOT_DIRS) or f.path in HOT_ALLOWLIST:
            return []
        out: list[Finding] = []
        jit_nodes: set[int] = set()
        for fn, _statics in jitted_functions(f.tree):
            for n in ast.walk(fn):
                jit_nodes.add(id(n))
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in self._SYNC_CALLS:
                out.append(self.finding(
                    f, node,
                    f"{name}() is a device->host sync on the hot path "
                    "(collapses the pipeline to depth-1)",
                ))
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in self._SYNC_METHODS
                and not node.args
            ):
                out.append(self.finding(
                    f, node,
                    f".{node.func.attr}() is a device->host sync on the "
                    "hot path (collapses the pipeline to depth-1)",
                ))
                continue
            if id(node) in jit_nodes:
                if name in self._TRACE_COERCE_CALLS:
                    out.append(self.finding(
                        f, node,
                        f"{name}() inside a jitted region pulls the value "
                        "to host at trace time (use jnp, or hoist out of "
                        "the jit)",
                    ))
                elif (
                    name in self._TRACE_COERCE_BUILTINS
                    and node.args
                    and not isinstance(node.args[0], ast.Constant)
                ):
                    out.append(self.finding(
                        f, node,
                        f"{name}() coercion inside a jitted region "
                        "concretizes a tracer (host sync at trace time)",
                    ))
        return out


class TraceTimeBranch(Rule):
    """Python ``if``/``while`` on a traced argument inside a jitted
    region: either a latent ConcretizationTypeError or — worse — a
    silent per-value recompile if the value is weakly typed.  ``is
    None`` / ``is not None`` structure checks are trace-safe (pytree
    structure is static) and exempt, as are ``static_argnames``.
    """

    id = "trace-time-branch"

    def check_file(self, f: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        for fn, statics in jitted_functions(f.tree):
            if isinstance(fn, ast.Lambda):
                continue            # lambdas cannot hold statements
            traced = set(_params_of(fn)) - statics
            if not traced:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                names = self._suspect_names(node.test, traced)
                if names:
                    kind = "while" if isinstance(node, ast.While) else "if"
                    out.append(super().finding(
                        f, node,
                        f"python `{kind}` on traced argument(s) "
                        f"{sorted(names)} inside a jitted region (use "
                        "jnp.where/lax.cond, or mark static)",
                    ))
        return out

    @staticmethod
    def _suspect_names(test: ast.AST, traced: set[str]) -> set[str]:
        """Traced params referenced by ``test`` outside an ``is``
        comparison."""
        exempt: set[int] = set()
        for n in ast.walk(test):
            if isinstance(n, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops
            ):
                for sub in ast.walk(n):
                    exempt.add(id(sub))
            elif isinstance(n, ast.Call) and dotted_name(n.func) in (
                "isinstance", "len", "getattr", "hasattr",
            ):
                # Structure/arity checks resolve at trace time.
                for sub in ast.walk(n):
                    exempt.add(id(sub))
        return {
            n.id
            for n in ast.walk(test)
            if isinstance(n, ast.Name)
            and n.id in traced
            and id(n) not in exempt
        }
