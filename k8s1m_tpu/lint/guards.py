"""Runtime lock discipline: ``@guarded_by`` annotations + audit mode.

Static analysis can prove a ``.item()`` never ships; it cannot prove the
webhook handler thread never touches the cycle thread's wave list.  This
module closes that gap with *declared* lock discipline, checked by a
test-only instrumentation mode:

    from k8s1m_tpu.lint import guarded_by, THREAD_OWNER

    @guarded_by(
        _external="_external_lock",   # only while self._external_lock held
        _inflights=THREAD_OWNER,      # only from the owning thread
    )
    class Coordinator: ...

Guard kinds:

- ``"_lock_attr"`` — the field may be read or written only while the
  named instance lock is held *by the current thread*.  Lock holding is
  tracked by returning a thin tracking proxy from the lock attribute
  while auditing (works for Lock and RLock alike, no reliance on
  ``_is_owned``).
- ``THREAD_OWNER`` — the field is thread-confined: the first thread to
  touch any owner-guarded field of the instance claims ownership; any
  other thread's access raises.  ``set_owner(obj)`` re-claims for the
  current thread, ``disown(obj)`` clears the claim (legitimate handoff).

Production cost is zero: ``guarded_by`` only records the annotation.
``audit()`` (a context manager; tests only) patches each annotated
class's ``__getattribute__``/``__setattr__`` with checking versions and
restores the originals on exit.  Violations BOTH raise
``GuardViolation`` and append to ``violations()`` — a raise inside a
server handler thread is usually swallowed by that handler's own error
path, so the stress test asserts on the recorded list.
"""

from __future__ import annotations

import contextlib
import threading

THREAD_OWNER = "<thread-owner>"
_OWNER_KEY = "__guard_owner_tid__"

_registry: list[type] = []
_patched: dict[type, tuple] = {}
_enabled = False
_violations: list[str] = []
_state_lock = threading.Lock()
_tls = threading.local()


class GuardViolation(AssertionError):
    """A guarded field was accessed without its declared protection."""


def guarded_by(**fields: str):
    """Class decorator declaring per-field guards (see module doc)."""

    def deco(cls: type) -> type:
        merged: dict[str, str] = {}
        for base in reversed(cls.__mro__[1:]):
            merged.update(getattr(base, "__guards__", None) or {})
        merged.update(fields)
        cls.__guards__ = merged
        with _state_lock:
            _registry.append(cls)
            if _enabled:
                _patch(cls)
        return cls

    return deco


def violations() -> list[str]:
    return list(_violations)


def racy_read(obj, name: str):
    """Deliberate unguarded read of a guarded field, bypassing the audit.

    For monitoring paths ONLY (metrics scrape callbacks, debug dumps):
    a ``len()`` of a list/deque owned by another thread is a benign
    torn-snapshot read under CPython, and a scrape must neither block on
    the cycle thread's locks nor count as a discipline violation.  The
    explicit call is the audit record — grep ``racy_read`` to enumerate
    every sanctioned unguarded access.  Never use it to *mutate*, or to
    read state whose torn value feeds a control decision.
    """
    return object.__getattribute__(obj, name)


def audit_enabled() -> bool:
    return _enabled


def set_owner(obj) -> None:
    """Claim (or re-claim) owner-guarded fields of ``obj`` for the
    current thread — the explicit handoff when an object is built on
    one thread and driven from another."""
    obj.__dict__[_OWNER_KEY] = threading.get_ident()


def disown(obj) -> None:
    obj.__dict__.pop(_OWNER_KEY, None)


# ---- lock-holding ledger ----------------------------------------------


def _held_map() -> dict[int, int]:
    m = getattr(_tls, "held", None)
    if m is None:
        m = _tls.held = {}
    return m


class _TrackedLock:
    """Context-manager/acquire-release proxy that records holding in a
    thread-local ledger keyed by the REAL lock's id (so every proxy of
    the same lock agrees)."""

    __slots__ = ("_lk",)

    def __init__(self, lk):
        object.__setattr__(self, "_lk", lk)

    def acquire(self, *a, **kw):
        ok = self._lk.acquire(*a, **kw)
        if ok:
            m = _held_map()
            m[id(self._lk)] = m.get(id(self._lk), 0) + 1
        return ok

    def release(self):
        m = _held_map()
        n = m.get(id(self._lk), 0)
        if n <= 1:
            m.pop(id(self._lk), None)
        else:
            m[id(self._lk)] = n - 1
        self._lk.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lk.locked()

    def __getattr__(self, name):
        return getattr(self._lk, name)


def _note_violation(msg: str) -> None:
    with _state_lock:
        _violations.append(msg)
    raise GuardViolation(msg)


# ---- class patching ----------------------------------------------------


def _unwrap(fn):
    """Skip any checking wrappers inherited from an already-patched base
    class — a subclass's wrapper must delegate to REAL behavior, and
    unpatching must never mistake a parent's wrapper for an original."""
    while getattr(fn, "_graftlint_wrapper", False):
        fn = fn.__wrapped__
    return fn


def _patch(cls: type) -> None:
    if cls in _patched:
        return
    guards: dict[str, str] = cls.__guards__
    lock_attrs = {g for g in guards.values() if g != THREAD_OWNER}
    # What THIS class defines in its own __dict__ (None = inherited):
    # unpatching restores these, or deletes our wrapper so inheritance
    # resumes — saving the MRO-resolved attribute would freeze a parent
    # class's (possibly checking) method onto the subclass forever.
    own = {
        name: cls.__dict__.get(name)
        for name in ("__getattribute__", "__setattr__", "__init__")
    }
    orig_get = _unwrap(cls.__getattribute__)
    orig_set = _unwrap(cls.__setattr__)
    orig_init = _unwrap(cls.__init__)

    def checking_init(self, *a, **kw):
        # Construction is single-threaded by definition (no other thread
        # holds a reference yet): guarded fields may be initialized
        # freely, and THREAD_OWNER ownership is claimed by the first
        # POST-construction accessor — which naturally supports the
        # construct-on-main, drive-on-worker pattern.
        d = object.__getattribute__(self, "__dict__")
        d["__guard_init_depth__"] = d.get("__guard_init_depth__", 0) + 1
        try:
            orig_init(self, *a, **kw)
        finally:
            d["__guard_init_depth__"] = d["__guard_init_depth__"] - 1

    def check(self, name: str, mode: str) -> None:
        if object.__getattribute__(self, "__dict__").get(
            "__guard_init_depth__", 0
        ):
            return
        guard = guards[name]
        if guard == THREAD_OWNER:
            tid = threading.get_ident()
            d = object.__getattribute__(self, "__dict__")
            # Atomic claim (setdefault under the GIL): a check-then-set
            # here would let two first-touching threads both claim —
            # missing the exact cross-thread race being audited, then
            # flagging the loser's next legitimate access.
            owner = d.setdefault(_OWNER_KEY, tid)
            if owner != tid:
                _note_violation(
                    f"{type(self).__name__}.{name} {mode} from thread "
                    f"{threading.current_thread().name} but owned by "
                    f"thread id {owner} (thread-confined field)"
                )
            return
        try:
            lock = orig_get(self, guard)
        except AttributeError:
            return          # under construction: the lock doesn't exist yet
        real = lock._lk if isinstance(lock, _TrackedLock) else lock
        if _held_map().get(id(real), 0) <= 0:
            _note_violation(
                f"{type(self).__name__}.{name} {mode} without {guard} "
                f"held (thread {threading.current_thread().name})"
            )

    def checking_get(self, name):
        if name in guards:
            check(self, name, "read")
        val = orig_get(self, name)
        if name in lock_attrs and not isinstance(val, _TrackedLock):
            return _TrackedLock(val)
        return val

    def checking_set(self, name, value):
        if name in guards:
            check(self, name, "write")
        orig_set(self, name, value)

    for wrapper, orig in (
        (checking_get, orig_get),
        (checking_set, orig_set),
        (checking_init, orig_init),
    ):
        wrapper._graftlint_wrapper = True
        wrapper.__wrapped__ = orig
    cls.__getattribute__ = checking_get
    cls.__setattr__ = checking_set
    cls.__init__ = checking_init
    _patched[cls] = own


def _unpatch_all() -> None:
    for cls, own in _patched.items():
        for name, orig in own.items():
            if orig is not None:
                setattr(cls, name, orig)
            else:
                # The class only had our wrapper: remove it so the
                # attribute resolves through the MRO again.
                delattr(cls, name)
    _patched.clear()


def enable_audit() -> None:
    global _enabled
    with _state_lock:
        if _enabled:
            return
        _enabled = True
        _violations.clear()
        for cls in _registry:
            _patch(cls)


def disable_audit() -> None:
    global _enabled
    with _state_lock:
        if not _enabled:
            return
        _enabled = False
        _unpatch_all()


@contextlib.contextmanager
def audit():
    """Test-scope instrumentation window; restores classes on exit.
    ``violations()`` stays readable after exit (cleared at next enable)."""
    enable_audit()
    try:
        yield
    finally:
        disable_audit()
