"""graftlint driver: run the passes, apply pragmas + baseline, report.

    python -m k8s1m_tpu.lint                 # lint the repo, honor baseline
    python -m k8s1m_tpu.lint --check-baseline  # also fail on stale entries
    python -m k8s1m_tpu.lint path/to/file.py   # lint specific files
    python -m k8s1m_tpu.lint --write-baseline  # regenerate (keeps comments out)

Exit codes: 0 clean (every finding baselined/pragma'd), 1 new findings
(or stale baseline entries under ``--check-baseline``), 2 usage error.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys

from k8s1m_tpu.lint import baseline as baseline_mod
from k8s1m_tpu.lint.base import (
    Finding,
    Rule,
    SourceFile,
    iter_py_files,
    load_file,
    suppressed,
)
from k8s1m_tpu.lint.rules_clock import NoWallClock
from k8s1m_tpu.lint.rules_except import BroadExcept
from k8s1m_tpu.lint.rules_hotfeed import HotfeedNoPerPodPython
from k8s1m_tpu.lint.rules_jax import HotPathHostSync, TraceTimeBranch
from k8s1m_tpu.lint.rules_metrics import MetricsRegistry
from k8s1m_tpu.lint.rules_retry import RetryThroughPolicy

ALL_RULES: tuple[type[Rule], ...] = (
    HotPathHostSync,
    NoWallClock,
    RetryThroughPolicy,
    MetricsRegistry,
    BroadExcept,
    TraceTimeBranch,
    HotfeedNoPerPodPython,
)

# The linted slice of the repo (everything else is docs/artifacts).
DEFAULT_SUBDIRS = ("k8s1m_tpu", "tests")


def repo_root() -> str:
    """The directory holding the k8s1m_tpu package (= repo root)."""
    import k8s1m_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        k8s1m_tpu.__file__
    )))


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]                    # after pragma suppression
    new: list[Finding]                         # not covered by baseline
    stale: list[tuple[str, str, str]]          # baseline entries unmatched
    files: int


def run_lint(
    root: str | None = None,
    paths: list[str] | None = None,
    baseline_path: str | None = None,
    rules: tuple[type[Rule], ...] = ALL_RULES,
) -> LintResult:
    """Run every pass; returns findings split against the baseline.

    ``baseline_path=None`` means "use <root>/lint_baseline.txt if it
    exists"; pass ``baseline_path=""`` to ignore any baseline.
    """
    root = root or repo_root()
    rels = paths if paths else iter_py_files(root, DEFAULT_SUBDIRS)
    files: list[SourceFile] = []
    for rel in rels:
        f = load_file(root, rel)
        if f is not None:
            files.append(f)

    instances = [cls() for cls in rules]
    findings: list[Finding] = []
    by_path = {f.path: f for f in files}
    for rule in instances:
        for f in files:
            for fd in rule.check_file(f):
                if not suppressed(f, fd):
                    findings.append(fd)
        for fd in rule.check_tree(files):
            src = by_path.get(fd.path)
            if src is None or not suppressed(src, fd):
                findings.append(fd)
    findings.sort(key=lambda fd: (fd.path, fd.line, fd.rule))

    entries: list[tuple[str, str, str]] = []
    if baseline_path != "":
        bp = baseline_path or os.path.join(
            root, baseline_mod.BASELINE_NAME
        )
        if os.path.exists(bp):
            with open(bp, encoding="utf-8") as fh:
                entries = baseline_mod.parse_baseline(fh.read())
        if paths:
            # Explicit file subset: entries for files outside it were
            # never given a chance to match — reporting them stale
            # would fail every single-file invocation.
            linted = {f.path for f in files}
            entries = [e for e in entries if e[0] in linted]
    new, stale = baseline_mod.split_findings(findings, entries)
    return LintResult(findings, new, stale, len(files))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m k8s1m_tpu.lint",
        description="graftlint: project-native static analysis",
    )
    ap.add_argument("paths", nargs="*",
                    help="repo-relative .py files (default: whole tree)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: derived from the package)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: <root>/lint_baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, baseline ignored")
    ap.add_argument("--check-baseline", action="store_true",
                    help="also fail on stale baseline entries (drift gate)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="print current findings in baseline format")
    args = ap.parse_args(argv)

    result = run_lint(
        root=args.root,
        paths=args.paths or None,
        baseline_path="" if args.no_baseline else args.baseline,
    )
    if args.write_baseline:
        print("# graftlint baseline — one 'path|rule|fingerprint' per "
              "line; comment WHY above each entry")
        for fd in result.findings:
            print(baseline_mod.format_entry(fd))
        return 0

    for fd in result.new:
        print(fd.render())
    if args.check_baseline:
        for path, rule, fp in result.stale:
            print(f"{path} {rule} STALE baseline entry (fixed? remove it): "
                  f"{fp!r}")
    failed = bool(result.new) or (args.check_baseline and bool(result.stale))
    grandfathered = len(result.findings) - len(result.new)
    print(
        f"graftlint: {result.files} files, {len(result.new)} new finding(s)"
        f", {grandfathered} baselined"
        + (f", {len(result.stale)} stale" if args.check_baseline else "")
    )
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
