"""graftlint driver: run the passes, apply pragmas + baseline, report.

    python -m k8s1m_tpu.lint                 # lint the repo, honor baseline
    python -m k8s1m_tpu.lint --check-baseline  # also fail on stale entries
    python -m k8s1m_tpu.lint path/to/file.py   # lint specific files
    python -m k8s1m_tpu.lint --write-baseline  # regenerate (keeps comments out)
    python -m k8s1m_tpu.lint --json            # machine-readable report
    python -m k8s1m_tpu.lint --jobs 4          # per-file rules across 4
                                               # processes (byte-identical
                                               # to --jobs 1)
    python -m k8s1m_tpu.lint --write-lockgraph # refresh artifacts/lockgraph.json

Exit codes: 0 clean (every finding baselined/pragma'd), 1 new findings
(or stale baseline entries under ``--check-baseline``, or stale pragmas
under ``--strict-pragmas``), 2 usage error.

Stale pragmas: a ``# graftlint: disable=<rule>`` on a line where that
rule no longer fires is reported as a warning (the pragma is dead weight
and, worse, would silently swallow a FUTURE finding on that line);
``--strict-pragmas`` promotes the warning to a failure.  The summary
counts pragma suppressions per rule so coverage stays visible as the
rule count grows.
"""

from __future__ import annotations

import argparse
import concurrent.futures
import dataclasses
import json
import os
import sys
import time

from k8s1m_tpu.lint import baseline as baseline_mod
from k8s1m_tpu.lint.base import (
    Finding,
    Rule,
    SourceFile,
    iter_py_files,
    load_file,
    suppressed,
)
from k8s1m_tpu.lint.lockgraph import (
    LockModel,
    LockOrderCycle,
    cycle_findings,
    sanctioned,
    write_artifact,
)
from k8s1m_tpu.lint.rules_blocking import BlockingUnderLock
from k8s1m_tpu.lint.rules_clock import NoWallClock
from k8s1m_tpu.lint.rules_deltacache import (
    DeltaCacheEpochKeyed,
    DeltaCacheIndexKeyed,
)
from k8s1m_tpu.lint.rules_donate import UndonatedDeviceUpdate
from k8s1m_tpu.lint.rules_except import BroadExcept
from k8s1m_tpu.lint.rules_fallback import FallbackAccounting
from k8s1m_tpu.lint.rules_fence import FencedStoreWrite
from k8s1m_tpu.lint.rules_guards import StaticGuardedBy
from k8s1m_tpu.lint.rules_hotfeed import HotfeedNoPerPodPython
from k8s1m_tpu.lint.rules_jax import HotPathHostSync, TraceTimeBranch
from k8s1m_tpu.lint.rules_mesh import MeshPurity
from k8s1m_tpu.lint.rules_metrics import MetricsRegistry
from k8s1m_tpu.lint.rules_nondet import NondetToPlacement
from k8s1m_tpu.lint.rules_retry import RetryThroughPolicy
from k8s1m_tpu.lint.rules_trace import TraceLazyEmit
from k8s1m_tpu.lint.rules_watchbuf import BoundedWatchBuffer
from k8s1m_tpu.lint.rules_wiretier import SharedFrameNoPerWatchEncode

ALL_RULES: tuple[type[Rule], ...] = (
    HotPathHostSync,
    NoWallClock,
    RetryThroughPolicy,
    MetricsRegistry,
    BroadExcept,
    TraceTimeBranch,
    HotfeedNoPerPodPython,
    StaticGuardedBy,
    LockOrderCycle,
    MeshPurity,
    FencedStoreWrite,
    UndonatedDeviceUpdate,
    DeltaCacheEpochKeyed,
    DeltaCacheIndexKeyed,
    TraceLazyEmit,
    BoundedWatchBuffer,
    NondetToPlacement,
    BlockingUnderLock,
    FallbackAccounting,
    SharedFrameNoPerWatchEncode,
)

# --json reports carry this so consumers can gate on shape changes.
SCHEMA_VERSION = 1

# The linted slice of the repo (everything else is docs/artifacts).
DEFAULT_SUBDIRS = ("k8s1m_tpu", "tests")

LOCKGRAPH_ARTIFACT = os.path.join("artifacts", "lockgraph.json")


def repo_root() -> str:
    """The directory holding the k8s1m_tpu package (= repo root)."""
    import k8s1m_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        k8s1m_tpu.__file__
    )))


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]                    # after pragma suppression
    new: list[Finding]                         # not covered by baseline
    stale: list[tuple[str, str, str]]          # baseline entries unmatched
    files: int
    # (path, line, rule): declared pragmas that suppressed nothing.
    stale_pragmas: list[tuple[str, int, str]] = dataclasses.field(
        default_factory=list
    )
    # rule -> number of findings a pragma suppressed.
    pragma_counts: dict[str, int] = dataclasses.field(default_factory=dict)
    # rule -> wall seconds spent in its hooks (summed across workers
    # under --jobs, so it reads as cost, not as a latency breakdown).
    rule_times: dict[str, float] = dataclasses.field(default_factory=dict)


def default_jobs() -> int:
    return min(4, os.cpu_count() or 1)


def _per_file_worker(payload):
    """Pool worker: run the per-file rules over one chunk of files.

    Findings come back tagged (rule position, file position) so the
    parent can replay them in exactly the order a sequential run
    produces them — byte-identical output is the contract ``--jobs``
    is gated on in tests.
    """
    root, chunk, rule_classes = payload
    instances = [cls() for cls in rule_classes]
    out: list[tuple[int, int, Finding]] = []
    times: dict[str, float] = {}
    for fidx, rel in chunk:
        f = load_file(root, rel)
        if f is None:
            continue
        for ridx, rule in enumerate(instances):
            t0 = time.perf_counter()
            fds = rule.check_file(f)
            times[rule.id] = (
                times.get(rule.id, 0.0) + time.perf_counter() - t0
            )
            for fd in fds:
                out.append((ridx, fidx, fd))
    return out, times


def run_lint(
    root: str | None = None,
    paths: list[str] | None = None,
    baseline_path: str | None = None,
    rules: tuple[type[Rule], ...] = ALL_RULES,
    jobs: int = 1,
) -> LintResult:
    """Run every pass; returns findings split against the baseline.

    ``baseline_path=None`` means "use <root>/lint_baseline.txt if it
    exists"; pass ``baseline_path=""`` to ignore any baseline.
    ``jobs>1`` fans the per-file rules out over a process pool (the
    cross-file rules stay a single pass in the parent — they need the
    whole tree anyway); output is byte-identical to ``jobs=1``.
    """
    root = root or repo_root()
    rels = paths if paths else iter_py_files(root, DEFAULT_SUBDIRS)
    files: list[SourceFile] = []
    for rel in rels:
        f = load_file(root, rel)
        if f is not None:
            files.append(f)
    # Cross-file rules (metrics registry, lock graph) need the WHOLE
    # tree for context even when only a subset is being reported — a
    # changed-only run must not think a dashboard prefix lost its
    # metric because the declaring file didn't change.  Findings are
    # still reported only for the requested subset.
    if paths:
        linted_set = {f.path for f in files}
        tree_files = list(files)
        seen = set(linted_set)
        for rel in iter_py_files(root, DEFAULT_SUBDIRS):
            f = load_file(root, rel)
            if f is not None and f.path not in seen:
                seen.add(f.path)
                tree_files.append(f)
    else:
        linted_set = None
        tree_files = files

    instances = [cls() for cls in rules]
    known_rules = {r.id for r in instances}
    findings: list[Finding] = []
    # (path, line, rule) pragmas that matched a finding — the live set.
    used_pragmas: set[tuple[str, int, str]] = set()
    pragma_counts: dict[str, int] = {}
    by_path = {f.path: f for f in tree_files}

    def consider(src: SourceFile | None, fd: Finding) -> None:
        if src is not None and suppressed(src, fd):
            if linted_set is None or fd.path in linted_set:
                used_pragmas.add((fd.path, fd.line, fd.rule))
                pragma_counts[fd.rule] = pragma_counts.get(fd.rule, 0) + 1
            return
        if linted_set is None or fd.path in linted_set:
            findings.append(fd)

    rule_times: dict[str, float] = {r.id: 0.0 for r in instances}
    per_file = [
        r for r in instances
        if type(r).check_file is not Rule.check_file
    ]
    per_file_results: dict[int, list[tuple[int, Finding]]] = {}
    if jobs > 1 and per_file and len(files) > 1:
        nchunks = min(jobs, len(files))
        chunks: list[list[tuple[int, str]]] = [[] for _ in range(nchunks)]
        for fidx, f in enumerate(files):
            chunks[fidx % nchunks].append((fidx, f.path))
        rule_classes = tuple(type(r) for r in per_file)
        with concurrent.futures.ProcessPoolExecutor(
            max_workers=nchunks
        ) as pool:
            for out, times in pool.map(
                _per_file_worker,
                [(root, chunk, rule_classes) for chunk in chunks],
            ):
                for rid, t in times.items():
                    rule_times[rid] += t
                for ridx, fidx, fd in out:
                    per_file_results.setdefault(ridx, []).append((fidx, fd))
        for acc in per_file_results.values():
            acc.sort(key=lambda t: t[0])        # stable: file order, then
    else:                                       # the rule's own order
        for ridx, rule in enumerate(per_file):
            acc = per_file_results.setdefault(ridx, [])
            for fidx, f in enumerate(files):
                t0 = time.perf_counter()
                fds = rule.check_file(f)
                rule_times[rule.id] += time.perf_counter() - t0
                for fd in fds:
                    acc.append((fidx, fd))

    per_file_pos = {id(r): i for i, r in enumerate(per_file)}
    for rule in instances:
        ridx = per_file_pos.get(id(rule))
        if ridx is not None:
            for _fidx, fd in per_file_results.get(ridx, ()):
                consider(by_path.get(fd.path), fd)
        t0 = time.perf_counter()
        tree_fds = rule.check_tree(tree_files)
        rule_times[rule.id] += time.perf_counter() - t0
        for fd in tree_fds:
            consider(by_path.get(fd.path), fd)
    findings.sort(key=lambda fd: (fd.path, fd.line, fd.rule))

    # Pragma staleness is judged against the FULL registry: an id not
    # in ALL_RULES is a typo (always stale); an id whose rule simply
    # did not run this invocation (rules= subset) is not evaluated —
    # otherwise run_lint(rules=(OneRule,)) would report every other
    # rule's live pragma as stale.
    all_ids = {cls.id for cls in ALL_RULES}
    stale_pragmas: list[tuple[str, int, str]] = []
    for f in files:
        for line, rule_ids in sorted(f.pragmas.items()):
            for rid in sorted(rule_ids):
                if rid not in all_ids:
                    stale_pragmas.append((f.path, line, rid))
                elif rid in known_rules and (
                    (f.path, line, rid) not in used_pragmas
                ):
                    stale_pragmas.append((f.path, line, rid))

    entries: list[tuple[str, str, str]] = []
    if baseline_path != "":
        bp = baseline_path or os.path.join(
            root, baseline_mod.BASELINE_NAME
        )
        if os.path.exists(bp):
            with open(bp, encoding="utf-8") as fh:
                entries = baseline_mod.parse_baseline(fh.read())
        if paths:
            # Explicit file subset: entries for files outside it were
            # never given a chance to match — reporting them stale
            # would fail every single-file invocation.
            linted = {f.path for f in files}
            entries = [e for e in entries if e[0] in linted]
    new, stale = baseline_mod.split_findings(findings, entries)
    return LintResult(
        findings, new, stale, len(files), stale_pragmas, pragma_counts,
        rule_times,
    )


def _json_report(result: LintResult, check_baseline: bool) -> dict:
    """Machine-readable report: rule -> count -> files (the CI shape)."""
    rules: dict[str, dict] = {}
    for fd in result.new:
        r = rules.setdefault(fd.rule, {"count": 0, "files": []})
        r["count"] += 1
        if fd.path not in r["files"]:
            r["files"].append(fd.path)
    return {
        "schema_version": SCHEMA_VERSION,
        "files": result.files,
        "new": [
            {"path": fd.path, "line": fd.line, "rule": fd.rule,
             "message": fd.message}
            for fd in result.new
        ],
        "rules": {k: rules[k] for k in sorted(rules)},
        "baselined": len(result.findings) - len(result.new),
        "stale_baseline": (
            [list(e) for e in result.stale] if check_baseline else None
        ),
        "stale_pragmas": [
            {"path": p, "line": ln, "rule": r}
            for p, ln, r in result.stale_pragmas
        ],
        "pragma_counts": {
            k: result.pragma_counts[k] for k in sorted(result.pragma_counts)
        },
        "rule_times": {
            k: round(result.rule_times[k], 4)
            for k in sorted(result.rule_times)
        },
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m k8s1m_tpu.lint",
        description="graftlint: project-native static analysis",
    )
    ap.add_argument("paths", nargs="*",
                    help="repo-relative .py files (default: whole tree)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: derived from the package)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: <root>/lint_baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, baseline ignored")
    ap.add_argument("--check-baseline", action="store_true",
                    help="also fail on stale baseline entries (drift gate)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="print current findings in baseline format")
    ap.add_argument("--strict-pragmas", action="store_true",
                    help="fail on pragmas whose rule no longer fires there")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report (rule -> count -> files)")
    ap.add_argument("--jobs", type=int, default=None, metavar="N",
                    help="run per-file rules across N processes "
                         "(default min(4, cpus); output is byte-identical "
                         "to --jobs 1)")
    ap.add_argument("--write-lockgraph", nargs="?", const=LOCKGRAPH_ARTIFACT,
                    default=None, metavar="PATH",
                    help="write the lock acquisition-order graph artifact "
                         f"(default {LOCKGRAPH_ARTIFACT}) and exit")
    args = ap.parse_args(argv)

    root = args.root or repo_root()
    if args.write_lockgraph is not None:
        rels = iter_py_files(root, DEFAULT_SUBDIRS)
        files = [f for f in (load_file(root, r) for r in rels)
                 if f is not None]
        model = LockModel(files)
        out = os.path.join(root, args.write_lockgraph)
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        write_artifact(model, out, files)
        # Pragma'd cycles (the documented escape hatch) are recorded in
        # the artifact as sanctioned and do not fail the write.
        bad = sum(
            1 for _cyc, fds in cycle_findings(model, files)
            if not sanctioned(files, fds)
        )
        ncyc = len(model.cycles())
        print(f"lockgraph: {len(model.edges)} edge(s), {ncyc} cycle(s) "
              f"({bad} unsanctioned) -> {out}")
        return 1 if bad else 0

    result = run_lint(
        root=args.root,
        paths=args.paths or None,
        baseline_path="" if args.no_baseline else args.baseline,
        jobs=args.jobs if args.jobs is not None else default_jobs(),
    )
    if args.write_baseline:
        print("# graftlint baseline — one 'path|rule|fingerprint' per "
              "line; comment WHY above each entry")
        for fd in result.findings:
            print(baseline_mod.format_entry(fd))
        return 0

    if args.json:
        print(json.dumps(
            _json_report(result, args.check_baseline), indent=2
        ))
    else:
        for fd in result.new:
            print(fd.render())
        if args.check_baseline:
            for path, rule, fp in result.stale:
                print(f"{path} {rule} STALE baseline entry (fixed? remove "
                      f"it): {fp!r}")
        known = {cls.id for cls in ALL_RULES}
        for path, line, rid in result.stale_pragmas:
            why = (
                "suppresses nothing" if rid in known
                else "names an unknown rule id (typo?)"
            )
            print(f"{path}:{line} stale-pragma '{rid}' {why} "
                  f"(remove it{'' if args.strict_pragmas else ' — warning'})")
    failed = (
        bool(result.new)
        or (args.check_baseline and bool(result.stale))
        or (args.strict_pragmas and bool(result.stale_pragmas))
    )
    if not args.json:
        grandfathered = len(result.findings) - len(result.new)
        coverage = ", ".join(
            f"{k}={v}" for k, v in sorted(result.pragma_counts.items())
        )
        print(
            f"graftlint: {result.files} files, {len(result.new)} new "
            f"finding(s), {grandfathered} baselined"
            + (f", {len(result.stale)} stale" if args.check_baseline else "")
            + f", {len(result.stale_pragmas)} stale pragma(s)"
            + (f"; pragma coverage: {coverage}" if coverage else "")
        )
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
