"""shared-frame-no-per-watch-encode: fan-out loops must not re-encode.

The wiretier contract (ISSUE 20): event bytes are encoded ONCE into a
shared frame table and fanned out by reference — per-watch work is
index/mask selection over shared bytes, never a re-serialize.  The
storm numbers hinge on it: one ``SerializeToString()`` inside a
per-subscriber loop silently restores encode-bound fan-out, and the
100K-watch drill degrades back to the ~4K events/s anchor without any
test failing (the bytes are still correct, just 3x the CPU).

This pass pins it statically: in ``k8s1m_tpu/store/``, any call to

- ``SerializeToString`` / ``SerializePartialToString``, or
- ``encode_event_batch`` (the tier's legacy per-watch response builder)

lexically inside a loop or comprehension that iterates a watcher-ish
population (an iteration source or loop target whose identifiers
mention ``watcher``/``subscriber``/``downstream``, or are exactly
``watchers``/``watches``/``wids``/``watch_ids``/``subscribers``/
``peers``) is a finding.

Per-watch CONTROL responses (created/canceled acks) legitimately
serialize per watch — they are tiny, per-watch by nature, and carry no
event payload; that is what the pragma escape is for:
``# graftlint: disable=shared-frame-no-per-watch-encode (reason)``.
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint.base import Finding, Rule, SourceFile

_SCOPED_DIR = "k8s1m_tpu/store/"

_BANNED = {
    "SerializeToString",
    "SerializePartialToString",
    "encode_event_batch",
}
_SUBSTR = ("watcher", "subscriber", "downstream")
_EXACT = {
    "watchers", "watches", "wids", "watch_ids", "subscribers", "peers",
}

_MSG = (
    "{name}() inside a per-watch loop in store/ — encode once into the "
    "shared frame table (wiretier) and fan bytes out by reference; "
    "per-watch work must be index selection, never a re-encode (pragma "
    "the line if this is a per-watch control ack)"
)


def _idents(node: ast.AST):
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            yield n.id
        elif isinstance(n, ast.Attribute):
            yield n.attr


def _watcherish(node: ast.AST) -> bool:
    for name in _idents(node):
        low = name.lower()
        if name in _EXACT or any(s in low for s in _SUBSTR):
            return True
    return False


def _call_name(node: ast.Call) -> str | None:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


class SharedFrameNoPerWatchEncode(Rule):
    id = "shared-frame-no-per-watch-encode"

    def check_file(self, f: SourceFile) -> list[Finding]:
        if not f.path.startswith(_SCOPED_DIR):
            return []
        out: list[Finding] = []
        seen: set[tuple[int, int]] = set()
        for node in ast.walk(f.tree):
            srcs: list[ast.AST] | None = None
            body: list[ast.AST] | None = None
            if isinstance(node, (ast.For, ast.AsyncFor)):
                srcs = [node.iter, node.target]
                body = list(node.body)
            elif isinstance(node, ast.While):
                srcs = [node.test]
                body = list(node.body)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp, ast.DictComp)):
                srcs = []
                for g in node.generators:
                    srcs += [g.iter, g.target]
                if isinstance(node, ast.DictComp):
                    body = [node.key, node.value]
                else:
                    body = [node.elt]
            if srcs is None or not any(_watcherish(s) for s in srcs):
                continue
            for stmt in body:
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.Call):
                        continue
                    name = _call_name(sub)
                    if name not in _BANNED:
                        continue
                    key = (sub.lineno, sub.col_offset)
                    if key in seen:   # nested watcher loops: report once
                        continue
                    seen.add(key)
                    out.append(self.finding(
                        f, sub, _MSG.format(name=name)
                    ))
        return out
