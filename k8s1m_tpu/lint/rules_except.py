"""broad-except: ``except Exception`` must not swallow silently.

A handler catching ``Exception``/``BaseException`` (alone or in a
tuple) must do at least one of:

- re-raise (any ``raise`` statement in the handler body),
- log with a traceback (``log.exception(...)`` or any logging call
  passing ``exc_info=``),
- carry a ``# graftlint: disable=broad-except`` pragma with its reason.

The rule exists because the control plane degrades *gracefully by
design* — resyncs, requeues, fallbacks — and a silent swallow converts
a designed degradation into an undiagnosable one.  The tree had ~40
bare sites when this rule landed; each is now a fix, a justified
pragma, or a baselined grandfather entry.
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint.base import (
    Finding,
    Rule,
    SourceFile,
    walk_no_nested_functions,
)

_BROAD = {"Exception", "BaseException"}


def _names_in_type(node: ast.AST | None) -> set[str]:
    out: set[str] = set()
    if node is None:
        return out
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _handler_complies(handler: ast.ExceptHandler) -> bool:
    # walk_no_nested_functions: a raise/log.exception inside a nested
    # def the handler merely DEFINES is not the handler complying.
    for n in walk_no_nested_functions(handler):
        if isinstance(n, ast.Raise):
            return True
        if isinstance(n, ast.Call):
            func = n.func
            if isinstance(func, ast.Attribute) and func.attr == "exception":
                return True
            if any(kw.arg == "exc_info" for kw in n.keywords):
                return True
    return False


class BroadExcept(Rule):
    id = "broad-except"

    def check_file(self, f: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not (_names_in_type(node.type) & _BROAD):
                continue
            if _handler_complies(node):
                continue
            out.append(self.finding(
                f, node,
                "except Exception must re-raise, log with traceback "
                "(log.exception / exc_info=True), or carry a pragma "
                "naming why the swallow is safe",
            ))
        return out
