"""hotfeed-no-per-pod-python: the hot encode path stays vectorized.

The hotfeed contract (snapshot/hotfeed.py) is that per-POD work in the
encode path is bounded to cheap dict/tuple bookkeeping — every array
write is a vectorized column write or a per-SHAPE fancy-indexed row
broadcast.  A ``for ... in pods:`` loop quietly reintroduced into that
path regresses the whole point of the subsystem, and nothing else would
catch it (the code stays correct, just 10x slower).

Scope — deliberately narrow, the two places the contract holds:

- any ``*hotfeed*.py`` under ``k8s1m_tpu/snapshot/`` (whole file —
  including ``encode_batch``, the one shared encode body);
- the coordinator feed path: the body of ``_take_batch`` in
  ``k8s1m_tpu/control/coordinator.py`` (pop + claim + encode).

Flagged shapes: ``for``-statements and comprehension generators whose
iterable is a pod list (names ``pods`` / ``batch_pods``, bare or
wrapped in enumerate/zip/reversed/sorted/list, or ``range(len(pods))``).

Escape hatches (base.py): a ``# graftlint: disable=`` pragma carrying
the reason the site is irreducibly O(pods)-cheap (fingerprinting, qkey
replay, scalar extraction feeding a vectorized write), or a baseline
entry for a grandfathered site.
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint.base import Finding, Rule, SourceFile

_POD_LIST_NAMES = {"pods", "batch_pods"}
_WRAPPERS = {"enumerate", "zip", "reversed", "sorted", "list", "tuple"}

COORDINATOR_PATH = "k8s1m_tpu/control/coordinator.py"
FEED_FUNCS = {"_take_batch"}


def _is_pod_iterable(node: ast.AST) -> bool:
    """True when ``node`` iterates a pod list: ``pods``, ``self.pods``,
    ``enumerate(pods)``, ``zip(a, pods)``, ``range(len(pods))``..."""
    if isinstance(node, ast.Name) and node.id in _POD_LIST_NAMES:
        return True
    if isinstance(node, ast.Attribute) and node.attr in _POD_LIST_NAMES:
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name):
            if fn.id in _WRAPPERS:
                return any(_is_pod_iterable(a) for a in node.args)
            if fn.id == "range":
                # range(len(pods)) and friends.
                for a in ast.walk(node):
                    if isinstance(a, ast.Name) and a.id in _POD_LIST_NAMES:
                        return True
    return False


class HotfeedNoPerPodPython(Rule):
    id = "hotfeed-no-per-pod-python"

    def check_file(self, f: SourceFile) -> list[Finding]:
        base = f.path.rsplit("/", 1)[-1]
        if f.path.startswith("k8s1m_tpu/snapshot/") and "hotfeed" in base:
            return self._scan(f, f.tree)
        if f.path == COORDINATOR_PATH:
            out: list[Finding] = []
            for node in ast.walk(f.tree):
                if (
                    isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name in FEED_FUNCS
                ):
                    out.extend(self._scan(f, node))
            return out
        return []

    def _scan(self, f: SourceFile, root: ast.AST) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(root):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                if _is_pod_iterable(node.iter):
                    out.append(self._flag(f, node))
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.DictComp,
                       ast.GeneratorExp)
            ):
                if any(_is_pod_iterable(g.iter) for g in node.generators):
                    out.append(self._flag(f, node))
        return out

    def _flag(self, f: SourceFile, node: ast.AST) -> Finding:
        return self.finding(
            f, node,
            "per-pod Python in the hotfeed encode path; use a cached "
            "template + vectorized column/row write, or pragma with the "
            "reason this site is irreducibly O(pods)-cheap",
        )
