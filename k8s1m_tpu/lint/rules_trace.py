"""trace-lazy-emit: tracing off must be free in the hot paths.

The podtrace contract (obs/podtrace.py) is the null-tracer pattern: a
coordinator holds ``NULL_TRACER`` by default, and every span/attr
construction in the scheduling hot paths sits behind one cheap
``tracer.enabled`` read — so a tracing-off run pays an attribute check
per site, never a span append, a key hash, or an attrs dict.  An
unguarded ``tracer.emit(...)`` quietly reintroduced into the cycle
would still be *correct* (the null tracer no-ops), but the argument
construction and call overhead would land on every pod of every wave —
exactly the regression the ±5% CPU-lane gate exists to catch, found
here at lint time instead.

Scope: ``k8s1m_tpu/engine/``, ``k8s1m_tpu/snapshot/`` and
``k8s1m_tpu/control/`` — the wave hot paths.  Flagged shape: a call
``<recv>.begin/.emit/.finish(...)`` whose receiver's dotted name
contains ``trace`` (``tracer``, ``self._tracer``, ``podtrace``) with no
enclosing guard on the ``enabled`` flag.  Guard forms recognized,
polarity-aware (a call in the body of ``if not tracer.enabled:`` is
NOT guarded — it runs exactly when tracing is off):

- ``if tracer.enabled:`` / the hoisted ``tr_on = tracer.enabled`` name
  (body guarded; ``else`` of a negated test guarded);
- the short-circuit ``tracer.enabled and tracer.emit(...)`` and the
  ternary's guarded arm;
- the early-return dominator: a top-level
  ``if not tracer.enabled: return`` earlier in the same function body
  guards everything after it (the whole-method-is-cold form).

Escape hatch: a ``# graftlint: disable=trace-lazy-emit`` pragma
carrying the reason the site is deliberately unguarded (a cold path
where emission cost is irrelevant).
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint.base import Finding, Rule, SourceFile, dotted_name

SCOPE_PREFIXES = (
    "k8s1m_tpu/engine/",
    "k8s1m_tpu/snapshot/",
    "k8s1m_tpu/control/",
)

# The span-chain mutators of the PodTracer surface.  Reads (spans_of,
# completed, attribution) are not flagged: they run on cold paths by
# construction and build nothing per pod.
_EMITTERS = {"begin", "emit", "finish"}


class TraceLazyEmit(Rule):
    id = "trace-lazy-emit"

    def check_file(self, f: SourceFile) -> list[Finding]:
        if not f.path.startswith(SCOPE_PREFIXES):
            return []
        # Names assigned from an ``.enabled`` read (``tr_on =
        # tracer.enabled``) guard like the attribute itself.
        enabled_names: set[str] = set()
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Attribute
            ) and node.value.attr == "enabled":
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        enabled_names.add(tgt.id)

        def mentions_enabled(test: ast.AST) -> bool:
            for n in ast.walk(test):
                if isinstance(n, ast.Attribute) and n.attr == "enabled":
                    return True
                if isinstance(n, ast.Name) and n.id in enabled_names:
                    return True
            return False

        def negated(test: ast.AST) -> bool:
            """STRICTLY `not <enabled>` — the only form whose else arm
            (or early return) soundly implies tracing is on."""
            return (
                isinstance(test, ast.UnaryOp)
                and isinstance(test.op, ast.Not)
                and mentions_enabled(test.operand)
            )

        def has_negated_mention(test: ast.AST) -> bool:
            """Any `not ...enabled...` ANYWHERE in the test (e.g.
            `cond and not tracer.enabled`) — such a test can be true
            with tracing OFF, so it guards nothing."""
            for n in ast.walk(test):
                if isinstance(n, ast.UnaryOp) and isinstance(
                    n.op, ast.Not
                ) and mentions_enabled(n.operand):
                    return True
            return False

        def positive(test: ast.AST) -> bool:
            return mentions_enabled(test) and not has_negated_mention(test)

        parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(f.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        def guarded(node: ast.AST) -> bool:
            cur = node
            while cur in parents:
                parent = parents[cur]
                if isinstance(parent, ast.If):
                    # Polarity-aware: a positive test guards its body,
                    # a negated test guards its else branch.
                    if cur in parent.body and positive(parent.test):
                        return True
                    if cur in parent.orelse and negated(parent.test):
                        return True
                elif isinstance(parent, ast.IfExp):
                    if cur is parent.body and positive(parent.test):
                        return True
                    if cur is parent.orelse and negated(parent.test):
                        return True
                elif isinstance(parent, ast.BoolOp) and isinstance(
                    parent.op, ast.And
                ):
                    # Short-circuit only guards operands AFTER the
                    # enabled test: `enabled and emit()` guards,
                    # `emit() and enabled` does not.
                    idx = next(
                        (j for j, v in enumerate(parent.values)
                         if v is cur),
                        None,
                    )
                    if idx is not None and any(
                        positive(v) for v in parent.values[:idx]
                    ):
                        return True
                elif isinstance(
                    parent, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    # Early-return dominator: a top-level
                    # `if not <enabled>: return` before this call makes
                    # the rest of the function tracing-on-only.
                    for st in parent.body:
                        if st.lineno >= node.lineno:
                            break
                        if (
                            isinstance(st, ast.If)
                            and negated(st.test)
                            and not st.orelse
                            and st.body
                            and all(
                                isinstance(b, (ast.Return, ast.Raise))
                                for b in st.body
                            )
                        ):
                            return True
                cur = parent
            return False

        out: list[Finding] = []
        for node in ast.walk(f.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _EMITTERS
            ):
                continue
            recv = dotted_name(node.func.value)
            if recv is None or "trace" not in recv.lower():
                continue
            if guarded(node):
                continue
            out.append(self.finding(
                f, node,
                f"unguarded tracer.{node.func.attr}() in a hot path; "
                "wrap the span construction in `if tracer.enabled:` "
                "(the null-tracer contract — tracing off must be "
                "free), or pragma with the reason this site is cold",
            ))
        return out
