"""fallback-counts-or-raises: fail-closed accounting as lint.

The control plane degrades gracefully BY DESIGN — resyncs, requeues,
cache drops, executable swaps.  The discipline that keeps graceful
degradation diagnosable is fail-closed accounting: every fallback
branch that diverts the production path must leave evidence — a
registered-metric increment — or re-raise.  broad-except enforces the
weakest form (don't swallow silently); this pass enforces the
accounting form, on the flow.py CFG, in the dirs where a silent
fallback corrupts the performance story rather than just the logs:
``engine/ snapshot/ parallel/ store/``.

A handler **diverts** when it exits the production path early
(``return`` / ``continue`` / ``break``) or invokes a degradation
helper (a call whose name contains ``fallback`` or is ``resync`` /
``drop_all`` / ``invalidate``).  Each divert must be **dominated** by
accounting — every path from the handler's entry to the divert passes
a ``<METRIC>.inc(...)`` / ``.observe(...)`` on a metric variable the
tree actually declares (the metrics-registry cross-check: an increment
on an unknown name is not accounting, it is a typo that counts into
the void), or a ``raise``.  For degradation-helper diverts the query
is the dual: control must not be able to LEAVE the handler without
passing accounting (``CFG.exit_reachable_avoiding``).

Escapes: ``# graftlint: disable=fallback-counts-or-raises`` with the
reason the divert is self-evident (e.g. the caller counts), or a
baseline entry.
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint import flow
from k8s1m_tpu.lint.base import (
    Finding,
    Rule,
    SourceFile,
    call_name,
    dotted_name,
    walk_no_nested_functions,
)

SCOPE_DIRS = (
    "k8s1m_tpu/engine/", "k8s1m_tpu/snapshot/", "k8s1m_tpu/parallel/",
    "k8s1m_tpu/store/",
)

_METRIC_CTORS = {"Counter", "Gauge", "Histogram", "AlertingHistogram",
                 "CallbackMetric"}
_ACCOUNT_METHODS = {"inc", "observe", "observe_many"}
_DEGRADE_LEAVES = {"resync", "drop_all", "invalidate"}


def _is_metric_ctor(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    return name is not None and name.rsplit(".", 1)[-1] in _METRIC_CTORS


def _divert_call(node: ast.AST) -> str | None:
    if not isinstance(node, ast.Call):
        return None
    name = call_name(node)
    if name is None:
        return None
    if "fallback" in name or name in _DEGRADE_LEAVES:
        return name
    return None


class FallbackAccounting(Rule):
    id = "fallback-counts-or-raises"

    def check_tree(self, files: list[SourceFile]) -> list[Finding]:
        metric_env = self._metric_vars(files)
        out: list[Finding] = []
        for f in files:
            if not f.path.startswith(SCOPE_DIRS):
                continue
            env = metric_env.get(f.path, set())
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Try):
                    for handler in node.handlers:
                        out.extend(self._check_handler(f, handler, env))
        out.sort(key=lambda fd: (fd.path, fd.line))
        return out

    # -- registered-metric environment ------------------------------------

    def _metric_vars(self, files: list[SourceFile]) -> dict[str, set[str]]:
        """path -> variable names bound (locally or by import) to a
        metric the tree declares — the names whose ``.inc()`` counts."""
        declared: dict[str, set[str]] = {}      # module -> vars
        for f in files:
            if not f.path.startswith("k8s1m_tpu/"):
                continue
            mod = f.path[:-3].replace("/", ".")
            for stmt in f.tree.body if isinstance(f.tree, ast.Module) else []:
                if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Call
                ) and _is_metric_ctor(stmt.value):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            declared.setdefault(mod, set()).add(tgt.id)
        out: dict[str, set[str]] = {}
        for f in files:
            if not f.path.startswith(SCOPE_DIRS):
                continue
            env = set(declared.get(f.path[:-3].replace("/", "."), ()))
            for node in ast.walk(f.tree):
                if isinstance(node, ast.ImportFrom) and node.module:
                    src = declared.get(node.module)
                    if not src:
                        continue
                    for alias in node.names:
                        if alias.name in src:
                            env.add(alias.asname or alias.name)
            out[f.path] = env
        return out

    # -- per-handler CFG analysis -----------------------------------------

    def _accounts(self, stmt: ast.stmt, env: set[str]) -> bool:
        """Does executing ``stmt`` itself leave fail-closed evidence —
        a raise, or an inc/observe on a declared metric variable?
        Compound statements contribute only their HEADER expressions
        (test/iter/items): their bodies are separate CFG nodes, and a
        raise buried in one branch must not mark the whole header."""
        if isinstance(stmt, ast.Raise):
            return True
        if isinstance(stmt, (ast.If, ast.While)):
            roots: list[ast.AST] = [stmt.test]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            roots = [stmt.iter]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            roots = [i.context_expr for i in stmt.items]
        elif isinstance(stmt, (ast.Try, ast.ExceptHandler)):
            return False
        else:
            roots = [stmt]
        for root in roots:
            for n in (root, *walk_no_nested_functions(root)):
                if isinstance(n, ast.Raise):
                    return True
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in _ACCOUNT_METHODS
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in env
                ):
                    return True
        return False

    def _unregistered_incs(self, handler: ast.ExceptHandler, env) -> list[str]:
        out = []
        for n in walk_no_nested_functions(handler):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in _ACCOUNT_METHODS
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id not in env
            ):
                out.append(n.func.value.id)
        return out

    def _check_handler(
        self, f: SourceFile, handler: ast.ExceptHandler, env: set[str]
    ) -> list[Finding]:
        # Early exits and degradation calls in the handler's OWN body
        # (a nested def's return is not this handler diverting).
        exits: list[ast.stmt] = []
        degrades: list[tuple[ast.AST, str]] = []
        for n in walk_no_nested_functions(handler):
            if isinstance(n, (ast.Return, ast.Continue, ast.Break)):
                exits.append(n)
            else:
                name = _divert_call(n)
                if name is not None:
                    degrades.append((n, name))
        if not exits and not degrades:
            return []

        cfg = flow.CFG.from_body(handler.body)
        # A break/continue that targets a loop INSIDE the handler stays
        # on the handler's own paths (no EXIT edge) — not a divert.
        exits = [
            s for s in exits
            if isinstance(s, ast.Return)
            or flow.EXIT in cfg.succ.get(cfg.node_of(s), ())
        ]
        if not exits and not degrades:
            return []
        accounting = {
            idx for idx, stmt in cfg.statements()
            if self._accounts(stmt, env)
        }
        dom = cfg.dominators()
        unknown = self._unregistered_incs(handler, env)
        hint = (
            f" (.inc() on {sorted(set(unknown))} is not a registered "
            f"metric — counts into the void)" if unknown else ""
        )

        out: list[Finding] = []
        for stmt in exits:
            idx = cfg.node_of(stmt)
            if idx is None:
                continue
            if any(cfg.dominates(a, idx, dom) for a in accounting):
                continue
            kind = type(stmt).__name__.lower()
            out.append(self.finding(
                f, stmt,
                f"fallback {kind} diverts the production path without "
                f"fail-closed accounting{hint}; increment a registered "
                f"metric or re-raise before diverting",
            ))
        if degrades and not out:
            # Fall-through divert: the handler swaps/drops and resumes.
            # Control must not LEAVE the handler unaccounted.
            if cfg.exit_reachable_avoiding(accounting):
                node, name = degrades[0]
                out.append(self.finding(
                    f, node,
                    f"fallback path calls {name}() but the handler can "
                    f"complete without fail-closed accounting{hint}; "
                    f"increment a registered metric or re-raise",
                ))
        return out
