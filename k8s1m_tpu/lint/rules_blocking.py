"""blocking-under-lock: no stall-the-world call while holding a lock.

A ``with self._lock:`` region in a ``@guarded_by`` class is a
contention point by declaration: every thread the guard protects
against will queue on it.  A device sync (``.item()``,
``jax.device_get``, ``block_until_ready``), a sleep, socket/file I/O
or a subprocess call inside that region turns a microsecond critical
section into a milliseconds-to-seconds one — and every queued thread
inherits the stall.  The runtime guard audit can't see this (it checks
WHO holds the lock, not how long); this pass proves it at lint time,
**interprocedurally**: a blocking call reached through the intra-repo
call graph from inside the locked region counts, with the call chain
printed as the witness.

Severity composes with lockgraph.py: when the held lock sits on a
committed acquisition-order edge (some path nests another lock inside
or around it), the finding is ranked **stall-the-world** — the stall
propagates across the lock graph, not just across one lock's waiters.

What counts as blocking (deliberately conservative — named device
syncs, ``time.sleep``, subprocess, socket verbs, ``open``): see
``blocking_reason``.  ``Condition.wait`` does NOT count — it releases
the lock while waiting, which is the one sanctioned way to block under
one.  Escapes: ``# graftlint: disable=blocking-under-lock`` with the
reason the blocking call is bounded, or a baseline entry.
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint import flow
from k8s1m_tpu.lint.base import (
    Finding,
    Rule,
    SourceFile,
    call_name,
    dotted_name,
)
from k8s1m_tpu.lint.lockgraph import LockModel
from k8s1m_tpu.lint.rules_guards import _guard_map

_SOCKET_VERBS = {"recv", "recv_into", "recvfrom", "sendall", "accept",
                 "connect", "makefile"}
_SUBPROCESS_LEAVES = {"check_output", "check_call", "communicate"}


def blocking_reason(node: ast.AST) -> str | None:
    """Why ``node`` blocks, else None.  Keyed on call shape only — the
    receiver's type is not consulted, so a non-socket ``recv`` needs a
    pragma (cheap, rare, and the pragma documents the claim)."""
    if not isinstance(node, ast.Call):
        return None
    d = dotted_name(node.func)
    leaf = call_name(node)
    if leaf == "item" and not node.args and not node.keywords and (
        isinstance(node.func, ast.Attribute)
    ):
        return "device sync .item()"
    if leaf == "block_until_ready":
        return "device sync block_until_ready()"
    if leaf == "device_get":
        return "device sync device_get()"
    if d == "time.sleep":
        return "time.sleep()"
    if d is not None and (
        d.startswith("subprocess.") or d.startswith("select.")
    ):
        return f"{d}()"
    if leaf in _SUBPROCESS_LEAVES:
        return f".{leaf}() (subprocess)"
    if leaf in _SOCKET_VERBS:
        return f".{leaf}() (socket I/O)"
    if isinstance(node.func, ast.Name) and node.func.id == "open":
        return "open() (file I/O)"
    return None


class BlockingUnderLock(Rule):
    id = "blocking-under-lock"

    def check_tree(self, files: list[SourceFile]) -> list[Finding]:
        prod = [f for f in files if f.path.startswith("k8s1m_tpu/")]
        cg = flow.CallGraph(files)
        model = LockModel(files)
        # Locks appearing on committed acquisition-order edges: a stall
        # while holding one of these backs up the wider lock graph.
        edge_locks = {e.src for e in model.edges} | {
            e.dst for e in model.edges
        }

        out: list[Finding] = []
        for f in prod:
            if not isinstance(f.tree, ast.Module):
                continue
            for node in f.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                if _guard_map(node) is None:
                    continue          # only @guarded_by classes declare
                out.extend(self._check_class(f, node, cg, edge_locks))
        out.sort(key=lambda fd: (fd.path, fd.line))
        return out

    def _check_class(
        self, f: SourceFile, cls: ast.ClassDef, cg, edge_locks
    ) -> list[Finding]:
        locks, alias = flow.lock_attrs_of(cls)
        if not locks:
            return []
        out: list[Finding] = []
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            seen_calls: set[int] = set()
            for node, held, _scope in flow.walk_held(
                meth, resolve=lambda a: alias.get(a, a)
            ):
                held_locks = sorted(h for h in held if h in locks)
                if not held_locks or not isinstance(node, ast.Call):
                    continue
                if id(node) in seen_calls:
                    continue
                seen_calls.add(id(node))
                rank = self._rank(f, cls, held_locks, edge_locks)
                reason = blocking_reason(node)
                if reason is not None:
                    out.append(self.finding(
                        f, node,
                        f"{reason} while holding "
                        f"self.{'/'.join(held_locks)} in "
                        f"{cls.name}.{meth.name}{rank}; move the "
                        f"blocking call outside the critical section "
                        f"or pragma with the bound",
                    ))
                    continue
                key = cg.target_of(node)
                if key is None:
                    continue
                got = cg.find_reachable(key, blocking_reason, max_depth=6)
                if got is not None:
                    chain, hit = got
                    via = " -> ".join(
                        (key.split("::")[-1],) + chain
                        + (f"line {hit.lineno}",)
                    )
                    out.append(self.finding(
                        f, node,
                        f"{blocking_reason(hit)} reachable via "
                        f"[{via}] while holding "
                        f"self.{'/'.join(held_locks)} in "
                        f"{cls.name}.{meth.name}{rank}; hoist the "
                        f"blocking step out of the locked region or "
                        f"pragma with the bound",
                    ))
        return out

    def _rank(self, f, cls, held_locks, edge_locks) -> str:
        on_edge = [
            a for a in held_locks
            if f"{f.path}::{cls.name}.{a}" in edge_locks
        ]
        if on_edge:
            return (
                " [STALL-THE-WORLD: lock on committed lockgraph "
                "acquisition edges]"
            )
        return ""
