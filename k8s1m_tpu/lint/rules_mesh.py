"""mesh-purity: the byte-identity contract, enforced before any wave runs.

PR 6 made mesh↔single-device BYTE-identity the invariant PR authors
must not break (MIGRATION.md "Sharded execution"): the sharded cycle's
tie-break hash runs over GLOBAL (pod row, node row) coordinates with one
shared per-wave seed, and the host-side merge replays query keys in dp
order — so the differential gate can demand bit-equality, not
statistics.  The gate only runs in the differential suite though; this
pass checks the purity rules that make it hold on every file, at lint
time:

1. **no per-shard PRNG folding** — ``jax.random.fold_in`` is banned in
   shard_map-mapped code (``parallel/``, ``ops/``, ``plugins/``).
   Folding shard coordinates into the key is the exact regression PR 6
   removed (the old ``fold_mesh_key``): it decorrelates tie-breaks
   across shards and demotes the mesh to statistical equivalence.
2. **axis-derived values stay out of tie-break hashes** — values
   data-flowing from ``lax.axis_index``/``lax.psum`` must not reach
   ``hash_jitter`` / ``pack_hashed`` / ``pack`` / ``seed_of`` arguments
   or any ``key=``/``seed=`` keyword, except via the blessed
   ``mesh_offsets`` helper (whose whole point is that the hash *base*
   globalizes, the key does not vary).  Tracked per function through
   local assignments; a tuple-unpack from ``mesh_offsets(...)`` is the
   sanctioned laundering point.
3. **top-k tie-breaks reference global offsets** — inside ``parallel/``,
   every ``filter_score_topk``/``pallas_candidates`` call must pass BOTH
   ``row_offset=`` and ``pod_offset=``; omitting either silently falls
   back to shard-local coordinates and byte-identity dies at the first
   cross-shard tie.
4. **no set iteration in encode/merge paths** — in
   ``snapshot/hotfeed*.py`` and ``snapshot/pod_encoding.py`` (the paths
   whose output ``merge_packed`` must rebuild byte-identically),
   iterating a Python ``set``/``frozenset`` injects hash-seed ordering
   into encoded bytes.  ``sorted(...)`` over a set is fine; dict
   iteration is insertion-ordered (deterministic) and exempt.

Every rule has the standard escape hatches: a ``# graftlint: disable=
mesh-purity`` pragma with a reason, or a baseline entry.
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint import flow
from k8s1m_tpu.lint.base import (
    Finding,
    Rule,
    SourceFile,
    call_name as _call_name,
)

MESH_DIRS = ("k8s1m_tpu/parallel/", "k8s1m_tpu/ops/", "k8s1m_tpu/plugins/")
TOPK_DIR = "k8s1m_tpu/parallel/"
MERGE_PATHS = ("k8s1m_tpu/snapshot/pod_encoding.py",)

_TAINT_SOURCES = {"axis_index", "psum"}
_HASH_SINKS = {"hash_jitter", "pack_hashed", "pack", "seed_of"}
_TOPK_CALLS = {"filter_score_topk", "pallas_candidates"}
_BLESSED = "mesh_offsets"


def _contains_taint_source(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _call_name(sub) in _TAINT_SOURCES:
            return True
    return False


def _launders(value: ast.AST) -> bool:
    """``mesh_offsets(...)`` is the sanctioned laundering point."""
    return isinstance(value, ast.Call) and _call_name(value) == _BLESSED


# The binding/taint/set walking lives on the flow.py chassis now; the
# aliases keep this module reading the way the docstring describes it.
_own_body = flow.own_body
_mentions = flow.mentions


def _is_merge_path(path: str) -> bool:
    base = path.rsplit("/", 1)[-1]
    if path.startswith("k8s1m_tpu/snapshot/") and "hotfeed" in base:
        return True
    return path in MERGE_PATHS


class MeshPurity(Rule):
    id = "mesh-purity"

    def check_file(self, f: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        if f.path.startswith(MESH_DIRS):
            out.extend(self._check_mesh(f))
        if _is_merge_path(f.path):
            out.extend(self._check_merge(f))
        out.sort(key=lambda fd: fd.line)
        return out

    # -- shard_map purity (rules 1-3) ------------------------------------

    def _check_mesh(self, f: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name == _BLESSED:
                    continue
                out.extend(self._check_mesh_func(f, node))
        return out

    def _check_mesh_func(self, f: SourceFile, fn) -> list[Finding]:
        out: list[Finding] = []
        # Bindings in source order, closed to a fixpoint so chains like
        # `idx = axis_index(...); off = idx * 128` taint through any
        # number of intermediates (and loops) — flow.py layer 1, which
        # this rule's private engine became.
        tainted = flow.taint_fixpoint(
            flow.collect_bindings(fn),
            contains_source=_contains_taint_source,
            launders=_launders,
        )
        for node in _own_body(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name == "fold_in":
                out.append(self.finding(
                    f, node,
                    "per-shard PRNG key folding in shard_map-mapped code "
                    "breaks the mesh byte-identity contract; derive "
                    "tie-breaks from mesh_offsets + hash_jitter over "
                    "global coordinates instead (the PR 6 regression)",
                ))
                continue
            if name in _HASH_SINKS:
                args = list(node.args) + [kw.value for kw in node.keywords]
            else:
                args = [
                    kw.value for kw in node.keywords
                    if kw.arg in ("key", "seed")
                ]
                if not args:
                    continue
            for a in args:
                if _contains_taint_source(a) or _mentions(a, tainted):
                    out.append(self.finding(
                        f, node,
                        f"axis_index/psum-derived value flows into "
                        f"{name}() — shard-varying tie-break/PRNG input "
                        f"breaks byte identity; route global coordinates "
                        f"through mesh_offsets",
                    ))
                    break
        if f.path.startswith(TOPK_DIR):
            for node in _own_body(fn):
                if (
                    isinstance(node, ast.Call)
                    and _call_name(node) in _TOPK_CALLS
                ):
                    kws = {kw.arg for kw in node.keywords}
                    missing = {"row_offset", "pod_offset"} - kws
                    if missing:
                        out.append(self.finding(
                            f, node,
                            f"{_call_name(node)}() without "
                            f"{'/'.join(sorted(missing))} — top-k "
                            f"tie-breaks must hash GLOBAL coordinates or "
                            f"the sharded cycle is only statistically "
                            f"equivalent to the single-device cycle",
                        ))
        return out

    # -- encode/merge determinism (rule 4) -------------------------------

    def _check_merge(self, f: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(f.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub, _target in flow.iterations_over_sets(node):
                out.append(self.finding(
                    f, sub,
                    "iteration over a set in an encode/merge path "
                    "feeding merge_packed byte-identity — set "
                    "order is hash-seed-dependent; iterate "
                    "sorted(...) or a list/dict instead",
                ))
        return out
