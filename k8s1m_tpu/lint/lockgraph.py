"""lock-order-cycle: the static lock acquisition-order graph.

``@guarded_by`` (rules_guards.py) proves each field access holds *its*
lock; nothing yet proves the locks themselves are acquired in one global
order.  Two call paths that nest the same pair of locks in opposite
orders are a deadlock waiting for the right interleaving — the class of
bug no amount of test traffic reliably finds (both orders run clean
until the day they overlap).  This pass builds the directed
acquisition-order graph over every ``with <lock>:`` in the tree and
fails on cycles, printing the conflicting acquisition paths.

Model:

- **Lock nodes** are ``path::Class.attr`` for instance locks
  (``self._lock = threading.Lock()`` — attributed to the *defining*
  class, so ``Counter.inc``'s lock is ``Metric._lock``) and
  ``path::NAME`` for module-level locks.  ``threading.Condition(self._x)``
  aliases to ``_x``; a bare ``Condition()`` is its own (reentrant) lock.
- **Edges** A -> B mean "B was acquired while A was held": directly
  (lexically nested ``with``) or interprocedurally — a call made under A
  reaching, through the intra-repo call graph (bounded depth, receiver
  types inferred from constructor assignments, parameter annotations and
  one-level factory returns), a function that acquires B.  Every edge
  carries a witness: outer site, inner site, and the call chain between
  them.
- **Cycles** fail the lint.  A self-edge (A -> A) fails only for a
  non-reentrant ``Lock`` whose witness chain stays on ``self`` — the
  provable single-instance re-acquisition deadlock; same-class
  cross-instance nesting (two ``HostFeed``s, say) shares a node but is
  not provably the same lock, so it is recorded in the artifact and not
  failed.

The graph itself is a committed artifact (``artifacts/lockgraph.json``,
written by ``python -m k8s1m_tpu.lint --write-lockgraph``) so every PR
diff shows exactly which acquisition orders it adds — the reviewable
form of the discipline, not just the pass/fail bit.

Known limits (deliberate): ``lock.acquire()``/``release()`` pairs
outside ``with`` are not tracked (the tree has none outside guards.py's
proxy), calls through function values (``fn()``, ``set_function``
callbacks) do not resolve, and ``super().__init__`` chains are skipped.
"""

from __future__ import annotations

import ast
import dataclasses
import json

from k8s1m_tpu.lint.base import Finding, Rule, SourceFile, call_name as _ctor_name

_MAX_DEPTH = 8


# ---- model -------------------------------------------------------------


@dataclasses.dataclass
class _Class:
    name: str
    path: str
    bases: list[str]
    node: ast.ClassDef
    lock_attrs: dict[str, str] = dataclasses.field(default_factory=dict)
    # attr -> "Lock" | "RLock" | "Condition"
    lock_alias: dict[str, str] = dataclasses.field(default_factory=dict)
    attr_types: dict[str, str] = dataclasses.field(default_factory=dict)
    methods: dict[str, "_Func"] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _Func:
    qual: str                      # "Class.meth" or "meth"
    path: str
    node: ast.AST
    cls: _Class | None
    # (lock node id, line, receiver-is-self) in body order
    acquires: list[tuple[str, int, bool]] = dataclasses.field(
        default_factory=list
    )
    # (callee key, line, held stack [(lock, line)], receiver-is-self)
    calls: list[tuple[str, int, tuple, bool]] = dataclasses.field(
        default_factory=list
    )
    # direct nested pairs:
    # (outer lock, outer line, inner lock, inner line, both-on-self)
    nested: list[tuple[str, int, str, int, bool]] = dataclasses.field(
        default_factory=list
    )


@dataclasses.dataclass(frozen=True)
class Edge:
    src: str
    dst: str
    outer_site: str                # "path:line" where src was taken
    inner_site: str                # "path:line" where dst was taken
    via: tuple[str, ...]           # call chain, "" for lexical nesting
    self_chain: bool               # every hop stayed on ``self``


_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}


def _ann_name(ann: ast.AST | None) -> str | None:
    if ann is None:
        return None
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Attribute):
        return ann.attr
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.strip().split("[")[0].split(".")[-1] or None
    if isinstance(ann, ast.Subscript):
        return _ann_name(ann.value)
    return None


class LockModel:
    """The whole-tree lock/call model shared by the rule and the
    artifact writer."""

    def __init__(self, files: list[SourceFile]):
        self.files = [f for f in files if f.path.startswith("k8s1m_tpu/")]
        self.classes: dict[str, _Class] = {}        # simple name -> class
        self.module_locks: dict[tuple[str, str], str] = {}  # (path,name)->kind
        self.module_types: dict[tuple[str, str], str] = {}  # (path,name)->cls
        self.funcs: dict[str, _Func] = {}           # "path::qual" -> func
        self.factories: dict[tuple[str, str], str] = {}  # (path,fn)->cls
        self._collect_defs()
        self._summarize()
        self.edges = self._build_edges()

    # -- pass 1: classes, locks, types ---------------------------------

    def _collect_defs(self) -> None:
        for f in self.files:
            if not isinstance(f.tree, ast.Module):
                continue
            for node in f.tree.body:
                if isinstance(node, ast.ClassDef):
                    c = _Class(
                        node.name, f.path,
                        [b.id if isinstance(b, ast.Name) else getattr(b, "attr", "")
                         for b in node.bases],
                        node,
                    )
                    self._scan_class_attrs(c)
                    # First definition wins; name collisions are rare and
                    # deterministic this way.
                    self.classes.setdefault(node.name, c)
                elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt = node.targets[0]
                    if isinstance(tgt, ast.Name) and isinstance(
                        node.value, ast.Call
                    ):
                        ctor = _ctor_name(node.value)
                        if ctor in _LOCK_CTORS:
                            self.module_locks[(f.path, tgt.id)] = (
                                _LOCK_CTORS[ctor]
                            )
                        elif ctor is not None:
                            self.module_types[(f.path, tgt.id)] = ctor
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Return) and isinstance(
                            sub.value, ast.Call
                        ):
                            ctor = _ctor_name(sub.value)
                            if ctor is not None:
                                self.factories[(f.path, node.name)] = ctor
                                break

    def _scan_class_attrs(self, c: _Class) -> None:
        for node in ast.walk(c.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            if not (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                continue
            if isinstance(node.value, ast.Call):
                ctor = _ctor_name(node.value)
                if ctor in _LOCK_CTORS:
                    if ctor == "Condition" and node.value.args:
                        a0 = node.value.args[0]
                        if (
                            isinstance(a0, ast.Attribute)
                            and isinstance(a0.value, ast.Name)
                            and a0.value.id == "self"
                        ):
                            c.lock_alias[tgt.attr] = a0.attr
                            continue
                    c.lock_attrs[tgt.attr] = _LOCK_CTORS[ctor]
                elif ctor is not None:
                    c.attr_types.setdefault(tgt.attr, ctor)
            elif isinstance(node.value, ast.Name):
                # self.x = param — type from the parameter annotation of
                # the enclosing function, found lazily in _param_types.
                c.attr_types.setdefault(
                    tgt.attr, f"<param>{node.value.id}"
                )

    # -- resolution helpers --------------------------------------------

    def _class_of(self, name: str | None) -> _Class | None:
        return self.classes.get(name) if name else None

    def _lock_owner(self, cls: _Class | None, attr: str) -> _Class | None:
        """The class (self or any base, BFS) whose __init__ assigns the
        lock — multiple inheritance checks EVERY base, or a LockMixin's
        lock would silently vanish from the graph."""
        queue = [cls] if cls is not None else []
        seen: set[str] = set()
        while queue:
            c = queue.pop(0)
            if c is None or c.name in seen:
                continue
            seen.add(c.name)
            if attr in c.lock_attrs:
                return c
            queue.extend(
                self.classes.get(b) for b in c.bases
                if self.classes.get(b) is not None
            )
        return None

    def _lock_node(self, cls: _Class | None, attr: str) -> str | None:
        owner = self._lock_owner(cls, attr)
        if owner is None:
            return None
        return f"{owner.path}::{owner.name}.{attr}"

    def lock_kind(self, node_id: str) -> str:
        path, _, rest = node_id.partition("::")
        if "." in rest:
            cname, attr = rest.split(".", 1)
            c = self.classes.get(cname)
            if c is not None:
                return c.lock_attrs.get(attr, "Lock")
            return "Lock"
        return self.module_locks.get((path, rest), "Lock")

    def _method_of(self, cls: _Class | None, name: str) -> _Func | None:
        """Method lookup over self and ALL bases (BFS; approximates the
        MRO closely enough for a lint — exact C3 order only matters
        when two bases define the same method AND acquire different
        locks in it)."""
        queue = [cls] if cls is not None else []
        seen: set[str] = set()
        while queue:
            c = queue.pop(0)
            if c is None or c.name in seen:
                continue
            seen.add(c.name)
            fn = c.methods.get(name)
            if fn is not None:
                return fn
            queue.extend(
                self.classes.get(b) for b in c.bases
                if self.classes.get(b) is not None
            )
        return None

    # -- pass 2: per-function summaries --------------------------------

    def _summarize(self) -> None:
        # Pre-register every function/method so calls resolve regardless
        # of definition order (forward references are the common case).
        work: list[tuple[SourceFile, ast.AST, _Class | None]] = []
        for f in self.files:
            if not isinstance(f.tree, ast.Module):
                continue
            for node in f.tree.body:
                if isinstance(node, ast.ClassDef):
                    c = self.classes.get(node.name)
                    if c is None or c.path != f.path:
                        continue
                    for sub in node.body:
                        if isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            fn = _Func(f"{c.name}.{sub.name}", f.path, sub, c)
                            c.methods[sub.name] = fn
                            self.funcs[f"{f.path}::{fn.qual}"] = fn
                            work.append((f, sub, c))
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = _Func(node.name, f.path, node, None)
                    self.funcs[f"{f.path}::{fn.qual}"] = fn
                    work.append((f, node, None))
        imports_cache: dict[str, dict[str, str]] = {}
        for f, node, c in work:
            if f.path not in imports_cache:
                imports_cache[f.path] = self._imports_of(f)
            self._summarize_func(f, node, c, imports_cache[f.path])

    def _imports_of(self, f: SourceFile) -> dict[str, tuple[str | None, str]]:
        """local name -> (source module dotted path or None, simple name).

        The module matters: resolving an imported callee by simple-name
        suffix alone could bind `flush` to whichever repo module sorts
        first and fabricate phantom acquisition edges.
        """
        out: dict[str, tuple[str | None, str]] = {}
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    out[alias.asname or alias.name] = (
                        node.module if not node.level else None,
                        alias.name,
                    )
        return out

    def _summarize_func(
        self, f: SourceFile, fn, cls: _Class | None, imports: dict
    ) -> _Func:
        qual = f"{cls.name}.{fn.name}" if cls is not None else fn.name
        out = self.funcs[f"{f.path}::{qual}"]
        param_types: dict[str, str] = {}
        for a in list(fn.args.args) + list(fn.args.kwonlyargs):
            t = _ann_name(a.annotation)
            if t is not None:
                param_types[a.arg] = t
        local_types: dict[str, str] = dict(param_types)

        def resolve_param_attr(tname: str | None) -> str | None:
            # "<param>x" markers from _scan_class_attrs resolve through
            # the __init__ annotations of the owning class.
            if tname is None or not tname.startswith("<param>"):
                return tname
            if cls is None:
                return None
            init = cls.methods.get("__init__")
            pname = tname[len("<param>"):]
            node = init.node if init is not None else None
            if node is None:
                for sub in cls.node.body:
                    if (
                        isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and sub.name == "__init__"
                    ):
                        node = sub
                        break
            if node is None:
                return None
            for a in list(node.args.args) + list(node.args.kwonlyargs):
                if a.arg == pname:
                    return _ann_name(a.annotation)
            return None

        def type_of(expr: ast.AST) -> str | None:
            """Best-effort class simple-name of an expression."""
            if isinstance(expr, ast.Name):
                t = local_types.get(expr.id)
                if t is not None:
                    return t
                t = self.module_types.get((f.path, expr.id))
                if t is not None:
                    return t
                return None
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and cls is not None
            ):
                return resolve_param_attr(cls.attr_types.get(expr.attr))
            if isinstance(expr, ast.Call):
                ctor = _ctor_name(expr)
                if ctor in self.classes:
                    return ctor
                if ctor is not None:
                    fac = self.factories.get((f.path, ctor))
                    if fac is not None:
                        return fac
            return None

        def lock_id(expr: ast.AST) -> tuple[str | None, bool]:
            """(lock node id, receiver-is-self) for a with-item."""
            if isinstance(expr, ast.Attribute):
                if isinstance(expr.value, ast.Name):
                    if expr.value.id == "self" and cls is not None:
                        attr = cls.lock_alias.get(expr.attr, expr.attr)
                        return self._lock_node(cls, attr), True
                    t = self._class_of(type_of(expr.value))
                    if t is not None:
                        attr = t.lock_alias.get(expr.attr, expr.attr)
                        return self._lock_node(t, attr), False
                elif isinstance(expr.value, ast.Attribute):
                    t = self._class_of(type_of(expr.value))
                    if t is not None:
                        attr = t.lock_alias.get(expr.attr, expr.attr)
                        return self._lock_node(t, attr), False
            elif isinstance(expr, ast.Name):
                name = expr.id
                if (f.path, name) in self.module_locks:
                    return f"{f.path}::{name}", False
                imp = imports.get(name)
                if imp is not None and imp[0] is not None:
                    p = imp[0].replace(".", "/") + ".py"
                    if (p, imp[1]) in self.module_locks:
                        return f"{p}::{imp[1]}", False
            return None, False

        def callee_key(call: ast.Call) -> tuple[str | None, bool]:
            """(func table key, receiver-is-self) for a call."""
            fnexpr = call.func
            if isinstance(fnexpr, ast.Name):
                name = fnexpr.id
                key = f"{f.path}::{name}"      # same-module first
                if key in self.funcs:
                    return key, False
                imported = imports.get(name)
                if imported is not None:
                    module, simple = imported
                    if module is not None:
                        # Exact: the imported module's own function.
                        mkey = f"{module.replace('.', '/')}.py::{simple}"
                        if mkey in self.funcs:
                            return mkey, False
                return None, False
            if isinstance(fnexpr, ast.Attribute):
                if (
                    isinstance(fnexpr.value, ast.Name)
                    and fnexpr.value.id == "self"
                    and cls is not None
                ):
                    m = self._method_of(cls, fnexpr.attr)
                    if m is not None:
                        return f"{m.path}::{m.qual}", True
                    return None, False
                t = self._class_of(type_of(fnexpr.value))
                if t is not None:
                    m = self._method_of(t, fnexpr.attr)
                    if m is not None:
                        return f"{m.path}::{m.qual}", False
            return None, False

        def visit(node: ast.AST, held: tuple) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new = list(held)
                for item in node.items:
                    lid, via_self = lock_id(item.context_expr)
                    if lid is not None:
                        line = item.context_expr.lineno
                        out.acquires.append((lid, line, via_self))
                        for hl, hline, h_self in new:
                            # both-on-self only when BOTH receivers are
                            # ``self``: `with self._a: with other._a:`
                            # shares a node but is not provably the
                            # same lock instance.
                            out.nested.append(
                                (hl, hline, lid, line, h_self and via_self)
                            )
                        new.append((lid, line, via_self))
                    else:
                        # Not a recognizable lock — but the header runs
                        # under the locks of the items before it, and a
                        # call in it (`with self._a, self._grab_b():`)
                        # can acquire locks: visit it with the stack.
                        visit(item.context_expr, tuple(new))
                for child in node.body:
                    visit(child, tuple(new))
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return      # runs later / different scope: no lock context
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    t = type_of(node.value)
                    if t is not None:
                        local_types[tgt.id] = t
            if isinstance(node, ast.Call):
                key, via_self = callee_key(node)
                if key is not None:
                    out.calls.append((key, node.lineno, held, via_self))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for child in fn.body:
            visit(child, ())
        return out

    # -- pass 3: edges ---------------------------------------------------

    def _transitive_acquires(
        self, key: str, depth: int, stack: frozenset
    ) -> list[tuple[str, str, tuple[str, ...], bool]]:
        """[(lock, site, via chain, all-self)] reachable from ``key``."""
        if depth > _MAX_DEPTH or key in stack:
            return []
        fn = self.funcs.get(key)
        if fn is None:
            return []
        out = []
        for lid, line, via_self in fn.acquires:
            out.append((lid, f"{fn.path}:{line}", (), via_self))
        stack = stack | {key}
        for callee, line, _held, call_self in fn.calls:
            step = f"{callee.split('::')[-1]} ({fn.path}:{line})"
            for lid, site, via, chain_self in self._transitive_acquires(
                callee, depth + 1, stack
            ):
                out.append(
                    (lid, site, (step,) + via, call_self and chain_self)
                )
        return out

    def _build_edges(self) -> list[Edge]:
        # Keyed on (src, dst, self_chain): a self-chain witness is what
        # proves a single-instance re-acquisition deadlock, so it must
        # never be displaced by a shorter cross-instance witness of the
        # same (src, dst) pair — both variants are kept.
        edges: dict[tuple[str, str, bool], Edge] = {}

        def add(e: Edge) -> None:
            k = (e.src, e.dst, e.self_chain)
            prev = edges.get(k)
            if prev is None or (
                (len(e.via), e.outer_site, e.inner_site)
                < (len(prev.via), prev.outer_site, prev.inner_site)
            ):
                edges[k] = e

        for key in sorted(self.funcs):
            fn = self.funcs[key]
            for outer, oline, inner, iline, both_self in fn.nested:
                add(Edge(
                    outer, inner,
                    f"{fn.path}:{oline}", f"{fn.path}:{iline}",
                    (), both_self,
                ))
            for callee, line, held, call_self in fn.calls:
                if not held:
                    continue
                step = f"{callee.split('::')[-1]} ({fn.path}:{line})"
                for lid, site, via, chain_self in self._transitive_acquires(
                    callee, 1, frozenset({key})
                ):
                    for hl, hline, h_self in held:
                        add(Edge(
                            hl, lid,
                            f"{fn.path}:{hline}", site,
                            (step,) + via,
                            h_self and call_self and chain_self,
                        ))
        return sorted(
            edges.values(), key=lambda e: (e.src, e.dst, e.self_chain)
        )

    # -- cycles ----------------------------------------------------------

    def cycles(self) -> list[list[Edge]]:
        """Elementary cycles that FAIL the lint: every multi-node cycle,
        plus single-node self-loops provably on one instance of a
        non-reentrant Lock."""
        by_src: dict[str, list[Edge]] = {}
        for e in self.edges:
            by_src.setdefault(e.src, []).append(e)
        out: list[list[Edge]] = []
        seen_keys: set[tuple] = set()

        for e in self.edges:
            if e.src == e.dst and e.self_chain and (
                self.lock_kind(e.src) == "Lock"
            ):
                out.append([e])

        # Bounded DFS for multi-node cycles (the graph is tiny; edges
        # number in the tens).
        def dfs(start: str, node: str, path: list[Edge], seen: set) -> None:
            for e in by_src.get(node, ()):
                if e.src == e.dst:
                    continue
                if e.dst == start and path:
                    cyc = path + [e]
                    key = tuple(sorted((c.src, c.dst) for c in cyc))
                    if key not in seen_keys:
                        seen_keys.add(key)
                        out.append(cyc)
                elif e.dst not in seen and len(path) < 6:
                    dfs(start, e.dst, path + [e], seen | {e.dst})

        for n in sorted({e.src for e in self.edges}):
            dfs(n, n, [], {n})
        return out

    # -- artifact --------------------------------------------------------

    def to_json(self, files: list[SourceFile] | None = None) -> dict:
        """The committed-artifact shape.  With ``files``, each cycle
        carries ``sanctioned``: True when every per-file finding it
        yields is pragma-suppressed (the ok_lockorder fixture pattern) —
        the CLI and the tier-1 artifact gate fail only on unsanctioned
        cycles, so the documented escape hatch actually escapes."""
        cycles = []
        for cyc, findings in cycle_findings(self, files or []):
            cycles.append({
                "edges": [
                    f"{c.src} -> {c.dst} ({c.outer_site} -> {c.inner_site})"
                    for c in cyc
                ],
                "sanctioned": bool(files) and sanctioned(files, findings),
            })
        return {
            "nodes": sorted(
                {e.src for e in self.edges} | {e.dst for e in self.edges}
            ),
            "edges": [
                {
                    "from": e.src,
                    "to": e.dst,
                    "outer_site": e.outer_site,
                    "inner_site": e.inner_site,
                    "via": list(e.via),
                    "self_chain": e.self_chain,
                }
                for e in self.edges
            ],
            "cycles": cycles,
        }


def cycle_findings(
    model: LockModel, files: list[SourceFile]
) -> list[tuple[list[Edge], list[Finding]]]:
    """Per cycle: ALL the findings it yields — one per file the cycle's
    acquisition sites touch, anchored at that file's lexically-last
    site.  A changed-only/subset run then still reports the cycle for
    the file that introduced its half of the inversion.  Pragma
    suppression is NOT applied here (the driver does that, so used-
    pragma accounting stays correct); use ``sanctioned`` to ask whether
    every finding of a cycle is pragma'd."""
    by_path = {f.path: f for f in files}
    out: list[tuple[list[Edge], list[Finding]]] = []
    for cyc in model.cycles():
        sites: dict[str, int] = {}
        for e in cyc:
            for site in (e.outer_site, e.inner_site):
                p, _, ln = site.rpartition(":")
                sites[p] = max(sites.get(p, 0), int(ln))
        findings: list[Finding] = []
        for path in sorted(sites):
            line = sites[path]
            src = by_path.get(path)
            findings.append(Finding(
                path, line, LockOrderCycle.id,
                "lock acquisition order cycle (potential deadlock): "
                + render_cycle(cyc),
                (
                    src.lines[line - 1].strip()
                    if src and 0 < line <= len(src.lines) else ""
                ),
            ))
        out.append((cyc, findings))
    return out


def sanctioned(files: list[SourceFile], findings: list[Finding]) -> bool:
    """True when every finding of a cycle is pragma-suppressed in its
    file — the documented escape hatch for a reviewed-safe inversion."""
    from k8s1m_tpu.lint.base import suppressed

    by_path = {f.path: f for f in files}
    return bool(findings) and all(
        by_path.get(fd.path) is not None
        and suppressed(by_path[fd.path], fd)
        for fd in findings
    )


def render_cycle(cyc: list[Edge]) -> str:
    """Human-readable conflicting acquisition paths for one cycle."""
    parts = []
    for e in cyc:
        chain = " -> ".join(e.via) if e.via else "lexically nested"
        parts.append(
            f"{e.src} held at {e.outer_site} then {e.dst} at "
            f"{e.inner_site} [{chain}]"
        )
    return " || ".join(parts)


def write_artifact(
    model: LockModel, path: str, files: list[SourceFile] | None = None
) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(model.to_json(files), fh, indent=2, sort_keys=True)
        fh.write("\n")


class LockOrderCycle(Rule):
    id = "lock-order-cycle"

    def check_tree(self, files: list[SourceFile]) -> list[Finding]:
        model = LockModel(files)
        out: list[Finding] = []
        for _cyc, findings in cycle_findings(model, files):
            out.extend(findings)
        return sorted(out, key=lambda fd: (fd.path, fd.line))
