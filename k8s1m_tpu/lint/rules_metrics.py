"""metrics-registry: the metric namespace is a checked interface.

Three invariants over the whole tree (a cross-file rule):

1. **Declared exactly once** — every metric name constructed via the
   ``obs/metrics.py`` helpers (Counter/Gauge/Histogram/
   AlertingHistogram/CallbackMetric) appears in exactly one
   declaration under ``k8s1m_tpu/``.  The runtime Registry only catches
   duplicates that actually import together; this catches them at lint
   time, tree-wide.
2. **Dashboard coverage both ways** — every row prefix in
   ``obs/dashboard.py`` matches at least one declared metric (a stale
   prefix is a silently empty dashboard row), and every declared
   metric is covered by some row prefix (an uncovered metric is
   evidence nobody can see).
3. **Label-set consistency** — every ``.inc()/.set()/.observe()/...``
   call site passes exactly the declared label names (call sites using
   ``**kwargs`` are skipped — they are dynamic by construction).

Tests are exempt from declaration scanning: scoped registries with
colliding names are a legitimate fixture pattern.
"""

from __future__ import annotations

import ast
import dataclasses

from k8s1m_tpu.lint.base import Finding, Rule, SourceFile, dotted_name

_CTORS = {"Counter", "Gauge", "Histogram", "AlertingHistogram",
          "CallbackMetric"}
# Metric methods whose **labels kwargs must match the declaration.
_LABEL_METHODS = {"inc", "dec", "set", "observe", "observe_many", "time",
                  "value", "set_function", "sum", "quantile"}

DASHBOARD_PATH = "k8s1m_tpu/obs/dashboard.py"
# Declared metrics that intentionally render nowhere (internal plumbing
# with a dedicated consumer rather than a panel).
DASHBOARD_EXEMPT: set[str] = set()


@dataclasses.dataclass
class _Decl:
    name: str
    labels: tuple[str, ...] | None   # None = not statically resolvable
    file: SourceFile
    node: ast.Call
    var: str | None                  # module-level variable name, if any


def _ctor_name(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1]
    return leaf if leaf in _CTORS else None


def _const_str_tuple(node: ast.AST) -> tuple[str, ...] | None:
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    return None


def _labels_of(call: ast.Call, ctor: str) -> tuple[str, ...] | None:
    """Statically-known labelnames of a metric constructor call."""
    if ctor == "CallbackMetric":
        return ()                    # CallbackMetric has no labelnames arg
    for kw in call.keywords:
        if kw.arg == "labelnames":
            return _const_str_tuple(kw.value)
        if kw.arg is None:
            return None              # **kwargs construction: unknown
    # Positional: (name, help, labelnames, ...)
    if len(call.args) >= 3:
        return _const_str_tuple(call.args[2])
    return ()


class MetricsRegistry(Rule):
    id = "metrics-registry"

    def check_tree(self, files: list[SourceFile]) -> list[Finding]:
        out: list[Finding] = []
        decls: list[_Decl] = []
        # module dotted name -> {var -> decl}
        module_vars: dict[str, dict[str, _Decl]] = {}

        for f in files:
            if not f.path.startswith("k8s1m_tpu/"):
                continue
            mod = f.path[:-3].replace("/", ".")
            for node in ast.walk(f.tree):
                if not (isinstance(node, ast.Call) and _ctor_name(node)):
                    continue
                ctor = _ctor_name(node)
                name = None
                if node.args and isinstance(node.args[0], ast.Constant):
                    if isinstance(node.args[0].value, str):
                        name = node.args[0].value
                if name is None:
                    for kw in node.keywords:
                        if kw.arg == "name" and isinstance(
                            kw.value, ast.Constant
                        ):
                            name = kw.value.value
                if name is None:
                    continue        # dynamic name: out of scope
                decls.append(_Decl(name, _labels_of(node, ctor), f, node, None))
            # Map module-level vars to their decls.
            for stmt in f.tree.body:
                if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Call
                ) and _ctor_name(stmt.value):
                    for d in decls:
                        if d.node is stmt.value:
                            for tgt in stmt.targets:
                                if isinstance(tgt, ast.Name):
                                    d.var = tgt.id
                                    module_vars.setdefault(mod, {})[
                                        tgt.id
                                    ] = d

        # 1. declared exactly once.
        seen: dict[str, _Decl] = {}
        for d in decls:
            if d.name in seen:
                first = seen[d.name]
                out.append(self.finding(
                    d.file, d.node,
                    f"metric {d.name!r} declared more than once (first "
                    f"at {first.file.path}:{first.node.lineno})",
                ))
            else:
                seen[d.name] = d

        # 2. dashboard coverage, both directions.
        dash = next((f for f in files if f.path == DASHBOARD_PATH), None)
        if dash is not None and seen:
            prefixes = self._dashboard_prefixes(dash)
            names = set(seen)
            for prefix, node in prefixes:
                if not any(n.startswith(prefix) for n in names):
                    out.append(self.finding(
                        dash, node,
                        f"dashboard row prefix {prefix!r} matches no "
                        "declared metric (silently empty row)",
                    ))
            all_prefixes = tuple(p for p, _ in prefixes)
            for n, d in seen.items():
                if n in DASHBOARD_EXEMPT:
                    continue
                if all_prefixes and not n.startswith(all_prefixes):
                    out.append(self.finding(
                        d.file, d.node,
                        f"metric {n!r} is covered by no dashboard row "
                        "prefix (obs/dashboard.py ROWS) — unobservable "
                        "evidence",
                    ))

        # 3. label-set consistency at call sites.
        for f in files:
            if not f.path.startswith("k8s1m_tpu/"):
                continue
            local = dict(module_vars.get(f.path[:-3].replace("/", "."), {}))
            # Resolve `from k8s1m_tpu.x import METRIC [as alias]`.
            for node in ast.walk(f.tree):
                if isinstance(node, ast.ImportFrom) and node.module:
                    src = module_vars.get(node.module)
                    if not src:
                        continue
                    for alias in node.names:
                        if alias.name in src:
                            local[alias.asname or alias.name] = src[
                                alias.name
                            ]
            if not local:
                continue
            for node in ast.walk(f.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _LABEL_METHODS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in local
                ):
                    continue
                d = local[node.func.value.id]
                if d.labels is None:
                    continue
                if any(kw.arg is None for kw in node.keywords):
                    continue        # **labels: dynamic, skip
                got = {kw.arg for kw in node.keywords}
                want = set(d.labels)
                if got != want and (got or want):
                    out.append(self.finding(
                        f, node,
                        f"label set {sorted(got)} != declared "
                        f"{sorted(want)} for metric {d.name!r}",
                    ))
        return out

    @staticmethod
    def _dashboard_prefixes(dash: SourceFile) -> list[tuple[str, ast.AST]]:
        out: list[tuple[str, ast.AST]] = []
        for stmt in dash.tree.body:
            if not (
                isinstance(stmt, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "ROWS"
                    for t in stmt.targets
                )
            ):
                continue
            for n in ast.walk(stmt.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    if n.value.endswith("_"):
                        out.append((n.value, n))
        return out
