"""fenced-store-write: coordinator store writes flow through the fence.

ISSUE 9's fencing contract is only as strong as its coverage: ONE
bind/evict/preempt path writing to the store directly re-opens the
classic fencing-token gap (a deposed leader's in-flight wave landing a
write behind the new leader's takeover).  This rule keeps the funnel
airtight statically: inside ``k8s1m_tpu/control/``, any call to a store
write method (``cas`` / ``put`` / ``put_batch`` / ``delete`` /
``bind_batch`` / ``put_frame`` / ``bind_frame``) on a receiver whose
dotted name ends in ``store`` must sit inside one of the designated
fenced helpers (``_fenced_cas`` / ``_fenced_bind_batch``) — everything
else is a finding.

``control/leader.py`` is exempt wholesale: the lease CAS there IS the
fence's arbiter (an election write cannot gate on the election it
implements).  ``control/shardset.py``'s shard-lease heartbeat and
rebalance writes predate the epoch fence and are fenced by their own
shard-lease CAS — grandfathered in the baseline until shardset grows
epoch fencing of its own.
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint.base import (
    Finding,
    Rule,
    SourceFile,
    dotted_name,
    walk_no_nested_functions,
)

SCOPE = "k8s1m_tpu/control/"
EXEMPT_PATHS = ("k8s1m_tpu/control/leader.py",)
FENCED_FUNCS = {"_fenced_cas", "_fenced_bind_batch"}
WRITE_METHODS = {
    "cas", "put", "put_batch", "delete", "bind_batch", "put_frame",
    "bind_frame",
}


def _store_write(call: ast.Call) -> str | None:
    """The write-method name when ``call`` is ``<...>.store.<write>(...)``
    or ``store.<write>(...)``, else None."""
    name = dotted_name(call.func)
    if name is None:
        return None
    parts = name.split(".")
    if len(parts) < 2 or parts[-1] not in WRITE_METHODS:
        return None
    if parts[-2] != "store" and not parts[-2].endswith("_store"):
        return None
    return parts[-1]


class FencedStoreWrite(Rule):
    id = "fenced-store-write"

    def check_file(self, f: SourceFile) -> list[Finding]:
        if not f.path.startswith(SCOPE) or f.path in EXEMPT_PATHS:
            return []
        out: list[Finding] = []
        scopes: list[tuple[str, ast.AST]] = [("<module>", f.tree)]
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append((node.name, node))
        for fname, scope in scopes:
            if fname in FENCED_FUNCS:
                continue
            for node in walk_no_nested_functions(
                scope, descend_lambdas=True
            ):
                if not isinstance(node, ast.Call):
                    continue
                method = _store_write(node)
                if method is None:
                    continue
                out.append(self.finding(
                    f, node,
                    f"direct store.{method} on a coordinator path; "
                    "route through the epoch-fenced helper "
                    "(_fenced_cas / _fenced_bind_batch) so a deposed "
                    "reign's writes can never land behind a takeover "
                    "(ISSUE 9 fencing contract)",
                ))
        return out
