"""The committed baseline: grandfathered findings, tracked until fixed.

Format — one entry per line, ``#`` comments encouraged (one per entry,
saying WHY it is grandfathered rather than fixed)::

    # soak cleanup: wait-then-kill is the documented teardown ladder
    k8s1m_tpu/tools/soak.py|broad-except|except Exception:

Fields are ``path|rule-id|source-fingerprint`` where the fingerprint is
the stripped text of the offending line — stable across the line-number
drift that makes path:line baselines rot.  Identical (path, rule,
fingerprint) triples are counted: two hits need two entries.

Matching is exact in both directions: a finding with no entry is NEW
(lint fails); an entry with no finding is STALE (``--check-baseline``
fails, so a fixed site must also be removed from the file — no silent
drift either way).
"""

from __future__ import annotations

import collections

from k8s1m_tpu.lint.base import Finding

BASELINE_NAME = "lint_baseline.txt"


def parse_baseline(text: str) -> list[tuple[str, str, str]]:
    entries: list[tuple[str, str, str]] = []
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split("|", 2)
        if len(parts) != 3:
            raise ValueError(
                f"baseline line {lineno}: want 'path|rule|fingerprint', "
                f"got {raw!r}"
            )
        entries.append((parts[0], parts[1], parts[2]))
    return entries


def format_entry(finding: Finding) -> str:
    return f"{finding.path}|{finding.rule}|{finding.source}"


def split_findings(
    findings: list[Finding], entries: list[tuple[str, str, str]]
) -> tuple[list[Finding], list[tuple[str, str, str]]]:
    """(new findings, stale entries) after counted matching."""
    budget = collections.Counter(entries)
    new: list[Finding] = []
    for fd in findings:
        key = (fd.path, fd.rule, fd.source)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
        else:
            new.append(fd)
    stale = [k for k, n in budget.items() for _ in range(n)]
    return new, stale
