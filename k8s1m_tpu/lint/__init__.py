"""graftlint — project-native static analysis + runtime lock discipline.

Static half (``python -m k8s1m_tpu.lint``): AST passes enforcing the
invariants no generic linter knows — no host sync in the TPU cycle
path, no wall clock where determinism-by-seed is the contract, all
retries through the one faultline RetryPolicy, a checked metric
namespace, no silent ``except Exception``, no trace-time branching on
traced values.  See cli.py for the driver, base.py for the pragma and
baseline escape hatches.

Runtime half (``lint/guards.py``): ``@guarded_by`` annotations on
shared mutable state, audited under a test-only instrumentation mode
that raises on any access without the named lock held (or off the
owning thread) — the race detector for the webhook-thread vs
cycle-thread interleavings the overload and pipelining work hardened
by hand.

This module deliberately imports only the guards API: production code
imports ``guarded_by`` from here, and must not pay for (or depend on)
the ast machinery.
"""

from k8s1m_tpu.lint.guards import (  # noqa: F401
    THREAD_OWNER,
    GuardViolation,
    audit,
    audit_enabled,
    disown,
    guarded_by,
    racy_read,
    set_owner,
    violations,
)
