"""graftlint core: files, pragmas, findings, and the rule protocol.

The framework is deliberately small — ``ast`` stdlib only, no
configuration language.  A rule is a class with an ``id`` and either a
``check_file(path, tree, lines)`` hook (runs per file) or a
``check_tree(files)`` hook (runs once over the parsed tree, for
cross-file invariants like the metric registry).  Findings are
``path:line rule-id message`` tuples; two escape hatches exist:

- a ``# graftlint: disable=<rule>[,<rule>]`` pragma on the offending
  line (or on a standalone comment line directly above it), for sites
  where the violation is deliberate and locally justified;
- the committed baseline file (see baseline.py), for grandfathered
  findings that predate the rule and are tracked until fixed.

Pragmas should carry a short reason in the same comment, e.g.::

    span = {"t": time.time()}  # graftlint: disable=no-wall-clock (wall stamp for cross-process correlation)
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import tokenize

# Directories never linted: generated protobufs, C sources, committed
# artifacts, the lint fixture tree (each fixture deliberately violates
# exactly one rule), and VCS/tool internals.
SKIP_DIRS = {
    ".git", "__pycache__", "artifacts", "lint_fixtures", "native",
    "related", "proto",
}
SKIP_FILE_SUFFIXES = ("_pb2.py",)

_PRAGMA_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str          # repo-root-relative, '/'-separated
    line: int          # 1-based
    rule: str          # rule id, e.g. "broad-except"
    message: str
    # The stripped source text of the offending line: the baseline's
    # drift-stable fingerprint (line numbers move; the text rarely does).
    source: str = ""

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"


@dataclasses.dataclass
class SourceFile:
    path: str                    # repo-root-relative
    abspath: str
    tree: ast.AST
    lines: list[str]             # raw source lines (index 0 = line 1)
    pragmas: dict[int, set[str]]  # line -> disabled rule ids


class Rule:
    """Base rule.  Subclasses set ``id`` and override one hook."""

    id = ""

    def check_file(self, f: SourceFile) -> list[Finding]:
        return []

    def check_tree(self, files: list[SourceFile]) -> list[Finding]:
        return []

    # -- helpers ---------------------------------------------------------

    def finding(self, f: SourceFile, node_or_line, message: str) -> Finding:
        line = (
            node_or_line
            if isinstance(node_or_line, int)
            else getattr(node_or_line, "lineno", 0)
        )
        src = f.lines[line - 1].strip() if 0 < line <= len(f.lines) else ""
        return Finding(f.path, line, self.id, message, src)


def _collect_pragmas(source: str) -> dict[int, set[str]]:
    """Map line -> rule ids disabled on that line.

    A pragma comment that shares its line with code applies to that
    line; a standalone pragma comment applies to the next line holding
    code (so multi-line statements can be annotated above).
    """
    pragmas: dict[int, set[str]] = {}
    import io

    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return pragmas
    lines = source.splitlines()
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _PRAGMA_RE.search(tok.string)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        lineno = tok.start[0]
        stripped = lines[lineno - 1].strip() if lineno <= len(lines) else ""
        if stripped.startswith("#"):
            # Standalone comment: applies to the next code line.
            tgt = lineno + 1
            while tgt <= len(lines) and (
                not lines[tgt - 1].strip()
                or lines[tgt - 1].strip().startswith("#")
            ):
                tgt += 1
            pragmas.setdefault(tgt, set()).update(rules)
        else:
            pragmas.setdefault(lineno, set()).update(rules)
    return pragmas


def load_file(root: str, relpath: str) -> SourceFile | None:
    abspath = os.path.join(root, relpath)
    try:
        with open(abspath, encoding="utf-8") as fh:
            source = fh.read()
        tree = ast.parse(source, filename=relpath)
    except (OSError, SyntaxError, ValueError):
        return None
    return SourceFile(
        path=relpath.replace(os.sep, "/"),
        abspath=abspath,
        tree=tree,
        lines=source.splitlines(),
        pragmas=_collect_pragmas(source),
    )


def iter_py_files(root: str, subdirs: tuple[str, ...] = ()) -> list[str]:
    """Repo-relative paths of lintable .py files under ``root`` (or only
    under ``root/<subdir>`` for each given subdir)."""
    out: list[str] = []
    starts = [os.path.join(root, s) for s in subdirs] if subdirs else [root]
    for start in starts:
        for dirpath, dirnames, filenames in os.walk(start):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in SKIP_DIRS and not d.startswith(".")
            )
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                if fn.endswith(SKIP_FILE_SUFFIXES):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                out.append(rel)
    return out


def suppressed(f: SourceFile, finding: Finding) -> bool:
    return finding.rule in f.pragmas.get(finding.line, ())


# -- small AST helpers shared by rules ----------------------------------


def call_name(call: ast.Call) -> str | None:
    """The simple callee name of a call: 'f' for ``f(...)`` and for
    ``a.b.f(...)`` alike, else None.  The one resolution rule every
    pass shares — keep refinements here, not in per-rule copies."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def dotted_name(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def walk_no_nested_functions(node: ast.AST, *, descend_lambdas: bool = False):
    """Yield nodes in ``node``'s body without descending into nested
    function/class definitions (their bodies run in another scope/time).
    ``descend_lambdas=True`` still walks lambda bodies — for passes
    whose property (e.g. value purity) holds across the lambda boundary
    even though the lambda runs later."""
    skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
    if not descend_lambdas:
        skip = skip + (ast.Lambda,)
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, skip):
            continue
        stack.extend(ast.iter_child_nodes(n))
