"""flow: the shared dataflow chassis graftlint rules are written on.

Sixteen rules grew sixteen private fragments of the same machinery —
binding collection, taint fixpoints, with-block lock context, call-graph
walking with receiver-type inference.  This module is that machinery
built once, so a rule states WHAT it checks (sources, sinks, guards)
and not HOW to walk the tree.  Four layers, independent and composable:

1. **Bindings + flow-insensitive taint** — ``collect_bindings`` gathers
   every binding form (plain/aug assignment, walrus, for-targets) in
   source order; ``taint_fixpoint`` closes a tainted-name set over them
   against a caller-supplied source predicate and laundering predicate.
   This is the exact engine rules_mesh.py grew for axis-index purity,
   extracted verbatim so the migration is byte-identical.

2. **CFG + dominators** — ``CFG.from_body`` builds an intraprocedural
   control-flow graph over a statement list (branches, loops,
   try/except/finally, with-blocks; break/continue resolved against the
   loop stack, return/raise edges to EXIT).  ``dominators()`` answers
   "every path to B passes A"; ``exit_reachable_avoiding`` answers
   "can control leave this region without passing one of these
   statements" — the two queries fail-closed accounting needs.

3. **Lexical lock context** — ``walk_held`` yields every node of a
   method with the ``with self.<lock>:`` set lexically held at it and
   the scope it runs in (nested defs/lambdas run later, on possibly
   another thread, so they inherit no lock context).  Extracted from
   rules_guards.py's summarizer; rules_guards consumes it now and
   blocking-under-lock shares it.

4. **Interprocedural call graph** — ``CallGraph`` resolves intra-repo
   calls (same-module names, ``from`` imports, ``self.`` methods with
   base-class lookup, receiver types inferred from constructor
   assignments, parameter annotations and one-level factory returns —
   the lockgraph.py discipline, generalized) and answers bounded-depth
   reachability queries: ``find_reachable`` (first node matching a
   predicate, with the call-chain witness) and ``returns_matching``
   (does a callee's return value derive from a source — the
   helper-propagation half of taint).

Known limits (deliberate, same family as lockgraph's): calls through
function values don't resolve, ``super()`` chains are skipped,
exceptions are modeled as edges from every statement of a ``try`` body
to each handler (not per-expression), and the taint fixpoint is
flow-insensitive — a name once tainted stays tainted for the whole
function, which over-approximates (safe for a linter with pragmas).
"""

from __future__ import annotations

import ast
import dataclasses

from k8s1m_tpu.lint.base import (
    SourceFile,
    call_name,
    walk_no_nested_functions,
)

# ---------------------------------------------------------------------------
# layer 0: tiny shared lexical helpers
# ---------------------------------------------------------------------------


def self_attr(node: ast.AST) -> str | None:
    """'x' for a ``self.x`` attribute node, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def own_body(fn: ast.AST):
    """Nodes of ``fn``'s own body — nested def/class bodies excluded,
    lambdas included (value-purity properties hold across the lambda
    boundary even though the body runs later)."""
    return walk_no_nested_functions(fn, descend_lambdas=True)


def mentions(node: ast.AST, names: set[str]) -> bool:
    """Does any Name in ``node`` belong to ``names``?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in names:
            return True
    return False


# ---------------------------------------------------------------------------
# layer 1: bindings + flow-insensitive taint fixpoint
# ---------------------------------------------------------------------------


def collect_bindings(fn: ast.AST) -> list[tuple[ast.AST, ast.AST]]:
    """(target, value) pairs for every binding form in ``fn``'s own
    body, in SOURCE order (the tree walk is unordered) — plain/aug
    assignment, walrus, and for-targets.  An ``x += tainted`` must not
    launder, so AugAssign contributes both (target, value) and
    (target, target)."""
    bindings: list[tuple[ast.AST, ast.AST]] = []
    for node in own_body(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                bindings.append((tgt, node.value))
        elif isinstance(node, ast.AugAssign):
            bindings.append((node.target, node.value))
            bindings.append((node.target, node.target))
        elif isinstance(node, ast.NamedExpr):
            bindings.append((node.target, node.value))
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            bindings.append((node.target, node.iter))
    bindings.sort(key=lambda tv: (tv[1].lineno, tv[1].col_offset))
    return bindings


def taint_fixpoint(
    bindings: list[tuple[ast.AST, ast.AST]],
    *,
    contains_source,
    launders=None,
    seeds: set[str] | None = None,
) -> set[str]:
    """Close the tainted-name set over ``bindings`` to a fixpoint.

    ``contains_source(expr)`` says an expression introduces taint on
    its own; ``launders(expr)`` marks a value expression as a sanctioned
    laundering point (its targets stay clean regardless of inputs);
    ``seeds`` pre-taints names (e.g. a for-target over a set).  Chains
    like ``idx = source(); off = idx * 128`` taint through any number
    of intermediates, including through loops (hence the fixpoint)."""
    tainted: set[str] = set(seeds or ())
    changed = True
    while changed:
        changed = False
        for tgt, value in bindings:
            if launders is not None and launders(value):
                continue
            if contains_source(value) or mentions(value, tainted):
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name) and sub.id not in tainted:
                        tainted.add(sub.id)
                        changed = True
    return tainted


def expr_tainted(expr: ast.AST, tainted: set[str], contains_source) -> bool:
    """Is ``expr`` tainted — directly (contains a source) or through a
    tainted name?"""
    return contains_source(expr) or mentions(expr, tainted)


# ---------------------------------------------------------------------------
# layer 1b: set-valuedness (iteration-order nondeterminism)
# ---------------------------------------------------------------------------


def set_locals_of(fn: ast.AST) -> set[str]:
    """Names provably bound to set values in ``fn``'s own body."""
    out: set[str] = set()
    for sub in own_body(fn):
        tgts: list[ast.AST] = []
        value: ast.AST | None = None
        if isinstance(sub, ast.Assign):
            tgts, value = sub.targets, sub.value
        elif isinstance(sub, (ast.AugAssign, ast.NamedExpr)):
            tgts, value = [sub.target], sub.value
        if tgts and value is not None and is_set_expr(value, out):
            for tgt in tgts:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def is_set_expr(node: ast.AST, set_locals: set[str]) -> bool:
    """A provably-set-valued expression (not wrapped in sorted)."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Name):
        return node.id in set_locals
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name in ("set", "frozenset"):
            return True
        # set-returning methods on a set-valued receiver
        if name in ("union", "intersection", "difference") and isinstance(
            node.func, ast.Attribute
        ):
            return is_set_expr(node.func.value, set_locals)
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.BitOr, ast.BitAnd, ast.Sub)
    ):
        return is_set_expr(node.left, set_locals) or (
            is_set_expr(node.right, set_locals)
        )
    return False


def iterations_over_sets(fn: ast.AST) -> list[tuple[ast.AST, ast.AST]]:
    """(iterating node, target) for every for-loop/comprehension in
    ``fn``'s own body whose iterable is provably a set — the
    hash-seed-ordering injection points."""
    set_locals = set_locals_of(fn)
    out: list[tuple[ast.AST, ast.AST]] = []
    for sub in own_body(fn):
        if isinstance(sub, (ast.For, ast.AsyncFor)):
            if is_set_expr(sub.iter, set_locals):
                out.append((sub, sub.target))
        elif isinstance(sub, (ast.ListComp, ast.SetComp, ast.DictComp,
                              ast.GeneratorExp)):
            for g in sub.generators:
                if is_set_expr(g.iter, set_locals):
                    out.append((sub, g.target))
                    break
    return out


# ---------------------------------------------------------------------------
# layer 2: intraprocedural CFG + dominators
# ---------------------------------------------------------------------------

ENTRY = -1
EXIT = -2


@dataclasses.dataclass
class _Loop:
    header: int
    breaks: list[int]


class CFG:
    """Statement-granular control-flow graph over one body.

    Nodes are statements (compound statements appear as their own
    header node; their bodies are nested statements with edges wired
    through).  ``ENTRY``/``EXIT`` are virtual.  Return/Raise edge to
    EXIT; break/continue resolve against the enclosing loop, or EXIT
    when the region itself is being analyzed in isolation (a handler
    body inside a loop the region doesn't contain).  A ``try`` body may
    raise anywhere, modeled as edges from every body statement (and the
    frontier entering the try) to each handler's entry."""

    def __init__(self) -> None:
        self.nodes: list[ast.stmt] = []
        self.succ: dict[int, set[int]] = {ENTRY: set(), EXIT: set()}
        self.pred: dict[int, set[int]] = {ENTRY: set(), EXIT: set()}
        self._ids: dict[int, int] = {}          # id(stmt) -> node index

    # -- construction ---------------------------------------------------

    @classmethod
    def from_body(cls, stmts: list[ast.stmt]) -> "CFG":
        cfg = cls()
        frontier = cfg._seq(stmts, {ENTRY}, [])
        for n in frontier:
            cfg._edge(n, EXIT)
        return cfg

    @classmethod
    def from_function(cls, fn: ast.AST) -> "CFG":
        return cls.from_body(list(fn.body))

    def _new(self, stmt: ast.stmt) -> int:
        idx = len(self.nodes)
        self.nodes.append(stmt)
        self.succ[idx] = set()
        self.pred[idx] = set()
        self._ids[id(stmt)] = idx
        return idx

    def _edge(self, a: int, b: int) -> None:
        self.succ[a].add(b)
        self.pred[b].add(a)

    def _enter(self, stmt: ast.stmt, frontier: set[int]) -> int:
        idx = self._new(stmt)
        for n in frontier:
            self._edge(n, idx)
        return idx

    def _seq(
        self, stmts: list[ast.stmt], frontier: set[int], loops: list[_Loop]
    ) -> set[int]:
        for stmt in stmts:
            if not frontier:
                # Unreachable code after return/raise/break: still give
                # it nodes (dominator queries over it are vacuous).
                pass
            frontier = self._stmt(stmt, frontier, loops)
        return frontier

    def _stmt(
        self, stmt: ast.stmt, frontier: set[int], loops: list[_Loop]
    ) -> set[int]:
        if isinstance(stmt, (ast.Return, ast.Raise)):
            idx = self._enter(stmt, frontier)
            self._edge(idx, EXIT)
            return set()
        if isinstance(stmt, ast.Break):
            idx = self._enter(stmt, frontier)
            if loops:
                loops[-1].breaks.append(idx)
            else:
                self._edge(idx, EXIT)
            return set()
        if isinstance(stmt, ast.Continue):
            idx = self._enter(stmt, frontier)
            self._edge(idx, loops[-1].header) if loops else (
                self._edge(idx, EXIT)
            )
            return set()
        if isinstance(stmt, ast.If):
            hdr = self._enter(stmt, frontier)
            body_f = self._seq(stmt.body, {hdr}, loops)
            if stmt.orelse:
                else_f = self._seq(stmt.orelse, {hdr}, loops)
                return body_f | else_f
            return body_f | {hdr}
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            hdr = self._enter(stmt, frontier)
            loop = _Loop(hdr, [])
            loops.append(loop)
            body_f = self._seq(stmt.body, {hdr}, loops)
            loops.pop()
            for n in body_f:
                self._edge(n, hdr)
            out = {hdr}
            if stmt.orelse:
                out = self._seq(stmt.orelse, {hdr}, loops)
            return out | set(loop.breaks)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            hdr = self._enter(stmt, frontier)
            return self._seq(stmt.body, {hdr}, loops)
        if isinstance(stmt, ast.Try):
            before = len(self.nodes)
            body_f = self._seq(stmt.body, frontier, loops)
            body_ids = set(range(before, len(self.nodes)))
            out: set[int] = set()
            for h in stmt.handlers:
                h_hdr = self._new(h)          # the `except ...:` header
                for n in frontier | body_ids:
                    self._edge(n, h_hdr)
                out |= self._seq(h.body, {h_hdr}, loops)
            if stmt.orelse:
                out |= self._seq(stmt.orelse, body_f, loops)
            else:
                out |= body_f
            if stmt.finalbody:
                out = self._seq(stmt.finalbody, out, loops)
            return out
        # Simple statement (expr, assign, nested def/class header, ...).
        idx = self._enter(stmt, frontier)
        return {idx}

    # -- queries ---------------------------------------------------------

    def node_of(self, stmt: ast.stmt) -> int | None:
        return self._ids.get(id(stmt))

    def statements(self):
        """(index, statement) pairs — ExceptHandler headers included."""
        return enumerate(self.nodes)

    def dominators(self) -> dict[int, frozenset[int]]:
        """node -> the set of nodes on EVERY entry path to it (itself
        included).  Standard iterative dataflow; unreachable nodes get
        the empty set (nothing dominates what never runs)."""
        # Reachable set first.
        reach: set[int] = set()
        stack = [ENTRY]
        while stack:
            n = stack.pop()
            if n in reach:
                continue
            reach.add(n)
            stack.extend(self.succ.get(n, ()))
        every = frozenset(reach)
        dom: dict[int, frozenset[int]] = {
            n: (frozenset({ENTRY}) if n == ENTRY else every) for n in reach
        }
        changed = True
        while changed:
            changed = False
            for n in reach:
                if n == ENTRY:
                    continue
                preds = [p for p in self.pred.get(n, ()) if p in reach]
                new = frozenset({n}) | (
                    frozenset.intersection(*(dom[p] for p in preds))
                    if preds else frozenset()
                )
                if new != dom[n]:
                    dom[n] = new
                    changed = True
        for n in set(self.succ) - reach:
            dom[n] = frozenset()
        return dom

    def dominates(
        self, a: int, b: int, dom: dict[int, frozenset[int]] | None = None
    ) -> bool:
        dom = dom if dom is not None else self.dominators()
        return a in dom.get(b, frozenset())

    def exit_reachable_avoiding(self, avoid: set[int]) -> bool:
        """Can control flow from ENTRY to EXIT without executing any
        node in ``avoid``?  The fail-closed query: avoid = accounting
        statements; True means a path escapes unaccounted."""
        seen: set[int] = set()
        stack = [ENTRY]
        while stack:
            n = stack.pop()
            if n in seen or n in avoid:
                continue
            if n == EXIT:
                return True
            seen.add(n)
            stack.extend(self.succ.get(n, ()))
        return False


# ---------------------------------------------------------------------------
# layer 3: lexical lock context (extracted from rules_guards)
# ---------------------------------------------------------------------------


def walk_held(fn: ast.AST, resolve=None):
    """Yield (node, held, scope) for every node under method ``fn``.

    ``held`` is the frozenset of lock attribute names lexically held
    via ``with self.<attr>:`` at the node; ``resolve(attr)`` maps
    aliases (``Condition(self._lock)``) onto their lock.  ``scope`` is
    the method name, or "method.nested" inside nested defs — which
    (with lambdas) inherit NO lock context because they run later,
    possibly on another thread.  With-items acquire left to right: a
    later item's context expression already runs under the earlier
    items' locks (``with self._lock, self._reader():`` calls _reader
    WITH _lock held).  Nested classes are a different ``self`` and are
    skipped entirely."""
    resolve = resolve or (lambda attr: attr)
    name = getattr(fn, "name", "<body>")

    def walk(node: ast.AST, held: frozenset, scope: str):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            yield node, held, scope
            acquired: set[str] = set()
            for item in node.items:
                yield from walk(
                    item.context_expr, held | frozenset(acquired), scope
                )
                attr = self_attr(item.context_expr)
                if attr is not None:
                    acquired.add(resolve(attr))
            inner = held | frozenset(acquired)
            for child in node.body:
                yield from walk(child, inner, scope)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, held, scope
            nested = f"{name}.{node.name}"
            for child in ast.iter_child_nodes(node):
                yield from walk(child, frozenset(), nested)
            return
        if isinstance(node, ast.Lambda):
            yield node, held, scope
            yield from walk(node.body, frozenset(), f"{name}.<lambda>")
            return
        if isinstance(node, ast.ClassDef):
            return
        yield node, held, scope
        for child in ast.iter_child_nodes(node):
            yield from walk(child, held, scope)

    for child in fn.body:
        yield from walk(child, frozenset(), name)


def lock_attrs_of(cls: ast.ClassDef) -> tuple[dict[str, str], dict[str, str]]:
    """(lock attrs, aliases) declared by ``self.x = threading.Lock()``
    style assignments anywhere in the class: attr -> "Lock"/"RLock"/
    "Condition", and alias attr -> aliased lock attr for
    ``Condition(self._lock)``."""
    kinds = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}
    locks: dict[str, str] = {}
    alias: dict[str, str] = {}
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = self_attr(node.targets[0])
        if tgt is None or not isinstance(node.value, ast.Call):
            continue
        ctor = call_name(node.value)
        if ctor not in kinds:
            continue
        if ctor == "Condition" and node.value.args:
            src = self_attr(node.value.args[0])
            if src is not None:
                alias[tgt] = src
                continue
        locks[tgt] = kinds[ctor]
    return locks, alias


# ---------------------------------------------------------------------------
# layer 4: interprocedural call graph
# ---------------------------------------------------------------------------

_MAX_DEPTH = 8


@dataclasses.dataclass
class FlowFunc:
    key: str                        # "path::Class.meth" / "path::fn"
    qual: str
    path: str
    node: ast.AST
    cls_name: str | None
    # resolved intra-repo calls: (callee key, line), body order
    calls: list[tuple[str, int]] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _FlowClass:
    name: str
    path: str
    bases: list[str]
    node: ast.ClassDef
    attr_types: dict[str, str] = dataclasses.field(default_factory=dict)
    methods: dict[str, FlowFunc] = dataclasses.field(default_factory=dict)


class CallGraph:
    """Intra-repo call resolution + bounded reachability.

    The resolution discipline is lockgraph.py's, generalized: exact
    module for imported names (a simple-name suffix match would bind
    ``flush`` to whichever module sorts first), receiver types from
    constructor assignments / parameter annotations / ``self.x = param``
    through ``__init__`` annotations / one-level factory returns, and
    method lookup over all bases (BFS ≈ MRO, exact C3 only matters when
    two bases define the same method differently)."""

    def __init__(self, files: list[SourceFile], scope: str = "k8s1m_tpu/"):
        self.files = [f for f in files if f.path.startswith(scope)]
        self.classes: dict[str, _FlowClass] = {}
        self.funcs: dict[str, FlowFunc] = {}
        self.module_types: dict[tuple[str, str], str] = {}
        self.factories: dict[tuple[str, str], str] = {}
        # id(ast.Call) -> resolved callee key, for rules that walk the
        # same trees and need per-call-site resolution.
        self.call_targets: dict[int, str] = {}
        self._collect()
        self._summarize()

    # -- collection ------------------------------------------------------

    def _collect(self) -> None:
        for f in self.files:
            if not isinstance(f.tree, ast.Module):
                continue
            for node in f.tree.body:
                if isinstance(node, ast.ClassDef):
                    c = _FlowClass(
                        node.name, f.path,
                        [b.id if isinstance(b, ast.Name)
                         else getattr(b, "attr", "") for b in node.bases],
                        node,
                    )
                    self._scan_attrs(c)
                    self.classes.setdefault(node.name, c)
                elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt = node.targets[0]
                    if isinstance(tgt, ast.Name) and isinstance(
                        node.value, ast.Call
                    ):
                        ctor = call_name(node.value)
                        if ctor is not None:
                            self.module_types[(f.path, tgt.id)] = ctor
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Return) and isinstance(
                            sub.value, ast.Call
                        ):
                            ctor = call_name(sub.value)
                            if ctor is not None:
                                self.factories[(f.path, node.name)] = ctor
                                break

    def _scan_attrs(self, c: _FlowClass) -> None:
        for node in ast.walk(c.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt = self_attr(node.targets[0])
            if tgt is None:
                continue
            if isinstance(node.value, ast.Call):
                ctor = call_name(node.value)
                if ctor is not None:
                    c.attr_types.setdefault(tgt, ctor)
            elif isinstance(node.value, ast.Name):
                c.attr_types.setdefault(tgt, f"<param>{node.value.id}")

    @staticmethod
    def _ann_name(ann: ast.AST | None) -> str | None:
        if ann is None:
            return None
        if isinstance(ann, ast.Name):
            return ann.id
        if isinstance(ann, ast.Attribute):
            return ann.attr
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            return ann.value.strip().split("[")[0].split(".")[-1] or None
        if isinstance(ann, ast.Subscript):
            return CallGraph._ann_name(ann.value)
        return None

    def _imports_of(self, f: SourceFile) -> dict[str, tuple[str | None, str]]:
        out: dict[str, tuple[str | None, str]] = {}
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    out[alias.asname or alias.name] = (
                        node.module if not node.level else None,
                        alias.name,
                    )
        return out

    # -- resolution ------------------------------------------------------

    def _method_of(self, cls: _FlowClass | None, name: str) -> FlowFunc | None:
        queue = [cls] if cls is not None else []
        seen: set[str] = set()
        while queue:
            c = queue.pop(0)
            if c is None or c.name in seen:
                continue
            seen.add(c.name)
            fn = c.methods.get(name)
            if fn is not None:
                return fn
            queue.extend(
                self.classes.get(b) for b in c.bases
                if self.classes.get(b) is not None
            )
        return None

    def _resolve_param_attr(
        self, cls: _FlowClass, tname: str | None
    ) -> str | None:
        if tname is None or not tname.startswith("<param>"):
            return tname
        pname = tname[len("<param>"):]
        for sub in cls.node.body:
            if isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and sub.name == "__init__":
                for a in list(sub.args.args) + list(sub.args.kwonlyargs):
                    if a.arg == pname:
                        return self._ann_name(a.annotation)
        return None

    def _summarize(self) -> None:
        work: list[tuple[SourceFile, ast.AST, _FlowClass | None]] = []
        for f in self.files:
            if not isinstance(f.tree, ast.Module):
                continue
            for node in f.tree.body:
                if isinstance(node, ast.ClassDef):
                    c = self.classes.get(node.name)
                    if c is None or c.path != f.path:
                        continue
                    for sub in node.body:
                        if isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            fn = FlowFunc(
                                f"{f.path}::{c.name}.{sub.name}",
                                f"{c.name}.{sub.name}", f.path, sub, c.name,
                            )
                            c.methods[sub.name] = fn
                            self.funcs[fn.key] = fn
                            work.append((f, sub, c))
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = FlowFunc(
                        f"{f.path}::{node.name}", node.name, f.path, node,
                        None,
                    )
                    self.funcs[fn.key] = fn
                    work.append((f, node, None))
        imports_cache: dict[str, dict] = {}
        for f, node, c in work:
            if f.path not in imports_cache:
                imports_cache[f.path] = self._imports_of(f)
            self._summarize_func(f, node, c, imports_cache[f.path])

    def _summarize_func(
        self, f: SourceFile, fn, cls: _FlowClass | None, imports: dict
    ) -> None:
        out = self.funcs[
            f"{f.path}::{cls.name}.{fn.name}" if cls else f"{f.path}::{fn.name}"
        ]
        local_types: dict[str, str] = {}
        for a in list(fn.args.args) + list(fn.args.kwonlyargs):
            t = self._ann_name(a.annotation)
            if t is not None:
                local_types[a.arg] = t

        def type_of(expr: ast.AST) -> str | None:
            if isinstance(expr, ast.Name):
                t = local_types.get(expr.id)
                if t is not None:
                    return t
                return self.module_types.get((f.path, expr.id))
            attr = self_attr(expr)
            if attr is not None and cls is not None:
                return self._resolve_param_attr(cls, cls.attr_types.get(attr))
            if isinstance(expr, ast.Call):
                ctor = call_name(expr)
                if ctor in self.classes:
                    return ctor
                if ctor is not None:
                    return self.factories.get((f.path, ctor))
            return None

        def callee_key(call: ast.Call) -> str | None:
            fnexpr = call.func
            if isinstance(fnexpr, ast.Name):
                key = f"{f.path}::{fnexpr.id}"
                if key in self.funcs:
                    return key
                imported = imports.get(fnexpr.id)
                if imported is not None and imported[0] is not None:
                    mkey = (
                        f"{imported[0].replace('.', '/')}.py::{imported[1]}"
                    )
                    if mkey in self.funcs:
                        return mkey
                return None
            if isinstance(fnexpr, ast.Attribute):
                if (
                    isinstance(fnexpr.value, ast.Name)
                    and fnexpr.value.id == "self"
                    and cls is not None
                ):
                    m = self._method_of(cls, fnexpr.attr)
                    return m.key if m is not None else None
                t = self.classes.get(type_of(fnexpr.value) or "")
                if t is not None:
                    m = self._method_of(t, fnexpr.attr)
                    return m.key if m is not None else None
            return None

        for node in own_body(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    t = type_of(node.value)
                    if t is not None:
                        local_types[tgt.id] = t
            if isinstance(node, ast.Call):
                key = callee_key(node)
                if key is not None:
                    out.calls.append((key, node.lineno))
                    self.call_targets[id(node)] = key

    # -- queries ---------------------------------------------------------

    def target_of(self, call: ast.Call) -> str | None:
        """The resolved callee key of a call site, if any."""
        return self.call_targets.get(id(call))

    def find_reachable(
        self,
        key: str,
        pred,
        max_depth: int = _MAX_DEPTH,
        _stack: frozenset = frozenset(),
    ) -> tuple[tuple[str, ...], ast.AST] | None:
        """First node matching ``pred(node)`` in the own-body of ``key``
        or anything it transitively calls (bounded depth, cycle-safe).
        Returns (call-chain witness, matching node); the chain is empty
        for a direct hit."""
        if max_depth < 0 or key in _stack:
            return None
        fn = self.funcs.get(key)
        if fn is None:
            return None
        for node in own_body(fn.node):
            if pred(node):
                return (), node
        stack = _stack | {key}
        for callee, line in fn.calls:
            got = self.find_reachable(callee, pred, max_depth - 1, stack)
            if got is not None:
                chain, node = got
                step = f"{callee.split('::')[-1]} ({fn.path}:{line})"
                return (step,) + chain, node
        return None

    def returns_matching(
        self,
        key: str,
        expr_pred,
        max_depth: int = 4,
        _stack: frozenset = frozenset(),
        _memo: dict | None = None,
    ) -> bool:
        """Does ``key`` return a value derived from an expression
        matching ``expr_pred`` — directly, through local bindings
        (flow-insensitive fixpoint), or through a callee that itself
        returns-matching (bounded depth)?  The helper-propagation half
        of source→sink taint: ``x = helper()`` taints ``x`` when
        ``helper`` returns a tainted value."""
        memo = _memo if _memo is not None else {}
        if key in memo:
            return memo[key]
        if max_depth < 0 or key in _stack:
            return False
        fn = self.funcs.get(key)
        if fn is None:
            return False
        stack = _stack | {key}

        def contains(expr: ast.AST) -> bool:
            for sub in ast.walk(expr):
                if expr_pred(sub):
                    return True
                if isinstance(sub, ast.Call):
                    callee = self.call_targets.get(id(sub))
                    if callee is not None and self.returns_matching(
                        callee, expr_pred, max_depth - 1, stack, memo
                    ):
                        return True
            return False

        tainted = taint_fixpoint(
            collect_bindings(fn.node), contains_source=contains
        )
        result = False
        for node in own_body(fn.node):
            if isinstance(node, ast.Return) and node.value is not None:
                if expr_tainted(node.value, tainted, contains):
                    result = True
                    break
        memo[key] = result
        return result
