import sys

from k8s1m_tpu.lint.cli import main

sys.exit(main())
