"""bounded-watch-buffer: subscriber queues/rings in store/ carry a bound.

The watch tier's failure economics (ISSUE 15 watchplane) hinge on one
property: NOTHING between a store write and a client socket buffers
without limit.  The history window is a bounded ring, every subscriber
FIFO has a hard cap with coalesce-then-cancel semantics, the per-stream
output queues bound how far a wedged socket can backpressure, and the
wire clients cap their client-side buffers.  An unbounded queue added
anywhere in that chain silently re-opens the storm amplifier: a slow
consumer turns into unbounded tier memory instead of a counted
degradation.

This pass pins it statically: in ``k8s1m_tpu/store/``, every
construction of

- ``collections.deque(...)`` / ``deque(...)`` without a ``maxlen``
  (second positional or keyword), and
- ``asyncio.Queue(...)`` / ``queue.Queue(...)`` / bare ``Queue(...)``
  without a ``maxsize`` (first positional or keyword)

is a finding.  A bound of literal ``0``/``None`` (the stdlib spellings
of "unbounded") counts as missing.

Escape hatches (base.py): a ``# graftlint: disable=`` pragma carrying
the reason the buffer is bounded by construction elsewhere (e.g. a
ready-set whose producers latch, a caller-paced request queue), or a
baseline entry.
"""

from __future__ import annotations

import ast

from k8s1m_tpu.lint.base import Finding, Rule, SourceFile

_SCOPED_DIR = "k8s1m_tpu/store/"

_MSG = (
    "unbounded {what} construction in store/ — subscriber queues and "
    "event rings must carry an explicit bound ({kw}=), or a pragma "
    "explaining what bounds them by construction"
)


def _is_unbounded_literal(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value in (0, None)


def _call_name(node: ast.Call) -> str | None:
    """Dotted tail of the constructor: 'deque', 'Queue', etc."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


class BoundedWatchBuffer(Rule):
    id = "bounded-watch-buffer"

    def check_file(self, f: SourceFile) -> list[Finding]:
        if not f.path.startswith(_SCOPED_DIR):
            return []
        out: list[Finding] = []
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name == "deque":
                # deque(iterable, maxlen) — bound is the 2nd positional
                # or the maxlen kwarg.
                bound = None
                if len(node.args) >= 2:
                    bound = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "maxlen":
                        bound = kw.value
                if bound is None or _is_unbounded_literal(bound):
                    out.append(self.finding(
                        f, node, _MSG.format(what="deque", kw="maxlen")
                    ))
            elif name in ("Queue", "LifoQueue", "PriorityQueue",
                          "SimpleQueue"):
                # Queue(maxsize) — 1st positional or the maxsize kwarg
                # (SimpleQueue cannot be bounded at all).
                bound = None
                if name != "SimpleQueue":
                    if node.args:
                        bound = node.args[0]
                    for kw in node.keywords:
                        if kw.arg == "maxsize":
                            bound = kw.value
                if bound is None or _is_unbounded_literal(bound):
                    out.append(self.finding(
                        f, node, _MSG.format(what=name, kw="maxsize")
                    ))
        return out
