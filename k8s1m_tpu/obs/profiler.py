"""Sampling profiler: the continuous-profiling role (Parca / pprof).

The reference runs a Parca server + eBPF agent fleet-wide (reference
terraform/victoriametrics/main.tf:190-236, terraform/kubernetes/
parca-agent.tf) and wires pprof + contention profiles into the
scheduler's mux (cmd/dist-scheduler/scheduler_metrics.go:68-74), so
"where do the microseconds go" is always answerable.  This is the same
capability without external agents: a wall-clock sampler over
``sys._current_frames()`` that folds stacks into collapsed-stack
format (flamegraph-compatible) plus a self-time table, cheap enough to
leave on for a whole bench window.

Three entry points:

- ``SamplingProfiler`` — start/stop around a window (sched_bench
  --profile wires it); ``report()`` returns the aggregate, ``dump()``
  writes the artifact next to the flight-recorder dumps.
- ``install_signal_dump()`` — the py-spy-dump-on-demand equivalent:
  SIGUSR2 writes every thread's current stack to a file, for attaching
  to a live coordinator that stopped making progress.
- Coordinator integration: the flight recorder's slow-cycle dump can
  carry the profiler's report (coordinator.py wires ``profiler=``), so
  a >threshold cycle leaves both the event ring AND where the time went.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import sys
import threading
import time
import traceback

_EXCLUDE_THREADS = ("sampling-profiler",)


def _fold(frame) -> str:
    """Innermost-last collapsed stack for one thread's current frame."""
    parts: list[str] = []
    while frame is not None:
        code = frame.f_code
        name = os.path.basename(code.co_filename)
        parts.append(f"{code.co_name} ({name}:{frame.f_lineno})")
        frame = frame.f_back
    parts.reverse()
    return ";".join(parts)


class SamplingProfiler:
    """Aggregating wall-clock sampler over the interpreter's threads.

    ``target_thread_ids=None`` samples every thread except the sampler
    itself; pass a set of idents to focus (e.g. just the coordinator's
    driving thread).
    """

    def __init__(
        self,
        hz: float = 97.0,
        target_thread_ids: set[int] | None = None,
    ):
        # A prime-ish rate avoids beating against periodic work.
        self.interval = 1.0 / hz
        self.targets = target_thread_ids
        self.stacks: collections.Counter[str] = collections.Counter()
        self.samples = 0
        self.started_at = 0.0
        self.wall_s = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "SamplingProfiler":
        # Wall stamp is report metadata; durations below use perf_counter.
        self.started_at = time.time()  # graftlint: disable=no-wall-clock
        self._t0 = time.perf_counter()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="sampling-profiler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None
        self.wall_s = time.perf_counter() - self._t0

    def __enter__(self) -> "SamplingProfiler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        me = threading.get_ident()
        while not self._stop.wait(self.interval):
            # Re-resolve the excluded set EVERY tick (names are in
            # threading.enumerate(), a cheap list walk): a profiler
            # thread started after this one would otherwise be sampled
            # as workload — its wait/fold frames accruing a full-count
            # entry per tick — because a start-time snapshot can never
            # see it.
            skip_idents = {
                t.ident for t in threading.enumerate()
                if t.name.startswith(_EXCLUDE_THREADS)
            }
            frames = sys._current_frames()
            self.samples += 1
            for ident, frame in frames.items():
                if ident == me or ident in skip_idents:
                    continue
                if self.targets is not None and ident not in self.targets:
                    continue
                self.stacks[_fold(frame)] += 1

    # -- reporting ------------------------------------------------------

    def report(self, top: int = 25) -> dict:
        """Self-time and cumulative-time tables + collapsed stacks."""
        self_time: collections.Counter[str] = collections.Counter()
        cum_time: collections.Counter[str] = collections.Counter()
        for stack, n in self.stacks.items():
            frames = stack.split(";")
            self_time[frames[-1]] += n
            for f in set(frames):
                cum_time[f] += n
        total = sum(self.stacks.values()) or 1
        return {
            "samples": self.samples,
            "thread_samples": total,
            "wall_s": round(self.wall_s, 3),
            "started_at": self.started_at,
            "top_self": [
                {"frame": f, "pct": round(100.0 * n / total, 2), "n": n}
                for f, n in self_time.most_common(top)
            ],
            "top_cumulative": [
                {"frame": f, "pct": round(100.0 * n / total, 2), "n": n}
                for f, n in cum_time.most_common(top)
            ],
            "collapsed": dict(self.stacks.most_common()),
        }

    def dump(self, path: str | None = None, top: int = 25) -> str:
        """Write the report next to the flight-recorder dumps."""
        if path is None:
            # graftlint: disable=no-wall-clock (epoch-ms dump name, correlates across restarts)
            path = f"/tmp/profile-{int(time.time() * 1e3)}.json"
        with open(path, "w") as f:
            json.dump(self.report(top), f, indent=1)
        return path

    def format_top(self, top: int = 12) -> str:
        rep = self.report(top)
        lines = [
            f"profile: {rep['thread_samples']} samples over "
            f"{rep['wall_s']}s (self-time %)"
        ]
        for row in rep["top_self"][:top]:
            lines.append(f"  {row['pct']:6.2f}%  {row['frame']}")
        return "\n".join(lines)


def install_signal_dump(
    dump_dir: str = "/tmp", sig: int = signal.SIGUSR2
) -> None:
    """py-spy dump equivalent: SIGUSR2 writes every thread's stack.

    For a live process that stopped making progress — the on-demand half
    of the reference's pprof endpoint (scheduler_metrics.go:68-74).
    """

    def handler(signum, frame):
        path = os.path.join(
            # graftlint: disable=no-wall-clock (epoch dump name, correlates across restarts)
            dump_dir, f"stacks-{os.getpid()}-{int(time.time())}.txt"
        )
        names = {t.ident: t.name for t in threading.enumerate()}
        try:
            with open(path, "w") as f:
                for ident, fr in sys._current_frames().items():
                    f.write(f"--- thread {names.get(ident, '?')} ({ident})\n")
                    f.write("".join(traceback.format_stack(fr)))
                    f.write("\n")
        except OSError:
            pass

    signal.signal(sig, handler)
