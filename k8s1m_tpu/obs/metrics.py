"""Prometheus-style metrics, self-contained (no client library).

Every component of the reference exposes Prometheus metrics (reference
mem_etcd/src/metrics.rs:50-209, dist-scheduler
cmd/dist-scheduler/scheduler_metrics.go:78-190); this module is the
framework-wide equivalent: counters, gauges, histograms with labels,
rendered in the Prometheus text exposition format by ``Registry.render``
and served by ``k8s1m_tpu.obs.http.start_metrics_server``.

``AlertingHistogram`` reproduces the reference's ``AlertingHistogramTimer``
(mem_etcd/src/store.rs:883-907): any observation over the alert threshold
is logged immediately, so slow ops surface without a dashboard.
"""

from __future__ import annotations

import bisect
import logging
import threading
import time
from contextlib import contextmanager

log = logging.getLogger("k8s1m.metrics")

# Exponential latency buckets: 10us .. ~160s.
DEFAULT_BUCKETS = tuple(1e-5 * (2**i) for i in range(24))


def _label_str(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...] = (),
                 registry: "Registry | None" = None):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        (registry if registry is not None else REGISTRY).register(self)

    def _key(self, labels: dict[str, str]) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, want {self.labelnames}"
            )
        return tuple(str(labels[k]) for k in self.labelnames)

    def render(self) -> list[str]:
        raise NotImplementedError


class Counter(Metric):
    kind = "counter"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._values: dict[tuple, float] = {}

    def inc(self, n: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            for key, v in sorted(self._values.items()):
                lbl = _label_str(dict(zip(self.labelnames, key)))
                out.append(f"{self.name}{lbl} {v}")
        return out


class Gauge(Metric):
    kind = "gauge"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._values: dict[tuple, float] = {}
        self._callbacks: dict[tuple, object] = {}

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(v)

    def set_function(self, fn, **labels) -> None:
        """Gauge computed at scrape time (e.g. store.num_keys)."""
        with self._lock:
            self._callbacks[self._key(labels)] = fn

    def inc(self, n: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def dec(self, n: float = 1.0, **labels) -> None:
        self.inc(-n, **labels)

    def value(self, **labels) -> float:
        key = self._key(labels)
        if key in self._callbacks:
            return float(self._callbacks[key]())
        return self._values.get(key, 0.0)

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            items = dict(self._values)
            for key, fn in self._callbacks.items():
                try:
                    items[key] = float(fn())
                except Exception:  # graftlint: disable=broad-except (scrape must not die with the callback)
                    continue
        for key, v in sorted(items.items()):
            lbl = _label_str(dict(zip(self.labelnames, key)))
            out.append(f"{self.name}{lbl} {v}")
        return out


class CallbackMetric(Metric):
    """Metric whose whole sample set is computed at scrape time.

    ``fn`` returns ``[(labels_dict, value), ...]``; label sets may vary
    scrape to scrape (e.g. the store's lock cells only exist for methods
    that have run).  A failing callback yields no samples — a scrape must
    never die with its source."""

    def __init__(self, name: str, help: str, fn, kind: str = "gauge",
                 registry: "Registry | None" = None):
        super().__init__(name, help, (), registry)
        self._fn = fn
        self.kind = kind

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} {self.kind}"]
        try:
            samples = self._fn()
        # A failing callback yields no samples (class contract above).
        except Exception:  # graftlint: disable=broad-except
            return out
        for labels, v in samples:
            out.append(f"{self.name}{_label_str(dict(labels))} {v}")
        return out


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...] = (),
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                 registry: "Registry | None" = None):
        super().__init__(name, help, labelnames, registry)
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def observe(self, v: float, **labels) -> None:
        key = self._key(labels)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            if key not in self._counts:
                self._counts[key] = [0] * (len(self.buckets) + 1)
                self._sums[key] = 0.0
                self._totals[key] = 0
            self._counts[key][i] += 1
            self._sums[key] += v
            self._totals[key] += 1

    def reset(self) -> None:
        """Drop all recorded samples (benchmark windows only)."""
        with self._lock:
            self._counts.clear()
            self._sums.clear()
            self._totals.clear()

    def observe_many(self, values, **labels) -> None:
        """Batch observe: one bucket pass and one lock acquisition for a
        whole wave (the per-pod path is measurable at 10K+ binds/s)."""
        if len(values) == 0:
            return
        import numpy as _np

        v = _np.asarray(values, float)
        idx = _np.searchsorted(self.buckets, v, side="left")
        counts = _np.bincount(idx, minlength=len(self.buckets) + 1)
        key = self._key(labels)
        with self._lock:
            if key not in self._counts:
                self._counts[key] = [0] * (len(self.buckets) + 1)
                self._sums[key] = 0.0
                self._totals[key] = 0
            c = self._counts[key]
            for i, n in enumerate(counts):
                if n:
                    c[i] += int(n)
            self._sums[key] += float(v.sum())
            self._totals[key] += int(v.size)

    @contextmanager
    def time(self, **labels):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0, **labels)

    def sum(self, **labels) -> float:
        """Total of observed values for one label set (bench reporting)."""
        with self._lock:
            return self._sums.get(self._key(labels), 0.0)

    def label_keys(self) -> list[tuple]:
        with self._lock:
            return list(self._counts)

    def quantile(self, q: float, **labels) -> float:
        """Approximate quantile, linearly interpolated within the bucket
        (Prometheus histogram_quantile semantics) — edge-snapping made a
        whole latency curve report one flat number per bucket."""
        key = self._key(labels)
        with self._lock:
            counts = list(self._counts.get(key, []))
            total = self._totals.get(key, 0)
        if not total:
            return 0.0
        target = q * total
        seen = 0
        for i, c in enumerate(counts):
            if seen + c >= target:
                if i >= len(self.buckets):
                    return float("inf")
                hi = self.buckets[i]
                lo = self.buckets[i - 1] if i > 0 else 0.0
                # q=0 (or an empty leading bucket) must report the
                # bucket's LOWER edge, not snap to its upper bound.
                frac = (target - seen) / c if c else 0.0
                return lo + (hi - lo) * frac
            seen += c
        return float("inf")

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            for key in sorted(self._counts):
                base = dict(zip(self.labelnames, key))
                cum = 0
                for i, ub in enumerate(self.buckets):
                    cum += self._counts[key][i]
                    lbl = _label_str({**base, "le": repr(ub)})
                    out.append(f"{self.name}_bucket{lbl} {cum}")
                lbl = _label_str({**base, "le": "+Inf"})
                out.append(f"{self.name}_bucket{lbl} {self._totals[key]}")
                out.append(f"{self.name}_sum{_label_str(base)} {self._sums[key]}")
                out.append(f"{self.name}_count{_label_str(base)} {self._totals[key]}")
        return out


class AlertingHistogram(Histogram):
    """Histogram that logs any observation above ``alert_s`` immediately
    (reference AlertingHistogramTimer, mem_etcd/src/store.rs:883-907)."""

    def __init__(self, *args, alert_s: float = 0.1, **kwargs):
        super().__init__(*args, **kwargs)
        self.alert_s = alert_s

    def observe(self, v: float, **labels) -> None:
        super().observe(v, **labels)
        if v > self.alert_s:
            log.warning("%s%s took %.1fms", self.name, labels or "", v * 1e3)


class LevelTimer:
    """Time-weighted occupancy of small integer levels.

    Built for the scheduling pipeline's in-flight depth: the coordinator
    calls ``set_level(len(inflights))`` whenever the pipeline grows or
    shrinks, and ``seconds()`` reports how long each depth was held —
    the evidence behind "sustained in-flight depth" in the churn bench
    (a plain gauge only shows the instant of the scrape).  Not a Metric:
    it has no labels and renders nowhere; consumers (sched_bench) read
    it directly.
    """

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._level = 0
        # Start accumulating at level 0 immediately — deferring to the
        # first set_level would silently drop the initial interval.
        self._since: float = self._clock()
        self._seconds: dict[int, float] = {}

    def set_level(self, level: int) -> None:
        now = self._clock()
        self._seconds[self._level] = (
            self._seconds.get(self._level, 0.0) + now - self._since
        )
        self._level = int(level)
        self._since = now

    def seconds(self) -> dict[int, float]:
        """Seconds spent at each level so far (open interval included)."""
        out = dict(self._seconds)
        out[self._level] = (
            out.get(self._level, 0.0) + self._clock() - self._since
        )
        return out

    def share(self, level: int) -> float:
        """Fraction of observed time spent at exactly ``level``."""
        secs = self.seconds()
        total = sum(secs.values())
        return secs.get(int(level), 0.0) / total if total else 0.0

    def reset(self) -> None:
        """Drop history; the current level keeps accumulating from now
        (benchmark windows only)."""
        self._seconds.clear()
        self._since = self._clock()


def quantile_report_ms(
    hist: Histogram,
    quantiles: tuple[float, ...] = (0.5, 0.95, 0.99),
    **labels,
) -> dict:
    """``{"p50_ms": ..., "p95_ms": ...}`` for one histogram label set —
    the schedule-to-bind report shape every bench shares (sched_bench's
    paced and fill reports, shard_bench's status doc).  One helper so
    the rounding/naming never drifts between the call sites."""
    out = {}
    for q in quantiles:
        pct = f"{q * 100:g}".replace(".", "_")
        out[f"p{pct}_ms"] = round(hist.quantile(q, **labels) * 1e3, 2)
    return out


class Registry:
    def __init__(self):
        self._metrics: dict[str, Metric] = {}
        self._lock = threading.Lock()

    def register(self, m: Metric) -> None:
        with self._lock:
            if m.name in self._metrics:
                raise ValueError(f"duplicate metric {m.name}")
            self._metrics[m.name] = m

    def get(self, name: str) -> Metric | None:
        return self._metrics.get(name)

    def metrics(self) -> list[Metric]:
        with self._lock:
            return list(self._metrics.values())

    def render(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


REGISTRY = Registry()
