from k8s1m_tpu.obs.metrics import (  # noqa: F401
    REGISTRY,
    AlertingHistogram,
    Counter,
    Gauge,
    Histogram,
    Registry,
)
