"""/metrics HTTP endpoint (the reference serves one per component —
mem_etcd's axum server on --metrics-port, reference main.rs:83-101)."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from k8s1m_tpu.obs.metrics import REGISTRY


def start_metrics_server(
    port: int, host: str = "127.0.0.1", extra=None
) -> ThreadingHTTPServer:
    """Serve REGISTRY (plus an optional extra text producer) on /metrics.

    Runs in a daemon thread; returns the server (``.server_port`` for
    port=0 auto-assignment, ``.shutdown()`` to stop).
    """

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.rstrip("/") not in ("", "/metrics"):
                self.send_response(404)
                self.end_headers()
                return
            body = REGISTRY.render()
            if extra is not None:
                body += extra()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.end_headers()
            self.wfile.write(body.encode())

        def log_message(self, *args):  # quiet
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server
