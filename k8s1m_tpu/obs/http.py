"""/metrics HTTP endpoint (the reference serves one per component —
mem_etcd's axum server on --metrics-port, reference main.rs:83-101).

``ssl_context`` + ``basic_auth`` reproduce the reference's exposure
path: VM-level nginx reverse proxies terminate TLS and check basic-auth
before the scrape reaches the component (reference
terraform/k8s-server/server.tf:204-229).  Certs come from
cluster/certs.py, the same chain the webhook uses.
"""

from __future__ import annotations

import base64
import hmac
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from k8s1m_tpu.lint import guarded_by
from k8s1m_tpu.obs.metrics import REGISTRY


@guarded_by(scrapes="_lock", denied="_lock", not_found="_lock")
class ScrapeStats:
    """Per-server scrape counters, mutated by concurrent handler threads.

    ThreadingHTTPServer runs one thread per connection, so these counts
    are exactly the shared-state shape the lint/guards.py audit checks:
    every increment and read takes ``_lock`` (int += is not atomic under
    free-threading, and torn counts in the self-monitoring endpoint are
    the kind of lie that wastes an incident hour).  Exposed as
    ``server.scrape_stats`` for harnesses and tests.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.scrapes = 0
        self.denied = 0
        self.not_found = 0

    def note(self, outcome: str) -> None:
        with self._lock:
            if outcome == "ok":
                self.scrapes += 1
            elif outcome == "denied":
                self.denied += 1
            else:
                self.not_found += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "scrapes": self.scrapes,
                "denied": self.denied,
                "not_found": self.not_found,
            }


def start_metrics_server(
    port: int,
    host: str = "127.0.0.1",
    extra=None,
    ssl_context=None,
    basic_auth: tuple[str, str] | None = None,
    request_timeout_s: float = 30.0,
) -> ThreadingHTTPServer:
    """Serve REGISTRY (plus an optional extra text producer) on /metrics.

    Runs in a daemon thread; returns the server (``.server_port`` for
    port=0 auto-assignment, ``.shutdown()`` to stop).

    Every connection carries ``request_timeout_s`` as a socket timeout
    (both the plain and TLS paths): a scraper that connects and stalls
    must not pin a ThreadingHTTPServer thread forever — threads are the
    resource an overloaded host runs out of (see k8s1m_tpu/loadshed).
    """
    expected = None
    if basic_auth is not None:
        expected = "Basic " + base64.b64encode(
            f"{basic_auth[0]}:{basic_auth[1]}".encode()
        ).decode()
    stats = ScrapeStats()

    class Handler(BaseHTTPRequestHandler):
        # Applied to the connection by StreamRequestHandler.setup();
        # a read timing out drops the connection instead of hanging.
        timeout = request_timeout_s

        def do_GET(self):
            if expected is not None and not hmac.compare_digest(
                self.headers.get("Authorization", ""), expected
            ):
                stats.note("denied")
                self.send_response(401)
                self.send_header("WWW-Authenticate", "Basic realm=metrics")
                self.end_headers()
                return
            if self.path.rstrip("/") not in ("", "/metrics"):
                stats.note("not_found")
                self.send_response(404)
                self.end_headers()
                return
            stats.note("ok")
            body = REGISTRY.render()
            if extra is not None:
                body += extra()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.end_headers()
            self.wfile.write(body.encode())

        def log_message(self, *args):  # quiet
            pass

    if ssl_context is None:
        server = ThreadingHTTPServer((host, port), Handler)
    else:
        # Wrap per-connection, after accept, with the handshake deferred
        # into the handler thread — wrapping the *listening* socket runs
        # the handshake inside the serve_forever accept loop, so one
        # client stalling mid-handshake would block every later scrape.
        class TLSServer(ThreadingHTTPServer):
            def get_request(self):
                sock, addr = super().get_request()
                # Bound a stalled handshake (the handler's own timeout
                # only applies after setup(), i.e. post-handshake).
                sock.settimeout(min(10.0, request_timeout_s))
                return (
                    ssl_context.wrap_socket(
                        sock, server_side=True,
                        do_handshake_on_connect=False,
                    ),
                    addr,
                )

            def finish_request(self, request, client_address):
                request.do_handshake()  # in the per-connection thread
                super().finish_request(request, client_address)

            def handle_error(self, request, client_address):
                # Failed/stalled handshakes are the client's problem
                # (ssl.SSLError is an OSError subclass); anything else
                # is OUR bug and must not vanish.
                import sys

                if not isinstance(sys.exc_info()[1], OSError):
                    super().handle_error(request, client_address)

        server = TLSServer((host, port), Handler)
    server.daemon_threads = True
    server.scrape_stats = stats
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server
