"""Grafana dashboard generator — the grafana-dashboard/ equivalent.

The reference ships a hand-maintained 66-panel dashboard JSON with
dedicated Scheduler / etcd / apiserver / kwok rows
(reference grafana-dashboard/dashboard.json; panels like "Scheduling
attempt rate" and "kwok_node_lease_delay_percentile max").  Hand-written
dashboards drift as metrics change, so here the dashboard is *generated*
from the metric registry: every Counter becomes a rate panel, every
Gauge a timeseries, every Histogram a p50/p99 percentile panel, grouped
into rows by subsystem prefix.

    python -m k8s1m_tpu.obs.dashboard > dashboard.json

imports the subsystems first so their metrics register, then emits a
Grafana v10 schema dashboard for a Prometheus datasource scraping
obs.http.start_metrics_server / the store server's --metrics-port.
"""

from __future__ import annotations

import json

from k8s1m_tpu.obs.metrics import (
    CallbackMetric,
    Counter,
    Gauge,
    Histogram,
    REGISTRY,
)

# Row layout mirrors the reference dashboard's subsystem rows.  The
# graftlint metrics-registry pass checks this list BOTH ways: every
# prefix must match a declared metric (no silently empty rows) and
# every declared metric must land under some prefix (no unobservable
# evidence) — keep it in sync with the obs/metrics declarations.
ROWS = [
    ("Scheduler", ("coordinator_", "leader_", "webhook_", "shardset_")),
    # Quiesce-free pipelining evidence: quiesce reasons, in-flight depth,
    # and the host-stage overlap split (pipeline_* in control/coordinator).
    ("Scheduling cycle", ("pipeline_",)),
    # Per-pod lifecycle tracing (obs/podtrace.py): the schedule-to-bind
    # latency decomposed by stage, trace-bus accounting, and the
    # flight recorder's dump-budget outcomes (obs/trace.py).
    ("Latency attribution", ("pod_stage_", "podtrace_", "flight_")),
    # Cached + overlapped pod encoding (snapshot/hotfeed.py): encode
    # seconds by path, template-cache hit/miss, staged-batch use and the
    # stale-discard reasons.
    ("Host feed", ("hotfeed_",)),
    # The dp x sp sharded execution path (parallel/): mesh axis sizes,
    # sharded dirty-row scatters by column class, per-dp-shard feed depth.
    ("Mesh (dp x sp sharded cycle)", ("mesh_",)),
    # Incremental scheduling (engine/deltacache.py): delta vs full wave
    # split, per-pod shape hit/miss, plane fills and LRU evictions
    # (HBM-budget pressure), journaled dirty rows (mean dirty fraction),
    # and planes resident across live caches.
    ("Incremental scheduling (deltasched)", ("deltasched_",)),
    # The 1,048,576-row operating shape (ISSUE 14 megarow): cold-build
    # wall seconds (bootstrap relist -> bulk ingest -> device table),
    # bulk-ingest row rate (snapshot/bulkload + bulk_upsert), and the
    # host mirror's column-byte budget under the narrow-dtype rule.
    ("Million-row (megarow)", ("megarow_",)),
    # Packed device snapshot + buffer donation (snapshot/packing.py,
    # ISSUE 10 devicestate): table HBM bytes by layout, per-wave commit
    # donations split by whether the runtime honored them in place, and
    # fail-closed packed-layout rebuilds by overflow reason.
    ("Device memory", ("device_", "commit_donation_")),
    ("Overload control", ("loadshed_", "admission_", "breaker_",
                          "degraded_")),
    # Multi-tenant fairness (k8s1m_tpu/tenancy): per-class admitted
    # throughput and debt, preemption evictions, gang all-or-none
    # settlement outcomes.
    ("Multi-tenant fairness", ("tenant_", "preemption_", "gang_")),
    # Coordinator failover (control/leader.py): takeover counts and
    # recovery seconds by warm/cold mode, lease-epoch fence rejections
    # by write path, the standby mirror's watch lag, and reconcile
    # repairs at takeover.
    ("Failover", ("failover_", "fencing_", "standby_")),
    # Fault injection + the one shared RetryPolicy (k8s1m_tpu/faultline).
    ("Resilience (faultline)", ("faultline_", "retry_")),
    ("Store (mem-etcd)", ("memstore_",)),
    # The apiserver-tier fan-out under storm (ISSUE 15 watchplane):
    # upstream breaks split into diff-replay resumes vs cancel-everyone
    # invalidations, per-subscriber latest-only coalescing volume, and
    # the live count of lag-degraded watchers.
    ("Watch fanout (watchplane)", ("watchcache_",)),
    ("KWOK nodes", ("kwok_", "kubelet_")),
]

_PANEL_W = 8
_PANEL_H = 7


def _target(expr: str, legend: str = "") -> dict:
    return {"expr": expr, "legendFormat": legend or "{{instance}}"}


def _panel(pid: int, title: str, targets: list[dict], x: int, y: int) -> dict:
    return {
        "id": pid,
        "title": title,
        "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "gridPos": {"h": _PANEL_H, "w": _PANEL_W, "x": x, "y": y},
        "fieldConfig": {"defaults": {"unit": "short"}, "overrides": []},
        "targets": targets,
    }


def _panels_for(metric) -> list[tuple[str, list[dict]]]:
    name = metric.name
    labels = "by (%s) " % ", ".join(metric.labelnames) if metric.labelnames else ""
    if isinstance(metric, Counter):
        return [(
            f"{name} rate",
            [_target(f"sum {labels}(rate({name}[1m]))",
                     "-".join("{{%s}}" % l for l in metric.labelnames))],
        )]
    if isinstance(metric, Histogram):
        return [(
            f"{name} p50/p99",
            [
                _target(
                    f"histogram_quantile(0.5, sum by (le) (rate({name}_bucket[1m])))",
                    "p50",
                ),
                _target(
                    f"histogram_quantile(0.99, sum by (le) (rate({name}_bucket[1m])))",
                    "p99",
                ),
            ],
        )]
    if isinstance(metric, Gauge):
        return [(
            name,
            [_target(f"sum {labels}({name})",
                     "-".join("{{%s}}" % l for l in metric.labelnames))],
        )]
    if isinstance(metric, CallbackMetric):
        # Scrape-computed sample sets (e.g. the store's lock-contention
        # cells, labeled by method/structure/rw, reference
        # "mem_etcd_lock_count" panels).
        if metric.kind == "counter":
            return [(f"{name} rate", [_target(f"rate({name}[1m])")])]
        return [(name, [_target(name)])]
    return []


def build_dashboard(registry=None) -> dict:
    registry = registry or REGISTRY
    panels = []
    pid = 1
    y = 0
    for row_title, prefixes in ROWS:
        row_metrics = [
            m for m in registry.metrics()
            if any(m.name.startswith(p) for p in prefixes)
        ]
        if not row_metrics:
            continue
        panels.append({
            "id": pid, "type": "row", "title": row_title,
            "collapsed": False,
            "gridPos": {"h": 1, "w": 24, "x": 0, "y": y},
        })
        pid += 1
        y += 1
        x = 0
        for m in sorted(row_metrics, key=lambda m: m.name):
            for title, targets in _panels_for(m):
                panels.append(_panel(pid, title, targets, x, y))
                pid += 1
                x += _PANEL_W
                if x >= 24:
                    x = 0
                    y += _PANEL_H
        if x:
            y += _PANEL_H
    return {
        "title": "k8s1m-tpu",
        "uid": "k8s1m-tpu",
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {
            "list": [{
                "name": "datasource", "type": "datasource",
                "query": "prometheus",
            }]
        },
        "panels": panels,
    }


def main() -> None:
    # Import the subsystems for their metric registrations — the
    # dashboard covers whatever the code actually exports.
    import k8s1m_tpu.cluster.kwok_controller  # noqa: F401
    import k8s1m_tpu.control.coordinator  # noqa: F401
    import k8s1m_tpu.control.leader  # noqa: F401
    import k8s1m_tpu.control.webhook  # noqa: F401
    import k8s1m_tpu.loadshed  # noqa: F401
    import k8s1m_tpu.store.etcd_server  # noqa: F401
    import k8s1m_tpu.store.watch_cache  # noqa: F401
    import k8s1m_tpu.tenancy  # noqa: F401

    print(json.dumps(build_dashboard(), indent=1))


if __name__ == "__main__":
    main()
