"""Log aggregation for multi-process cluster runs — the fluent-bit →
VictoriaLogs role at rig scale (reference terraform/kubernetes/
fluentbit.tf: every pod's stderr shipped to one queryable place).

A cluster run spans many processes (store server, watch-cache tier,
KWOK controllers, shard coordinators, webhook); without collection,
diagnosing a failed 1M run means stitching N interleaved stderr streams
by eye.  LogShipper funnels every process's stderr/stdout into ONE
timestamped JSONL file:

    {"ts": 1735689600.123, "src": "store", "line": "..."}

Usage (the harness wires this automatically when ClusterSpec.log_dir is
set):

    ship = LogShipper(run_dir)
    proc = subprocess.Popen(cmd, stderr=ship.pipe("store"))
    ...
    ship.close()

Each pipe() returns a real file descriptor the child inherits; a reader
thread per source timestamps lines as they arrive, so ordering in the
file reflects arrival order across the whole cluster.  The parent's own
logging can join the stream via attach_logging().
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from k8s1m_tpu.lint import guarded_by


@guarded_by(
    # Reader threads emit concurrently with pipe()/close() callers; the
    # fd and reader bookkeeping used to mutate unlocked (a close racing
    # a late pipe() could leak the new fd or skip its join) — found by
    # the lint/guards.py audit, fixed by taking _lock everywhere and
    # refusing pipe() once close() has begun (_accepting).
    _f="_lock",
    _closed="_lock",
    _accepting="_lock",
    _readers="_lock",
    _write_fds="_lock",
)
class LogShipper:
    """Funnel many processes' output streams into one JSONL file."""

    def __init__(self, run_dir: str, name: str | None = None):
        os.makedirs(run_dir, exist_ok=True)
        ts = time.strftime("%Y%m%dT%H%M%S")
        self.path = os.path.join(run_dir, name or f"cluster-{ts}.jsonl")
        self._lock = threading.Lock()
        self._f = open(self.path, "a", buffering=1)
        self._readers: list[threading.Thread] = []
        self._write_fds: list[int] = []
        # _accepting gates pipe() the moment close() begins (a pipe
        # registered after close's snapshot would leak its fd and strand
        # its reader); _closed gates emit() only after the readers have
        # drained, so the tail lines still land in the file.
        self._accepting = True
        self._closed = False

    def emit(self, src: str, line: str) -> None:
        # graftlint: disable=no-wall-clock (cross-process log correlation needs epoch time)
        rec = {"ts": round(time.time(), 3), "src": src, "line": line}
        with self._lock:
            if not self._closed:
                self._f.write(json.dumps(rec) + "\n")

    def pipe(self, src: str) -> int:
        """A write fd to hand a child as stderr/stdout; lines arriving on
        it are shipped under ``src``.  The caller (subprocess.Popen)
        closes its copy after spawn; the reader thread exits on EOF when
        the LAST process holding the fd exits."""
        r, w = os.pipe()

        def read() -> None:
            with os.fdopen(r, "r", errors="replace") as f:
                for line in f:
                    self.emit(src, line.rstrip("\n"))

        t = threading.Thread(target=read, name=f"logship-{src}", daemon=True)
        # Register AND start under one lock acquisition: close() must
        # either see nothing (and this call raises) or see a started
        # reader plus its fd (and joins/closes both) — never a half-
        # registered pipe.
        with self._lock:
            if not self._accepting:
                os.close(r)
                os.close(w)
                raise RuntimeError("LogShipper is closed")
            self._write_fds.append(w)
            self._readers.append(t)
            t.start()
        return w

    def attach_logging(self, src: str = "harness",
                       logger: logging.Logger | None = None) -> logging.Handler:
        """Route the parent's own logging records into the stream."""
        ship = self

        class _H(logging.Handler):
            def emit(self, record: logging.LogRecord) -> None:
                try:
                    ship.emit(src, self.format(record))
                # A logging handler must never raise into its caller.
                except Exception:  # graftlint: disable=broad-except
                    pass

        h = _H()
        h.setFormatter(logging.Formatter("%(levelname)s %(name)s %(message)s"))
        (logger or logging.getLogger()).addHandler(h)
        return h

    def close(self, timeout: float = 5.0) -> None:
        """Close parent-side write fds (children should have exited) and
        drain the readers."""
        # Snapshot under the lock, join outside it: the readers need the
        # lock inside emit(), so holding it across join() would deadlock.
        with self._lock:
            self._accepting = False     # no pipes registered past here
            fds, self._write_fds = self._write_fds, []
            readers = list(self._readers)
        for w in fds:
            try:
                os.close(w)
            except OSError:
                pass
        for t in readers:
            t.join(timeout=timeout)
        with self._lock:
            self._closed = True
            self._f.close()
