"""Flight recorder: always-on ring of recent spans, dumped on slow ops.

The reference keeps golang.org/x/exp/trace.NewFlightRecorder running and
dumps /tmp/flight-<pod>-<ts>.perf whenever a pod takes >10ms to schedule
(reference cmd/dist-scheduler/scheduler.go:333,448,556-565).  This is the
same idea without the Go runtime: every span lands in a bounded ring; a
span over ``threshold_s`` dumps the ring as JSON so the events *leading
up to* the slow op are preserved.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time

from k8s1m_tpu.obs.metrics import Counter

log = logging.getLogger("k8s1m.trace")

_DUMPS = Counter(
    "flight_dumps_total",
    "Flight-recorder dump attempts by outcome (suppressed = the "
    "max_dumps budget is spent — later slow ops leave no artifact; "
    "error = the dump write itself failed)",
    ("outcome",),
)


class FlightRecorder:
    def __init__(
        self,
        threshold_s: float = 0.010,
        capacity: int = 4096,
        dump_dir: str = "/tmp",
        max_dumps: int = 16,
    ):
        self.threshold_s = threshold_s
        self.dump_dir = dump_dir
        self.max_dumps = max_dumps
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._dumps = 0
        self._suppression_logged = False

    def record(self, name: str, duration_s: float, **fields) -> None:
        # graftlint: disable=no-wall-clock (span wall stamp for cross-process correlation; dur_s is caller-measured monotonic)
        span = {"name": name, "t": time.time(), "dur_s": duration_s, **fields}
        with self._lock:
            self._ring.append(span)
        if duration_s > self.threshold_s:
            self.dump(reason=f"{name} took {duration_s * 1e3:.1f}ms")

    def span(self, name: str, **fields):
        return _Span(self, name, fields)

    def dump(self, reason: str = "", extra: dict | None = None) -> str | None:
        """Write the ring (+ optional ``extra`` payload — the slow pod's
        podtrace span chain) to a dump file.  Exhaustion of the
        ``max_dumps`` budget is not silent: it is counted in
        ``flight_dumps_total{outcome="suppressed"}`` and logged once."""
        suppressed = first = False
        with self._lock:
            if self._dumps >= self.max_dumps:
                suppressed = True
                first = not self._suppression_logged
                self._suppression_logged = True
            else:
                self._dumps += 1
                ring = list(self._ring)
                n = self._dumps
        if suppressed:
            if first:
                log.warning(
                    "flight recorder: max_dumps=%d budget spent; further "
                    "dumps suppressed (flight_dumps_total{outcome="
                    '"suppressed"} keeps counting)', self.max_dumps,
                )
            _DUMPS.inc(outcome="suppressed")
            return None
        path = os.path.join(
            # graftlint: disable=no-wall-clock (epoch-ms dump name, correlates across restarts)
            self.dump_dir, f"flight-{int(time.time() * 1e3)}-{n}.json"
        )
        doc = {"reason": reason, "spans": ring}
        if extra:
            doc.update(extra)
        try:
            with open(path, "w") as f:
                json.dump(doc, f)
        except OSError:
            _DUMPS.inc(outcome="error")
            return None
        _DUMPS.inc(outcome="written")
        log.warning("flight recorder dump: %s (%s)", path, reason)
        return path


class _Span:
    def __init__(self, rec: FlightRecorder, name: str, fields: dict):
        self.rec, self.name, self.fields = rec, name, fields

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.rec.record(self.name, time.perf_counter() - self._t0, **self.fields)
