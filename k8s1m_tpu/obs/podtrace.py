"""podtrace: end-to-end per-pod lifecycle tracing with stage attribution.

The north-star latency metric (`coordinator_schedule_to_bind_seconds`)
is one opaque histogram: nothing says how much of a pod's journey went
to admission, queue wait, gang staging, encode, pipeline depth, device
dispatch, or bind-CAS retries.  The reference answers "where did the
microseconds go" per pod — dist-scheduler dumps a flight trace for
every pod that takes >10ms to schedule (reference
cmd/dist-scheduler/scheduler.go:333,448,556-565).  This module is the
per-pod half of that answer:

- **PodTracer** — a lock-sharded, bounded, head-sampled (1-in-N pods,
  deterministic by pod-key hash: no RNG, no wall clock — durations are
  ``perf_counter`` intervals) trace bus.  A sampled pod's lifecycle is
  a CONTIGUOUS span chain: every ``emit`` opens its span at the
  previous span's end, so the chain telescopes to the pod's whole
  schedule-to-bind window and stage attribution sums to the end-to-end
  latency by construction (the ≥95% coverage gate in
  tests/test_podtrace.py guards dropped spans and missed anchors, the
  two ways attribution can silently go partial).
- **Stage histograms** — every span lands in
  ``pod_stage_seconds{stage}``, so the schedule-to-bind p50/p99
  decomposes into per-stage components on the dashboard's "Latency
  attribution" row.
- **Perfetto export** — ``export(path)`` writes Chrome trace-event
  JSON (load in ui.perfetto.dev / chrome://tracing): stages as tracks,
  pods as flow events arrowing each pod's journey across waves.
  ``validate_trace`` is the structural gate (monotone per-track
  timestamps, every flow event resolves) run in tier-1.
- **Attribution report** — ``attribution()`` returns the latency
  waterfall (per-stage p50/p99 + share of total + coverage), the
  ``latency_attribution`` detail of sched_bench/steady_drill and the
  committed ``artifacts/podtrace_attribution.json``.

Tracing off must be FREE: ``NULL_TRACER`` (the null-tracer pattern) is
what a coordinator holds by default — a single ``.enabled`` attribute
read per site.  The graftlint pass ``trace-lazy-emit``
(lint/rules_trace.py) statically enforces that span/attr construction
in engine/snapshot/control hot paths sits behind that guard.

Attribution contract for NEW lifecycle stages (MIGRATION.md
"Per-pod tracing"): a stage is a contiguous interval — ``emit`` anchors
its start at the previous span's end, so never pre-compute a span start
yourself; emit behind the ``enabled`` guard; and add the stage name to
``STAGES`` so the exporter gives it a stable track and the dashboard a
bounded label set.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import threading
import time
import zlib

import numpy as np

from k8s1m_tpu.obs.metrics import Counter, Histogram

# Track order in the Perfetto export; also the bounded label set of
# pod_stage_seconds.  Keep in sync with the coordinator's emit sites
# (the attribution contract above).
STAGES = (
    "admit",          # webhook/submit_external admission decision + staging
    "gang_stage",     # all-or-none gang assembly wait (tenancy/gang.py)
    "queue_wait",     # pending-queue (+ retry backoff) residence
    "encode",         # host encode (hotfeed claim or inline; cache attrs)
    "dispatch_wait",  # encode end -> device dispatch (pipeline slot wait)
    "device",         # dispatch -> result sync (wave epoch/depth/path attrs)
    "bind",           # bind CAS + wave settlement (outcome attr)
    "requeue",        # terminal non-bind settlement (unschedulable, deleted)
)

_STAGE_SECONDS = Histogram(
    "pod_stage_seconds",
    "Per-pod lifecycle stage seconds for traced pods (obs/podtrace.py; "
    "the schedule-to-bind histogram decomposed by stage)",
    ("stage",),
)
_PODS = Counter(
    "podtrace_pods_total",
    "Traced pods by outcome: sampled = trace opened, finished = span "
    "chain closed at a terminal stage, dropped = head-sample hit the "
    "live-trace bound (raise max_live or sample wider)",
    ("outcome",),
)


@dataclasses.dataclass
class PodTrace:
    """One pod's contiguous span chain: ``spans`` is a list of
    ``(stage, t0, t1, attrs)`` with ``spans[i+1].t0 == spans[i].t1``."""

    key: str
    t0: float
    attrs: dict
    last_t: float = 0.0
    spans: list = dataclasses.field(default_factory=list)

    def doc(self) -> dict:
        """JSON-ready form (flight-recorder dumps, debugging)."""
        return {
            "pod": self.key,
            "total_s": round(self.last_t - self.t0, 6),
            **self.attrs,
            "spans": [
                {"stage": s, "dur_s": round(t1 - t0, 6), **a}
                for s, t0, t1, a in self.spans
            ],
        }


class PodTracer:
    """Lock-sharded, bounded, head-sampled per-pod trace bus.

    ``sample_n`` traces 1-in-N pods, chosen deterministically by pod-key
    hash (two runs over the same population trace the same pods — the
    faultline determinism contract extended to observability).
    ``max_live`` bounds in-flight trace memory; ``ring`` bounds the
    completed-trace history the exporter/attribution read.
    """

    enabled = True

    def __init__(
        self,
        sample_n: int = 16,
        *,
        max_live: int = 4096,
        ring: int = 8192,
        shards: int = 8,
    ):
        if sample_n < 1:
            raise ValueError(f"sample_n must be >= 1, got {sample_n}")
        self.sample_n = sample_n
        self.max_live = max_live
        # Power-of-two shard count so the shard pick is a mask.
        n = 1
        while n < shards:
            n <<= 1
        self._mask = n - 1
        self._shards: list[dict[str, PodTrace]] = [{} for _ in range(n)]
        self._locks = [threading.Lock() for _ in range(n)]
        self._done: collections.deque[PodTrace] = collections.deque(
            maxlen=ring
        )
        self._done_lock = threading.Lock()

    # ---- sampling ------------------------------------------------------

    def sampled(self, key: str) -> bool:
        """Deterministic head-sample decision for a pod key."""
        if self.sample_n <= 1:
            return True
        return zlib.crc32(key.encode()) % self.sample_n == 0

    def _shard(self, key: str) -> int:
        return zlib.crc32(key.encode()[::-1]) & self._mask

    # ---- the span chain ------------------------------------------------

    def begin(self, key: str, t: float, **attrs) -> bool:
        """Open a trace anchored at ``t`` (the intake timestamp).  A
        no-op for unsampled keys and for keys already live (webhook
        intake begins before the watch echo re-begins); False either
        way, True when a fresh trace opened."""
        if not self.sampled(key):
            return False
        i = self._shard(key)
        with self._locks[i]:
            shard = self._shards[i]
            if key in shard:
                return False
            if sum(len(s) for s in self._shards) >= self.max_live:
                _PODS.inc(outcome="dropped")
                return False
            shard[key] = PodTrace(key, t, attrs, last_t=t)
        _PODS.inc(outcome="sampled")
        return True

    def emit(self, key: str, stage: str, t: float | None = None,
             **attrs) -> bool:
        """Close the span ``[last_t, t]`` under ``stage``.  ``t=None``
        reads ``perf_counter`` now.  No-op (False) for keys without a
        live trace — unsampled pods early-out on one hash, before any
        lock, so tracing-on overhead scales with the SAMPLED count,
        not the batch size."""
        if not self.sampled(key):
            return False
        if t is None:
            t = time.perf_counter()
        i = self._shard(key)
        with self._locks[i]:
            tr = self._shards[i].get(key)
            if tr is None:
                return False
            t = max(t, tr.last_t)     # monotone chain, clock never rewinds
            tr.spans.append((stage, tr.last_t, t, attrs))
            dur = t - tr.last_t
            tr.last_t = t
        _STAGE_SECONDS.observe(dur, stage=stage)
        return True

    def finish(self, key: str, stage: str, t: float | None = None,
               **attrs) -> PodTrace | None:
        """Terminal ``emit``: close the chain and move the trace to the
        completed ring.  Returns the completed trace (the flight
        recorder attaches its span chain to slow-pod dumps)."""
        if not self.emit(key, stage, t, **attrs):
            return None
        i = self._shard(key)
        with self._locks[i]:
            tr = self._shards[i].pop(key, None)
        if tr is None:
            return None
        with self._done_lock:
            self._done.append(tr)
        _PODS.inc(outcome="finished")
        return tr

    # ---- reads ---------------------------------------------------------

    def spans_of(self, key: str) -> list[dict]:
        """The live span chain for a pod (flight-recorder dumps); []
        when the pod is not being traced."""
        i = self._shard(key)
        with self._locks[i]:
            tr = self._shards[i].get(key)
            if tr is None:
                return []
            spans = list(tr.spans)
        return [
            {"stage": s, "dur_s": round(t1 - t0, 6), **a}
            for s, t0, t1, a in spans
        ]

    def live_count(self) -> int:
        return sum(len(s) for s in self._shards)

    def completed(self) -> list[PodTrace]:
        with self._done_lock:
            return list(self._done)

    # ---- consumers -----------------------------------------------------

    def attribution(self) -> dict:
        """The latency waterfall over completed traces: per-stage
        p50/p99 + share of total, end-to-end p50/p99, and coverage
        (sum of stage spans vs end-to-end — the ≥0.95 acceptance gate;
        1.0 by construction unless spans were dropped or anchors
        missed)."""
        traces = self.completed()
        if not traces:
            return {"pods": 0, "stages": {}, "end_to_end": None,
                    "coverage": None}
        by_stage: dict[str, list[float]] = {}
        totals: list[float] = []
        covered: list[float] = []
        for tr in traces:
            total = tr.last_t - tr.t0
            totals.append(total)
            covered.append(sum(t1 - t0 for _, t0, t1, _ in tr.spans))
            for s, t0, t1, _ in tr.spans:
                by_stage.setdefault(s, []).append(t1 - t0)
        grand = sum(totals) or 1.0
        stages = {}
        order = {s: i for i, s in enumerate(STAGES)}
        for s in sorted(by_stage, key=lambda s: order.get(s, len(order))):
            d = np.asarray(by_stage[s])
            stages[s] = {
                "p50_ms": round(float(np.percentile(d, 50)) * 1e3, 3),
                "p99_ms": round(float(np.percentile(d, 99)) * 1e3, 3),
                "seconds": round(float(d.sum()), 4),
                "share": round(float(d.sum()) / grand, 4),
                "spans": int(d.size),
            }
        e2e = np.asarray(totals)
        return {
            "pods": len(traces),
            "sample_n": self.sample_n,
            "stages": stages,
            "end_to_end": {
                "p50_ms": round(float(np.percentile(e2e, 50)) * 1e3, 3),
                "p99_ms": round(float(np.percentile(e2e, 99)) * 1e3, 3),
            },
            "coverage": round(sum(covered) / grand, 4),
        }

    def to_trace_events(self) -> dict:
        """Chrome trace-event JSON (the Perfetto/chrome://tracing
        format): each stage is a track (tid), each span a complete "X"
        event, and each pod's journey a flow (s/t/f arrows binding its
        spans across tracks and waves)."""
        traces = self.completed()
        tids = {s: i + 1 for i, s in enumerate(STAGES)}
        events: list[dict] = [{
            "ph": "M", "pid": 1, "tid": 0, "ts": 0,
            "name": "process_name", "args": {"name": "k8s1m coordinator"},
        }]
        epoch = min((tr.t0 for tr in traces), default=0.0)

        def us(t: float) -> int:
            return int(round((t - epoch) * 1e6))

        flow_id = 0
        for tr in traces:
            flow_id += 1
            n = len(tr.spans)
            for j, (stage, t0, t1, attrs) in enumerate(tr.spans):
                tid = tids.setdefault(stage, len(tids) + 1)
                events.append({
                    "ph": "X", "pid": 1, "tid": tid, "name": stage,
                    "cat": "pod", "ts": us(t0), "dur": max(0, us(t1) - us(t0)),
                    "args": {"pod": tr.key, **attrs},
                })
                if n < 2:
                    continue
                # Flow arrows: s at the first span's end, t at each
                # middle span's start, f at the last span's start.
                if j == 0:
                    events.append({
                        "ph": "s", "pid": 1, "tid": tid, "name": "pod",
                        "cat": "flow", "id": flow_id, "ts": us(t1),
                    })
                elif j == n - 1:
                    events.append({
                        "ph": "f", "bp": "e", "pid": 1, "tid": tid,
                        "name": "pod", "cat": "flow", "id": flow_id,
                        "ts": us(t0),
                    })
                else:
                    events.append({
                        "ph": "t", "pid": 1, "tid": tid, "name": "pod",
                        "cat": "flow", "id": flow_id, "ts": us(t0),
                    })
        for stage, tid in tids.items():
            events.append({
                "ph": "M", "pid": 1, "tid": tid, "ts": 0,
                "name": "thread_name", "args": {"name": stage},
            })
        # Monotone per-track order: one stable global sort by timestamp
        # (metadata first; a flow start sorts before the step/finish it
        # feeds at equal timestamps).
        ph_rank = {"M": -1, "X": 0, "s": 1, "t": 2, "f": 3}
        events.sort(key=lambda e: (e["ts"], ph_rank.get(e["ph"], 4)))
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export(self, path: str) -> dict:
        """Write the trace-event export (parent directory created —
        an end-of-run export must not lose the whole run's report to a
        missing output dir)."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        doc = self.to_trace_events()
        with open(path, "w") as f:
            json.dump(doc, f)
        return doc


def trace_report_detail(tracer, trace_out: str | None = None) -> dict:
    """The shared ``latency_attribution`` report block for tools
    (sched_bench, steady_drill): the waterfall, plus the Perfetto
    export when ``trace_out`` is given.  {} when tracing is off."""
    if tracer is None:
        return {}
    out = {"latency_attribution": tracer.attribution()}
    if trace_out:
        tracer.export(trace_out)
        out["trace_out"] = trace_out
    return out


class _NullTracer:
    """Tracing off: the coordinator's default collaborator.  Every
    surface exists and no-ops; hot paths check ``enabled`` once and
    skip span/attr construction entirely (the trace-lazy-emit lint
    contract)."""

    enabled = False
    sample_n = 0

    def sampled(self, key: str) -> bool:
        return False

    def begin(self, key: str, t: float, **attrs) -> bool:
        return False

    def emit(self, key: str, stage: str, t=None, **attrs) -> bool:
        return False

    def finish(self, key: str, stage: str, t=None, **attrs):
        return None

    def spans_of(self, key: str) -> list:
        return []

    def completed(self) -> list:
        return []

    def attribution(self) -> dict:
        return {}


NULL_TRACER = _NullTracer()


def validate_trace(doc) -> list[str]:
    """Structural validation of a trace-event export (the tier-1 gate):
    well-formed events, monotone per-track timestamps, and every flow
    step/finish resolving to an earlier flow start whose chain also
    terminates.  Returns problems; [] means valid."""
    errs: list[str] = []
    evs = doc.get("traceEvents") if isinstance(doc, dict) else None
    if not isinstance(evs, list):
        return ["traceEvents is not a list"]
    last_ts: dict[tuple, int] = {}
    started: set = set()
    finished: set = set()
    for i, e in enumerate(evs):
        ph = e.get("ph")
        if ph not in ("M", "X", "s", "t", "f"):
            errs.append(f"event {i}: unknown ph {ph!r}")
            continue
        ts = e.get("ts")
        if not isinstance(ts, int) or ts < 0:
            errs.append(f"event {i}: bad ts {ts!r}")
            continue
        if ph == "M":
            continue
        track = (e.get("pid"), e.get("tid"))
        if ph == "X":
            if not e.get("name"):
                errs.append(f"event {i}: X event without a name")
            dur = e.get("dur")
            if not isinstance(dur, int) or dur < 0:
                errs.append(f"event {i}: bad dur {dur!r}")
            if ts < last_ts.get(track, 0):
                errs.append(
                    f"event {i}: track {track} timestamps not monotone "
                    f"({ts} after {last_ts[track]})"
                )
            last_ts[track] = max(last_ts.get(track, 0), ts)
            continue
        fid = e.get("id")
        if fid is None:
            errs.append(f"event {i}: flow event without an id")
            continue
        if ph == "s":
            started.add(fid)
        elif fid not in started:
            errs.append(f"event {i}: flow {ph!r} id {fid} before its 's'")
        if ph == "f":
            finished.add(fid)
    for fid in sorted(started - finished, key=str):
        errs.append(f"flow id {fid} started but never finished")
    return errs
