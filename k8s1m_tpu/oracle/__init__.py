from k8s1m_tpu.oracle.reference_scheduler import oracle_feasible, oracle_score

__all__ = ["oracle_feasible", "oracle_score"]
