"""Pure-Python reference scheduler: the differential-correctness oracle.

Evaluates filter and score semantics directly on host objects (NodeInfo /
PodInfo, strings and dicts) with none of the interning, encoding, or
tensor machinery — an independent implementation of the same upstream
plugin semantics the kernels implement.  The differential harness feeds
identical snapshots to both and compares bit-for-bit (masks) and
value-for-value (integer scores).

This is the test the reference never had: its correctness story for the
scheduling path was "trust the upstream fork" (reference RUNNING.adoc:207
admits the code is messy and not well-tested).  SURVEY.md §7 calls this
harness non-negotiable.

Arithmetic note: score formulas are computed in float32 like the kernels,
so floor() boundaries agree; the *semantics* (what matches, what counts)
share no code with the device path except semantics.py, which is the
single definition of toleration matching by design.
"""

from __future__ import annotations

import numpy as np

from k8s1m_tpu.config import (
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    EFFECT_PREFER_NO_SCHEDULE,
    SEL_OP_DOES_NOT_EXIST,
    SEL_OP_EXISTS,
    SEL_OP_GT,
    SEL_OP_IN,
    SEL_OP_LT,
    SEL_OP_NOT_IN,
)
from k8s1m_tpu.semantics import pod_tolerates_taint
from k8s1m_tpu.snapshot.node_table import (
    HOSTNAME_LABEL,
    UNSCHEDULABLE_TAINT_KEY,
    NodeInfo,
    Taint,
)
from k8s1m_tpu.snapshot.pod_encoding import PodInfo


def _effective_labels(node: NodeInfo) -> dict[str, str]:
    labels = dict(node.labels)
    labels.setdefault(HOSTNAME_LABEL, node.name)
    return labels


def _effective_taints(node: NodeInfo) -> list[Taint]:
    taints = list(node.taints)
    if node.unschedulable:
        taints.append(Taint(UNSCHEDULABLE_TAINT_KEY, "", EFFECT_NO_SCHEDULE))
    return taints


def _match_expr(labels: dict[str, str], req) -> bool:
    present = req.key in labels
    val = labels.get(req.key)
    if req.op == SEL_OP_IN:
        return present and val in req.values
    if req.op == SEL_OP_NOT_IN:
        return not (present and val in req.values)
    if req.op == SEL_OP_EXISTS:
        return present
    if req.op == SEL_OP_DOES_NOT_EXIST:
        return not present
    if req.op in (SEL_OP_GT, SEL_OP_LT):
        if not present or not req.values:
            return False
        try:
            node_num = int(val, 10)
            operand = int(req.values[0], 10)
        except (ValueError, TypeError):
            return False
        return node_num > operand if req.op == SEL_OP_GT else node_num < operand
    return False


def _match_term(labels: dict[str, str], term) -> bool:
    if not term.match_expressions:
        return False  # upstream: an empty term matches nothing
    return all(_match_expr(labels, e) for e in term.match_expressions)


def oracle_feasible(
    node: NodeInfo,
    pod: PodInfo,
    requested: tuple[int, int, int] = (0, 0, 0),
) -> bool:
    """All filter plugins, host-side. requested = (cpu, mem, pods) in use."""
    rc, rm, rp = requested
    if pod.cpu_milli > node.cpu_milli - rc:
        return False
    if pod.mem_kib > node.mem_kib - rm:
        return False
    if node.pods - rp < 1:
        return False
    if pod.node_name is not None and pod.node_name != node.name:
        return False
    for taint in _effective_taints(node):
        if taint.effect in (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE):
            if not pod_tolerates_taint(pod.tolerations, taint):
                return False
    labels = _effective_labels(node)
    for k, v in pod.node_selector.items():
        if labels.get(k) != v:
            return False
    if pod.required_terms:
        if not any(_match_term(labels, t) for t in pod.required_terms):
            return False
    return True


def oracle_score(
    node: NodeInfo,
    pod: PodInfo,
    requested: tuple[int, int, int] = (0, 0, 0),
    *,
    taint_slots: int = 8,
    weights=(1, 1, 3, 2),
) -> int:
    """Weighted integer score; weights = (least_allocated,
    balanced_allocation, taint_toleration, node_affinity)."""
    f32 = np.float32
    rc, rm, _ = requested
    w_la, w_ba, w_tt, w_na = weights

    cpu_after = f32(rc + pod.cpu_milli)
    mem_after = f32(rm + pod.mem_kib)
    alloc_cpu = f32(max(node.cpu_milli, 1))
    alloc_mem = f32(max(node.mem_kib, 1))

    la = f32(50.0) * (
        np.clip((alloc_cpu - cpu_after) / alloc_cpu, f32(0), None)
        + np.clip((alloc_mem - mem_after) / alloc_mem, f32(0), None)
    )

    f_cpu = np.clip(cpu_after / alloc_cpu, f32(0), f32(1))
    f_mem = np.clip(mem_after / alloc_mem, f32(0), f32(1))
    ba = f32(100.0) * (f32(1.0) - np.abs(f_cpu - f_mem) / f32(2.0))

    soft_untol = sum(
        1
        for t in _effective_taints(node)
        if t.effect == EFFECT_PREFER_NO_SCHEDULE
        and not pod_tolerates_taint(pod.tolerations, t)
    )
    tt = f32(100.0) * (f32(1.0) - f32(soft_untol) / f32(taint_slots))

    labels = _effective_labels(node)
    total_w = max(sum(p.weight for p in pod.preferred_terms), 1)
    matched_w = sum(
        p.weight for p in pod.preferred_terms if _match_term(labels, p.term)
    )
    na = f32(100.0) * f32(matched_w) / f32(total_w)

    return (
        int(np.floor(la)) * w_la
        + int(np.floor(ba)) * w_ba
        + int(np.floor(tt)) * w_tt
        + int(np.floor(na)) * w_na
    )
