"""Scheduler shard set: N coordinators splitting the pod stream and the
node space, with leader-driven rebalancing.

The reference scales host-side by running up to 256 dist-scheduler
replicas: pods are routed to a replica by an FNV-32 hash of ``ns/name``
(reference pkg/schedulerset/schedulerset.go:130-143) and the elected
leader continuously rebalances ``dist-scheduler.dev/scheduler`` node
labels so every replica owns an even slice of the node space, minimizing
moves and patching nodes 1,000 at a time (reference
cmd/dist-scheduler/leader_activities.go:227-343).

The TPU re-expression keeps both partitions but changes their mechanics:

- **Pod intake partition** — each shard's coordinator installs an
  ``intake_filter`` so only pods with ``fnv32(ns/name) % num_shards ==
  shard_idx`` enter its queue.  Other shards' pods are still observed
  (their binds feed external accounting, so constraint counts and node
  usage stay globally correct in every shard).
- **Node-space partition as a mask, not a partition of memory** — every
  shard holds the FULL node table on its device; ownership is a bool[N]
  ``row_mask`` ANDed into candidate selection (engine mask_rows).  Nodes
  hash into ``NUM_GROUPS`` stable groups and the shared store holds one
  small group->shard assignment object; "moving a node" is a CAS on that
  object followed by every member flipping mask bits — no 1,000-way node
  patch storm, no table data movement, no recompile (the mask is traced).
- **Rebalancer** — the leader (control/leader.py election) recomputes the
  assignment from live group populations and member heartbeats: groups on
  dead shards are reassigned first, then groups move from the most- to
  the least-loaded shard while the imbalance shrinks, capped per round
  (move minimization + batching, with a minimum interval between rounds
  like the reference's 30 s).

Under a stable assignment the masks are disjoint, so two shards never
pick the same node for conflicting pods.  Across a rebalance the handoff
is drop-before-claim: a member applies lost groups to its mask the tick
it observes the new version, but defers *gained* groups by one tick — by
then the donor (draining the same watch on its own tick cadence) has
dropped them, so the dual-ownership window collapses to donor-lag, the
same exposure the reference has between a node-label patch and the other
replica's informer observing it (leader_activities.go's merge-patches vs
informer caches).  The CAS bind path still guards pod-object races
either way.  A pod whose feasible nodes all live in another shard's
slice retries and reports unschedulable exactly as in the reference's
design (a replica only sees its own label slice, README.adoc:525-531).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time

import numpy as np

from k8s1m_tpu import faultline
from k8s1m_tpu.control.coordinator import Coordinator
from k8s1m_tpu.faultline import GiveUp, policy_for
from k8s1m_tpu.lint import THREAD_OWNER, guarded_by
from k8s1m_tpu.obs.metrics import Counter, Gauge
from k8s1m_tpu.store.native import drain_events_light, prefix_end

log = logging.getLogger("k8s1m.shardset")

# Node groups: the unit of ownership transfer.  256 matches the
# reference's replica ceiling (256 shards, README.adoc:730) while keeping
# the assignment object a few KB.
NUM_GROUPS = 256

ASSIGN_KEY = b"/registry/k8s1m/scheduler-set/assignment"
STATUS_PREFIX = b"/registry/k8s1m/scheduler-set/status/"

_REBALANCES = Counter(
    "shardset_rebalances_total", "Assignment rewrites by the leader", ()
)
_GROUP_MOVES = Counter(
    "shardset_group_moves_total", "Node groups moved between shards", ()
)
_MASK_REFRESH = Counter(
    "shardset_mask_refreshes_total", "Ownership mask rebuilds", ("shard",)
)
_OWNED_NODES = Gauge(
    "shardset_owned_nodes", "Nodes owned by this shard", ("shard",)
)


def fnv32(s: str) -> int:
    """FNV-1a 32-bit — the reference's pod->shard hash
    (schedulerset.go:130-143 uses FNV over ``ns/name``)."""
    h = 0x811C9DC5
    for b in s.encode():
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


def pod_shard(pod_key: str, num_shards: int) -> int:
    """Shard index for a pod ``ns/name`` key."""
    return fnv32(pod_key) % num_shards


def group_of(node_name: str) -> int:
    """Stable node->group hash (process-independent)."""
    # Salted so a node name and a same-named pod key don't correlate.
    return fnv32("g:" + node_name) % NUM_GROUPS


@dataclasses.dataclass
class Assignment:
    """The group->shard map, one small CAS-guarded store object."""

    version: int
    num_shards: int
    groups: list[int]               # len NUM_GROUPS, values in [0, num_shards)
    mod_revision: int = 0           # store CAS handle (0 = not persisted)

    def encode(self) -> bytes:
        return json.dumps(
            {
                "version": self.version,
                "numShards": self.num_shards,
                "groups": self.groups,
            }
        ).encode()

    @classmethod
    def decode(cls, data: bytes, mod_revision: int = 0) -> "Assignment":
        obj = json.loads(data)
        groups = [int(g) for g in obj["groups"]]
        if len(groups) != NUM_GROUPS:
            raise ValueError(
                f"assignment has {len(groups)} groups, expected {NUM_GROUPS}"
            )
        return cls(
            version=int(obj["version"]),
            num_shards=int(obj["numShards"]),
            groups=groups,
            mod_revision=mod_revision,
        )


def load_assignment(store) -> Assignment | None:
    kv = store.get(ASSIGN_KEY)
    if kv is None:
        return None
    return Assignment.decode(kv.value, kv.mod_revision)


def init_assignment(store, num_shards: int) -> Assignment:
    """Create the round-robin initial assignment if absent (CAS on
    version=0 so concurrent initializers converge on one winner)."""
    cur = load_assignment(store)
    if cur is not None:
        return cur
    a = Assignment(1, num_shards, [g % num_shards for g in range(NUM_GROUPS)])
    ok, _, _ = store.cas(ASSIGN_KEY, a.encode(), required_version=0)
    if not ok:
        return load_assignment(store)
    return load_assignment(store)


def rebalance_groups(
    groups: list[int],
    group_load: np.ndarray,
    alive: set[int],
    max_moves: int = 32,
) -> list[int]:
    """Move-minimizing rebalance (reference leader_activities.go:227-343
    semantics: even split, fewest moves, batched).

    ``group_load[g]`` = nodes currently hashed into group g.  Groups on
    dead shards are reassigned first (failure recovery); then the
    heaviest shard donates its lightest groups to the lightest shard
    while that strictly shrinks the spread.  Returns a NEW groups list
    (possibly identical).
    """
    if not alive:
        return list(groups)
    groups = list(groups)
    load = {s: 0 for s in alive}
    for g, s in enumerate(groups):
        if s in load:
            load[s] += int(group_load[g])
    moves = 0

    # Dead-shard evacuation (unconditional — correctness, not balance).
    for g, s in enumerate(groups):
        if s not in alive:
            tgt = min(load, key=load.get)
            groups[g] = tgt
            load[tgt] += int(group_load[g])
            moves += 1

    while moves < max_moves and len(load) > 1:
        hi = max(load, key=load.get)
        lo = min(load, key=load.get)
        spread = load[hi] - load[lo]
        if spread <= 0:
            break
        # The lightest non-empty group on the heaviest shard that still
        # shrinks the spread when moved.
        best, best_w = -1, None
        for g, s in enumerate(groups):
            if s != hi:
                continue
            w = int(group_load[g])
            if w == 0:
                continue
            if w < spread and (best_w is None or w < best_w):
                best, best_w = g, w
        if best < 0:
            break
        groups[best] = lo
        load[hi] -= best_w
        load[lo] += best_w
        moves += 1
    return groups


@guarded_by(
    # Mask state is tick-thread-confined: the ownership set, the
    # deferred-claim set (drop-before-claim correctness depends on their
    # relative order) and the row->group journal fold all belong to the
    # thread driving tick() — audited, not assumed (lint/guards.py).
    _claimed=THREAD_OWNER,
    _pending_claim=THREAD_OWNER,
    _row_group=THREAD_OWNER,
    assignment=THREAD_OWNER,
)
class ShardMember:
    """One shard: a Coordinator plus intake filter, ownership mask
    upkeep, and a status heartbeat.

    Tick-driven like everything else in the control plane: call
    ``tick(now)`` per cycle; it drains the assignment watch, refreshes
    the mask when the assignment version or the host table's row mapping
    moved, heartbeats, and runs one coordinator step.
    """

    def __init__(
        self,
        store,
        coordinator: Coordinator,
        shard_idx: int,
        num_shards: int,
        *,
        heartbeat_every: float = 2.0,
    ):
        if not 0 <= shard_idx < num_shards:
            raise ValueError(f"shard_idx {shard_idx} not in [0, {num_shards})")
        self.store = store
        self.coordinator = coordinator
        self.shard_idx = shard_idx
        self.num_shards = num_shards
        self.heartbeat_every = heartbeat_every
        coordinator.intake_filter = (
            lambda key: pod_shard(key, num_shards) == shard_idx
        )
        self.assignment: Assignment | None = None
        self._assign_watch = None
        self._group_cache: dict[str, int] = {}
        # Incremental mask state: row->group (journal-maintained, -1 =
        # empty row), the set of groups currently claimed, and groups
        # assigned to us whose claim is deferred one tick
        # (drop-before-claim, module doc).
        self._row_group = np.full(
            (coordinator.table_spec.max_nodes,), -1, np.int32
        )
        self._journal = coordinator.host.enable_row_journal()
        # Rows that predate the journal (already-bootstrapped coordinator).
        for name, row in coordinator.host._row_of.items():
            self._row_group[row] = group_of(name)
        self._claimed: set[int] = set()
        self._pending_claim: set[int] = set()
        self._mask_version = -1
        self._last_beat = 0.0
        self._status_rev = 0

    # ---- lifecycle -----------------------------------------------------

    def start(self, now: float) -> None:
        """``now`` must come from the same clock every later ``tick``
        uses (simulated or wall — never mixed; the rebalancer compares
        heartbeat times against its own ``now``)."""
        self.coordinator.bootstrap()
        self.assignment = init_assignment(self.store, self.num_shards)
        self._assign_watch = self.store.watch(
            ASSIGN_KEY, start_revision=self.assignment.mod_revision + 1
        )
        # First claim is immediate: a member starting up owns whatever
        # the current assignment says (there is no donor mid-handoff).
        self._claimed = {
            g for g, s in enumerate(self.assignment.groups)
            if s == self.shard_idx
        }
        self._mask_version = self.assignment.version
        self._refresh_mask(force=True)
        self.heartbeat(now)

    def close(self) -> None:
        if self._assign_watch is not None:
            self._assign_watch.cancel()
            self._assign_watch = None
        self.coordinator.close()

    # ---- mask upkeep ---------------------------------------------------

    def _drain_assignment(self) -> None:
        try:
            for etype, _key, value, mrev in drain_events_light(
                self._assign_watch
            ):
                if etype != 0:
                    continue
                try:
                    self.assignment = Assignment.decode(value, mrev)
                except Exception:
                    log.exception(
                        "undecodable shard assignment; keeping current"
                    )
        except Exception:
            # Watch lost (store restart / overflow): re-read + re-watch —
            # the assignment object is tiny, resync is one get.
            log.info("assignment watch lost; resyncing", exc_info=True)
            try:
                self._assign_watch.cancel()
            # Canceling an already-broken watch may itself fail; the
            # rewatch below is the recovery either way.
            except Exception:  # graftlint: disable=broad-except
                pass
            cur = load_assignment(self.store)
            if cur is not None:
                self.assignment = cur
            self._assign_watch = self.store.watch(
                ASSIGN_KEY,
                start_revision=(cur.mod_revision + 1) if cur else 0,
            )

    def _drain_journal(self) -> bool:
        """Fold host row->name changes into _row_group; True if any."""
        if not self._journal:
            return False
        cache = self._group_cache
        for name, row, alive in self._journal:
            if alive:
                g = cache.get(name)
                if g is None:
                    g = cache[name] = group_of(name)
                self._row_group[row] = g
            else:
                self._row_group[row] = -1
        self._journal.clear()
        return True

    def _refresh_mask(self, force: bool = False) -> None:
        """Apply assignment + row changes to the ownership mask.

        Assignment version moved: lost groups drop from ``_claimed`` now;
        gained groups go to ``_pending_claim`` and are claimed on the
        NEXT call (drop-before-claim, module doc).  Row changes come from
        the host's delta journal, so steady state is O(changes) python +
        one vectorized rebuild, not an O(N) name loop per tick.
        """
        rows_changed = self._drain_journal()
        a = self.assignment
        version_changed = a.version != self._mask_version
        claim_now = bool(self._pending_claim)
        if not (rows_changed or version_changed or claim_now or force):
            return
        if claim_now:
            self._claimed |= self._pending_claim
            self._pending_claim = set()
        if version_changed:
            target = {
                g for g, s in enumerate(a.groups) if s == self.shard_idx
            }
            self._pending_claim = target - self._claimed
            self._claimed &= target          # drops apply immediately
            self._mask_version = a.version
        claim_np = np.zeros((NUM_GROUPS,), bool)
        if self._claimed:
            claim_np[list(self._claimed)] = True
        mask = claim_np[np.clip(self._row_group, 0, NUM_GROUPS - 1)]
        mask &= self._row_group >= 0
        self.coordinator.set_row_mask(mask)
        _MASK_REFRESH.inc(shard=str(self.shard_idx))
        _OWNED_NODES.set(int(mask.sum()), shard=str(self.shard_idx))

    # ---- status heartbeat ----------------------------------------------

    def heartbeat(self, now: float) -> None:
        """Publish liveness + load; the rebalancer reads these.

        Faultline hook (``shardset.lease``, op ``heartbeat/<shard>``):
        a dropped heartbeat is simply skipped — exactly a renewal the
        process never got to send — so the rebalancer's dead-shard
        evacuation fires after ``dead_after``, the same recovery a real
        silent shard gets.  Real write failures retry under the
        shardset.lease policy; give-up also skips (the next tick's
        heartbeat is the retry that matters — liveness is level-based,
        not edge-based)."""
        d = faultline.decide(
            "shardset.lease", f"heartbeat/{self.shard_idx}"
        )
        if d is not None:
            if d.kind == "delay":
                time.sleep(d.delay_s)
            else:
                log.warning(
                    "shard %d heartbeat suppressed (injected %s)",
                    self.shard_idx, d.kind,
                )
                return
        owned = (
            int(self.coordinator._row_mask_np.sum())
            if self.coordinator._row_mask_np is not None
            else 0
        )
        body = json.dumps(
            {
                "shard": self.shard_idx,
                "renewTime": now,
                "queue": len(self.coordinator.queue),
                "ownedNodes": owned,
            }
        ).encode()
        key = STATUS_PREFIX + str(self.shard_idx).encode()
        try:
            self._status_rev = policy_for("shardset.lease").call(
                lambda: self.store.put(key, body), op="heartbeat"
            )
        except GiveUp as e:
            log.warning("shard %d heartbeat failed: %s", self.shard_idx, e)
            return
        self._last_beat = now

    # ---- cycle ---------------------------------------------------------

    def tick(self, now: float) -> int:
        """One cycle: assignment drain -> mask refresh -> heartbeat ->
        coordinator step.  Returns pods bound this tick.

        ``now`` is required and must share a clock with the rebalancer's
        ``run_once`` — heartbeat freshness is a comparison between the
        two, so mixing simulated and wall time silently declares every
        member dead (or immortal)."""
        self._drain_assignment()
        bound = self.coordinator.step()
        # After the step: the coordinator's watch drain may have added
        # nodes this tick; refresh so the NEXT wave sees them owned.
        self._refresh_mask()
        if now - self._last_beat >= self.heartbeat_every:
            self.heartbeat(now)
        return bound


class Rebalancer:
    """Leader activity: keep the assignment balanced over live shards.

    Run by whichever process holds the control-plane lease
    (control/leader.py) — mirrors the reference's single-leader node
    labeler (leader_activities.go:100-343) with a minimum interval
    between rounds and a per-round move cap.
    """

    def __init__(
        self,
        store,
        host,                        # any current NodeTableHost view
        num_shards: int,
        *,
        min_interval: float = 30.0,
        max_moves: int = 32,
        dead_after: float = 15.0,
    ):
        self.store = store
        self.host = host
        self.num_shards = num_shards
        self.min_interval = min_interval
        self.max_moves = max_moves
        self.dead_after = dead_after
        # Starts at 0, not -inf: under a simulated clock (harness ticks
        # from 0) the first round waits out min_interval like every later
        # one; under time.monotonic() "now" dwarfs the interval and the
        # first round runs immediately — both match the reference's
        # min-30s-between-rebalances floor.
        self._last_run = 0.0
        self._group_cache: dict[str, int] = {}

    def alive_shards(self, now: float) -> set[int]:
        """Shards whose status heartbeat is fresh."""
        res = self.store.range(STATUS_PREFIX, prefix_end(STATUS_PREFIX))
        alive: set[int] = set()
        for kv in res.kvs:
            try:
                obj = json.loads(kv.value)
                if now - float(obj["renewTime"]) <= self.dead_after:
                    alive.add(int(obj["shard"]))
            except Exception:
                # A malformed heartbeat reads as a dead shard (its groups
                # get evacuated) — keep the parse failure diagnosable
                # without letting one bad record kill the round.
                log.debug("undecodable shard status %r", kv.key,
                          exc_info=True)
                continue
        return {s for s in alive if 0 <= s < self.num_shards}

    def group_loads(self) -> np.ndarray:
        counts = np.zeros((NUM_GROUPS,), np.int64)
        cache = self._group_cache
        for name in self.host._row_of:
            g = cache.get(name)
            if g is None:
                g = cache[name] = group_of(name)
            counts[g] += 1
        return counts

    def run_once(self, now: float, *, force: bool = False) -> bool:
        """One rebalance round; returns True if the assignment changed.

        ``now`` must share a clock with the members' ``tick`` (see
        ShardMember.tick).  CAS-guarded: a concurrent leader handover
        can't interleave two writers (the loser's CAS fails and it
        re-reads next round).
        """
        if not force and now - self._last_run < self.min_interval:
            return False
        self._last_run = now
        # Faultline hook (``shardset.lease``, op ``rebalance``): a failed
        # round is skipped whole — the interval timer ran, so the NEXT
        # round is the retry (the reference's leader activity has the
        # same shape: best-effort per round, durable across rounds).
        d = faultline.decide("shardset.lease", "rebalance")
        if d is not None:
            if d.kind == "delay":
                time.sleep(d.delay_s)
            else:
                log.warning("rebalance round skipped (injected %s)", d.kind)
                return False
        cur = init_assignment(self.store, self.num_shards)
        alive = self.alive_shards(now)
        if not alive:
            return False
        new_groups = rebalance_groups(
            cur.groups, self.group_loads(), alive, self.max_moves
        )
        if new_groups == cur.groups:
            return False
        moved = sum(1 for a, b in zip(cur.groups, new_groups) if a != b)
        nxt = Assignment(cur.version + 1, self.num_shards, new_groups)
        ok, _, _ = self.store.cas(
            ASSIGN_KEY, nxt.encode(), required_mod=cur.mod_revision
        )
        if ok:
            _REBALANCES.inc()
            _GROUP_MOVES.inc(float(moved))
            log.info(
                "rebalanced: %d groups moved, alive=%s", moved, sorted(alive)
            )
        return ok
