"""Kubernetes-shaped object codec for the control-plane store.

The reference stores real Kubernetes protobuf under /registry/ (written by
kube-apiserver, reference README.adoc:316-328 for the key layout); this
framework's control plane stores the same object *shapes* as JSON under
the same keys, so the store traffic pattern (per-Kind prefixes, Txn CAS
updates, lease churn) is preserved while staying self-contained.

Key layout (matching kube-apiserver's registry paths):
- nodes:  /registry/minions/<name>
- pods:   /registry/pods/<namespace>/<name>
- leases: /registry/leases/<namespace>/<name>

``decode_pod`` compiles the inline affinity/topologySpreadConstraint
specs into interned slot references via a ConstraintTracker — the
host-side half of the feature compiler (SURVEY.md §7 step 1).
"""

from __future__ import annotations

import json
import re

from k8s1m_tpu.config import (
    K8S_DEFAULT_SCHEDULER,
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    EFFECT_NONE,
    EFFECT_PREFER_NO_SCHEDULE,
    SEL_OP_DOES_NOT_EXIST,
    SEL_OP_EXISTS,
    SEL_OP_GT,
    SEL_OP_IN,
    SEL_OP_LT,
    SEL_OP_NOT_IN,
    SPREAD_DO_NOT_SCHEDULE,
    SPREAD_SCHEDULE_ANYWAY,
    TOL_OP_EQUAL,
    TOL_OP_EXISTS,
    TOPO_HOSTNAME,
    TOPO_REGION,
    TOPO_ZONE,
)
from k8s1m_tpu.ops.priority import pod_priority_of
from k8s1m_tpu.snapshot.constraints import ConstraintTracker
from k8s1m_tpu.snapshot.node_table import NodeInfo, Taint
from k8s1m_tpu.snapshot.pod_encoding import (
    AffinityTermRef,
    NodeSelectorTerm,
    PodInfo,
    PreferredSchedulingTerm,
    SelectorRequirement,
    SpreadConstraintRef,
    Toleration,
)


_EFFECTS = {
    "": EFFECT_NONE,
    "NoSchedule": EFFECT_NO_SCHEDULE,
    "PreferNoSchedule": EFFECT_PREFER_NO_SCHEDULE,
    "NoExecute": EFFECT_NO_EXECUTE,
}
_EFFECT_NAMES = {v: k for k, v in _EFFECTS.items()}
_SEL_OPS = {
    "In": SEL_OP_IN,
    "NotIn": SEL_OP_NOT_IN,
    "Exists": SEL_OP_EXISTS,
    "DoesNotExist": SEL_OP_DOES_NOT_EXIST,
    "Gt": SEL_OP_GT,
    "Lt": SEL_OP_LT,
}
_SEL_OP_NAMES = {v: k for k, v in _SEL_OPS.items()}
_TOPO_KEYS = {
    "kubernetes.io/hostname": TOPO_HOSTNAME,
    "topology.kubernetes.io/zone": TOPO_ZONE,
    "topology.kubernetes.io/region": TOPO_REGION,
}
_TOPO_NAMES = {v: k for k, v in _TOPO_KEYS.items()}

_BIN_SUFFIX = {"Ki": 1 << 10, "Mi": 1 << 20, "Gi": 1 << 30, "Ti": 1 << 40}
_DEC_SUFFIX = {"k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12}


def node_key(name: str) -> bytes:
    return f"/registry/minions/{name}".encode()


def pod_key(namespace: str, name: str) -> bytes:
    return f"/registry/pods/{namespace}/{name}".encode()


def lease_key(namespace: str, name: str) -> bytes:
    return f"/registry/leases/{namespace}/{name}".encode()


def pod_key_str_of_obj(obj: dict) -> str:
    """``"<ns>/<name>"`` for a pod manifest dict — the ``PodInfo.key``
    shape (unset namespace = "default", upstream semantics).  The ONE
    derivation the webhook and ``submit_external`` both use for
    podtrace keys: the two sites must produce byte-identical keys or a
    webhook-opened trace never matches the coordinator's chain."""
    md = obj.get("metadata") or {}
    return f"{md.get('namespace') or 'default'}/{md.get('name', '')}"


# ---- quantities ------------------------------------------------------------


def parse_cpu(q: str | int | float) -> int:
    """Kubernetes cpu quantity -> milliCPU ("2" -> 2000, "500m" -> 500)."""
    if isinstance(q, (int, float)):
        return int(q * 1000)
    q = q.strip()
    if q.endswith("m"):
        return int(q[:-1])
    return int(float(q) * 1000)


def parse_mem(q: str | int | float) -> int:
    """Kubernetes memory quantity -> KiB ("8Gi" -> 8388608, bare -> bytes)."""
    if isinstance(q, (int, float)):
        return int(q) >> 10
    q = q.strip()
    for suf, mult in _BIN_SUFFIX.items():
        if q.endswith(suf):
            return int(float(q[: -len(suf)]) * mult) >> 10
    for suf, mult in _DEC_SUFFIX.items():
        if q.endswith(suf):
            return int(float(q[: -len(suf)]) * mult) >> 10
    return int(float(q)) >> 10


def cpu_str(milli: int) -> str:
    return f"{milli}m"


def mem_str(kib: int) -> str:
    return f"{kib}Ki"


# ---- Node ------------------------------------------------------------------


def encode_node(node: NodeInfo) -> bytes:
    obj = {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": node.name, "labels": dict(node.labels)},
        "spec": {},
        "status": {
            "allocatable": {
                "cpu": cpu_str(node.cpu_milli),
                "memory": mem_str(node.mem_kib),
                "pods": str(node.pods),
            },
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }
    if node.unschedulable:
        obj["spec"]["unschedulable"] = True
    if node.taints:
        obj["spec"]["taints"] = [
            {"key": t.key, "value": t.value, "effect": _EFFECT_NAMES[t.effect]}
            for t in node.taints
        ]
    return json.dumps(obj, separators=(",", ":")).encode()


def decode_node(data: bytes) -> NodeInfo:
    node = decode_node_fast(data)
    if node is not None:
        return node
    obj = json.loads(data)
    meta = obj.get("metadata", {})
    spec = obj.get("spec", {})
    alloc = obj.get("status", {}).get("allocatable", {})
    return NodeInfo(
        name=meta["name"],
        labels=dict(meta.get("labels", {})),
        cpu_milli=parse_cpu(alloc.get("cpu", "0")),
        mem_kib=parse_mem(alloc.get("memory", "0")),
        pods=int(alloc.get("pods", 0)),
        unschedulable=bool(spec.get("unschedulable", False)),
        taints=[
            Taint(t["key"], t.get("value", ""), _EFFECTS[t.get("effect", "")])
            for t in spec.get("taints", [])
        ],
    )


# Exact grammar of encode_node's output for a plain schedulable node
# (no taints, no unschedulable, fixed Ready conditions): the bulk
# cold-build lane (snapshot/bulkload.py) FULLMATCHES a value against
# this and reads the captures directly — name, raw label blob, cpu
# milli, mem KiB, pods.  Everything variable is captured by character
# classes that exclude quotes, backslashes and control bytes, so a
# fullmatch parses byte-identically to json.loads by construction
# (json.dumps ensure_ascii escapes non-ASCII into backslash sequences,
# which simply fail the match); any other shape — heartbeat-churned
# status, taints, escapes — falls back to decode_node per value.
_S = rb'[^"\\\x00-\x1f]*'
CANONICAL_NODE_RE = re.compile(
    rb'\{"apiVersion":"v1","kind":"Node","metadata":\{"name":"(' + _S +
    rb')","labels":\{((?:"' + _S + rb'":"' + _S +
    rb'"(?:,"' + _S + rb'":"' + _S + rb'")*)?)\}\},"spec":\{\},'
    rb'"status":\{"allocatable":\{"cpu":"(\d+)m","memory":"(\d+)Ki",'
    rb'"pods":"(\d+)"\},"conditions":\[\{"type":"Ready","status":'
    rb'"True"\}\]\}\}'
)
# One label pair inside the captured blob (the blob grammar above
# guarantees findall reconstructs it exactly; duplicate keys resolve
# last-wins below, matching json.loads).
CANONICAL_LABEL_RE = re.compile(rb'"(' + _S + rb')":"(' + _S + rb')"')

# Byte landmarks of the canonical encode_node shape (same restricted-
# parser contract as decode_pod_fast): accepted iff the metadata prefix
# matches exactly, spec is EMPTY (taints/unschedulable fall back to the
# JSON path), and allocatable uses the canonical "<n>m"/"<n>Ki" units.
# Anything after allocatable.pods — conditions, kubelet heartbeats — is
# deliberately ignored: the scheduler reads nothing from node status
# beyond allocatable, so status-churning writers stay on the fast path.
_FN_HEAD = b'{"apiVersion":"v1","kind":"Node","metadata":{"name":"'
_FN_LABELS = b'","labels":{'
# spec must be empty AND allocatable must open status — anchored as one
# contiguous landmark so a nested "allocatable" deeper in status can
# never be mistaken for the real one (the fast path must parse bytes
# identically to the JSON path or not at all).
_FN_SPEC_ALLOC = b'},"spec":{},"status":{"allocatable":{"cpu":"'
_FN_MEM = b'","memory":"'
_FN_PODS = b'","pods":"'


def _scan_labels(data: bytes, i: int):
    """Parse a flat {"k":"v",...} object of plain strings starting at
    ``i`` (just past the opening brace).  Returns (labels, index past the
    closing brace) or None for any other shape — shared by the canonical
    pod and node fast parsers so their escape/quote handling can never
    drift apart."""
    labels: dict[str, str] = {}
    if data[i : i + 1] == b"}":
        return labels, i + 1
    while True:
        if data[i : i + 1] != b'"':
            return None
        j = data.find(b'"', i + 1)
        lk = data[i + 1 : j]
        if data[j : j + 3] != b'":"':
            return None
        i = j + 3
        j = data.find(b'"', i)
        labels[lk.decode()] = data[i:j].decode()
        nxt = data[j + 1 : j + 2]
        i = j + 2
        if nxt == b",":
            continue
        if nxt == b"}":
            return labels, i
        return None


_WS = b" \t\n\r"
# Keys whose re-appearance would shadow state the fast path already
# consumed (json.loads is last-wins; the byte scanner is first-wins).
_DUP_STATUS_KEYS = frozenset((b"allocatable",))
_DUP_TOP_KEYS = frozenset((b"metadata", b"spec", b"status"))


# Any raw control byte anywhere in the value demotes to the JSON path:
# valid compact JSON (what every canonical writer emits) contains none,
# and inside strings json.loads rejects them — one C-level scan closes
# that divergence for the whole value, parsed span and tail alike.
_CTRL_RE = re.compile(rb"[\x00-\x1f]")

# RFC 8259 number grammar (json.loads rejects 01, 1., .5, bare -).
_NUM_PAT = rb"-?(?:0|[1-9][0-9]*)(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?"
_NUM_RE = re.compile(_NUM_PAT)

# Fast-accept for the hot tail shape — a flat conditions array of
# string/number/bool/null fields (the framework's own encoder plus
# kubelet-style heartbeat churn), matched in one C-level regex pass so
# the Python FSM below only ever runs on exotic tails.  Strings here are
# printable-ASCII-only (no quote/backslash/ctrl); anything else (UTF-8
# text, nesting, ws) falls to the FSM.  The shape admits no status-level
# key but "conditions" and no top-level key at all, so duplicate
# landmarks cannot hide in a fast-accepted tail.
_STR_PAT = rb'"[ !#-\[\]-~]*"'
_CONDV_PAT = rb"(?:" + _STR_PAT + rb"|" + _NUM_PAT + rb"|true|false|null)"
_CONDKV_PAT = _STR_PAT + rb":" + _CONDV_PAT
_CONDOBJ_PAT = rb"\{(?:" + _CONDKV_PAT + rb"(?:," + _CONDKV_PAT + rb")*)?\}"
_TAIL_CANON_RE = re.compile(
    rb'\}(?:,"conditions":\[(?:'
    + _CONDOBJ_PAT
    + rb"(?:,"
    + _CONDOBJ_PAT
    + rb")*)?\])?\}\}\Z"
)


def _node_tail_ok(data: bytes, i: int) -> bool:
    """Validate the unparsed tail of a canonical node value, starting
    just past allocatable's closing brace (inside the status object,
    expecting ',' or '}').

    Two jobs, both required for the fast path's contract ("parse
    identically to json.loads or not at all"):
      1. reject duplicate landmark KEYS json.loads would last-win — a
         second "allocatable" at the status level, a second metadata/
         spec/status at the top level;
      2. reject malformed tails json.loads would raise on (garbage
         literals, mismatched brackets, bad commas), so corrupted bytes
         never parse fast while raising for every pure-JSON consumer.
    A strict streaming validator over the (short) conditions tail.
    Tokenizing is simple because the caller already rejected values
    containing backslashes or control bytes: a quote always terminates a
    string.  This is the SLOW fallback — the caller fast-accepts the
    canonical conditions shape with _TAIL_CANON_RE first, so this runs
    only on exotic tails.
    """
    try:
        # json.loads(bytes) decodes UTF-8 first; tail strings are never
        # decoded by the fast path, so validate here or diverge on
        # invalid UTF-8.
        data[i:].decode()
    except UnicodeDecodeError:
        return False
    n = len(data)
    # Container stack: True = object, False = array.  We start inside
    # status, whose parent is the root object; a key is status-level
    # when len(stack) == 2 and top-level when len(stack) == 1.
    stack = [True, True]
    COMMA_OR_CLOSE, KEY, COLON, VALUE, FIRST_KEY, FIRST_VALUE = range(6)
    state = COMMA_OR_CLOSE
    while True:
        while i < n and data[i] in _WS:
            i += 1
        if not stack:
            return i == n          # root closed; only ws may trail
        if i >= n:
            return False           # truncated
        c = data[i]
        if state == COMMA_OR_CLOSE:
            if c == 0x2C:          # ','
                i += 1
                state = KEY if stack[-1] else VALUE
            elif c == (0x7D if stack[-1] else 0x5D):   # '}' / ']'
                stack.pop()
                i += 1
            else:
                return False
        elif state == KEY:
            if c != 0x22:          # '"'
                return False
            q = data.find(b'"', i + 1)
            if q < 0:
                return False
            key = data[i + 1 : q]
            if len(stack) == 2 and key in _DUP_STATUS_KEYS:
                return False
            if len(stack) == 1 and key in _DUP_TOP_KEYS:
                return False
            i = q + 1
            state = COLON
        elif state == COLON:
            if c != 0x3A:          # ':'
                return False
            i += 1
            state = VALUE
        elif state == VALUE:
            if c == 0x22:          # string
                q = data.find(b'"', i + 1)
                if q < 0:
                    return False
                i = q + 1
                state = COMMA_OR_CLOSE
            elif c == 0x7B:        # '{'
                stack.append(True)
                i += 1
                state = FIRST_KEY
            elif c == 0x5B:        # '['
                stack.append(False)
                i += 1
                state = FIRST_VALUE
            elif data.startswith(b"true", i):
                i += 4
                state = COMMA_OR_CLOSE
            elif data.startswith(b"false", i):
                i += 5
                state = COMMA_OR_CLOSE
            elif data.startswith(b"null", i):
                i += 4
                state = COMMA_OR_CLOSE
            else:
                m = _NUM_RE.match(data, i)
                if m is None:
                    return False
                i = m.end()
                state = COMMA_OR_CLOSE
        elif state == FIRST_KEY:
            if c == 0x7D:          # '}': empty object
                stack.pop()
                i += 1
                state = COMMA_OR_CLOSE
            else:
                state = KEY        # no advance; re-dispatch this char
        else:                      # FIRST_VALUE
            if c == 0x5D:          # ']': empty array
                stack.pop()
                i += 1
                state = COMMA_OR_CLOSE
            else:
                state = VALUE      # no advance; re-dispatch this char


def decode_node_fast(data: bytes) -> NodeInfo | None:
    """Parse the canonical node shape with byte scans; None = use JSON.

    The node-decode analogue of decode_pod_fast: a 1M-node bootstrap (or
    a heartbeat-churning watch stream) otherwise spends ~26µs/node in
    json.loads for objects this framework's own encoders wrote.
    """
    if not data.startswith(_FN_HEAD) or b"\\" in data or _CTRL_RE.search(data):
        return None
    i = len(_FN_HEAD)
    j = data.find(b'"', i)
    name = data[i:j]
    if not data.startswith(_FN_LABELS, j):
        return None
    scanned = _scan_labels(data, j + len(_FN_LABELS))
    if scanned is None:
        return None
    labels, i = scanned
    if not data.startswith(_FN_SPEC_ALLOC, i):
        return None
    i += len(_FN_SPEC_ALLOC)
    j = data.find(b'"', i)
    cpu_b = data[i:j]
    if not data.startswith(_FN_MEM, j):
        return None
    i = j + len(_FN_MEM)
    j = data.find(b'"', i)
    mem_b = data[i:j]
    if not data.startswith(_FN_PODS, j):
        return None
    i = j + len(_FN_PODS)
    j = data.find(b'"', i)
    pods_b = data[i:j]
    if not cpu_b.endswith(b"m") or not mem_b.endswith(b"Ki"):
        return None
    # allocatable must CLOSE right after pods (a further key in it —
    # e.g. a duplicate "cpu" — would last-win under json.loads while the
    # scan above already consumed the first).
    if data[j + 1 : j + 2] != b"}":
        return None
    # The rest of the tail (conditions, heartbeat noise) is unparsed —
    # but json.loads is last-wins for duplicate keys, so a later
    # duplicate of any landmark we already consumed would make the two
    # paths disagree, and a malformed tail would parse fast while
    # raising for every pure-JSON consumer.  One C-level regex accepts
    # the hot heartbeat shape; anything else takes the strict FSM walk.
    if _TAIL_CANON_RE.match(data, j + 1) is None and not _node_tail_ok(
        data, j + 2
    ):
        return None
    try:
        return NodeInfo(
            name=name.decode(),
            labels=labels,
            cpu_milli=int(cpu_b[:-1]),
            mem_kib=int(mem_b[:-2]),
            pods=int(pods_b),
        )
    except ValueError:
        return None


# ---- Pod -------------------------------------------------------------------


def _encode_term(term: NodeSelectorTerm) -> dict:
    return {
        "matchExpressions": [
            {
                "key": r.key,
                "operator": _SEL_OP_NAMES[r.op],
                **({"values": list(r.values)} if r.values else {}),
            }
            for r in term.match_expressions
        ]
    }


def _decode_term(obj: dict) -> NodeSelectorTerm:
    return NodeSelectorTerm(
        match_expressions=[
            SelectorRequirement(
                key=e["key"],
                op=_SEL_OPS[e["operator"]],
                values=list(e.get("values", [])),
            )
            for e in obj.get("matchExpressions", [])
        ]
    )


def encode_pod(pod: PodInfo, *, scheduler_name: str | None = None,
               raw_affinity: dict | None = None,
               raw_spread: list | None = None) -> bytes:
    """PodInfo -> Kubernetes-shaped JSON.

    Slot references (spread_refs/affinity_refs) are a compiled, tracker-
    relative form, so callers that built the pod from raw constraint specs
    pass them through ``raw_affinity``/``raw_spread`` for re-encoding.
    """
    spec: dict = {
        "schedulerName": scheduler_name or pod.scheduler_name,
        "containers": [
            {
                "name": "app",
                "image": "img",
                "resources": {
                    "requests": {
                        "cpu": cpu_str(pod.cpu_milli),
                        "memory": mem_str(pod.mem_kib),
                    }
                },
            }
        ],
    }
    if pod.node_name:
        spec["nodeName"] = pod.node_name
    if pod.node_selector:
        spec["nodeSelector"] = dict(pod.node_selector)
    if pod.tolerations:
        spec["tolerations"] = [
            {
                **({"key": t.key} if t.key else {}),
                "operator": "Exists" if t.op == TOL_OP_EXISTS else "Equal",
                **({"value": t.value} if t.value else {}),
                **(
                    {"effect": _EFFECT_NAMES[t.effect]}
                    if t.effect != EFFECT_NONE
                    else {}
                ),
            }
            for t in pod.tolerations
        ]
    affinity = dict(raw_affinity or {})
    if pod.required_terms or pod.preferred_terms:
        node_aff: dict = {}
        if pod.required_terms:
            node_aff["requiredDuringSchedulingIgnoredDuringExecution"] = {
                "nodeSelectorTerms": [_encode_term(t) for t in pod.required_terms]
            }
        if pod.preferred_terms:
            node_aff["preferredDuringSchedulingIgnoredDuringExecution"] = [
                {"weight": p.weight, "preference": _encode_term(p.term)}
                for p in pod.preferred_terms
            ]
        affinity["nodeAffinity"] = node_aff
    if affinity:
        spec["affinity"] = affinity
    if raw_spread:
        spec["topologySpreadConstraints"] = list(raw_spread)
    if pod.priority:
        # Appended after the canonical fields: spec still OPENS with
        # schedulerName, so the bind splice landmark is unchanged; the
        # extra key makes the object non-canonical for the byte-scan
        # fast parsers, which is correct — priority-bearing pods belong
        # on the full decode path where admission/preemption read it.
        spec["priority"] = int(pod.priority)
    obj = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": pod.name,
            "namespace": pod.namespace,
            "labels": dict(pod.labels),
        },
        "spec": spec,
        "status": {"phase": "Pending"},
    }
    return json.dumps(obj, separators=(",", ":")).encode()


def decode_pod(data: bytes, tracker: ConstraintTracker | None = None) -> PodInfo:
    """JSON -> PodInfo; inline constraints are interned via ``tracker``.

    Without a tracker, podAffinity/topologySpreadConstraints are ignored
    (the caller only wants identity/resources — e.g. load accounting).
    """
    pod = decode_pod_fast(data, tracker)
    if pod is not None:
        return pod
    return decode_pod_obj(json.loads(data), tracker)


# Byte landmarks of the canonical encode_pod shape.  The fast parser
# accepts EXACTLY the objects this module's encode_pod emits for pods with
# no selectors/tolerations/affinity/spread (plus the nodeName-spliced bind
# form) — anything else, including any backslash escape anywhere, falls
# back to the full JSON path.  This is the restricted-parser analogue of
# the reference's empirically-restricted Txn support (one shape, fast;
# everything else rejected — kv_service.rs:126-337).
_FP_HEAD = b'{"apiVersion":"v1","kind":"Pod","metadata":{"name":"'
_FP_NS = b'","namespace":"'
_FP_LABELS = b'","labels":{'
_FP_NODE = b'"nodeName":"'
_FP_SCHED = b'"schedulerName":"'
_FP_CONTAINERS = (
    b'","containers":[{"name":"app","image":"img",'
    b'"resources":{"requests":{"cpu":"'
)
_FP_MEM = b'","memory":"'
_FP_TAIL = b'"}}}]},"status":{"phase":"Pending"}}'
# encode_pod appends nodeName after containers (dict insertion order);
# the bind splice inserts it before schedulerName.  Accept both.
_FP_NODE_TAIL = b'"}}}],"nodeName":"'
_FP_STATUS = b'"},"status":{"phase":"Pending"}}'


def decode_pod_fast(
    data: bytes, tracker: ConstraintTracker | None = None
) -> PodInfo | None:
    """Parse the canonical pod shape with byte scans; None = not canonical.

    ~4x faster than json.loads + decode_pod_obj on the watch firehose,
    where nearly every object is one this framework's own encoders wrote.
    """
    if not data.startswith(_FP_HEAD) or b"\\" in data:
        return None
    i = len(_FP_HEAD)
    j = data.find(b'"', i)
    name = data[i:j]
    if not data.startswith(_FP_NS, j):
        return None
    i = j + len(_FP_NS)
    j = data.find(b'"', i)
    namespace = data[i:j]
    if not data.startswith(_FP_LABELS, j):
        return None
    scanned = _scan_labels(data, j + len(_FP_LABELS))
    if scanned is None:
        return None
    labels, i = scanned
    if data[i : i + 10] != b'},"spec":{':
        return None
    i += 10
    node_name = None
    if data.startswith(_FP_NODE, i):
        i += len(_FP_NODE)
        j = data.find(b'"', i)
        node_name = data[i:j].decode()
        if data[j : j + 2] != b'",':
            return None
        i = j + 2
    if not data.startswith(_FP_SCHED, i):
        return None
    i += len(_FP_SCHED)
    j = data.find(b'"', i)
    scheduler_name = data[i:j]
    if not data.startswith(_FP_CONTAINERS, j):
        return None
    i = j + len(_FP_CONTAINERS)
    j = data.find(b'"', i)
    cpu_b = data[i:j]
    if not data.startswith(_FP_MEM, j):
        return None
    i = j + len(_FP_MEM)
    j = data.find(b'"', i)
    mem_b = data[i:j]
    # The tail must be the EXACT remainder: proves there is no
    # nodeSelector/tolerations/affinity/topologySpreadConstraints.
    if data[j:] != _FP_TAIL:
        if node_name is not None or not data.startswith(_FP_NODE_TAIL, j):
            return None
        i = j + len(_FP_NODE_TAIL)
        j = data.find(b'"', i)
        node_name = data[i:j].decode()
        if data[j:] != _FP_STATUS:
            return None
    if not cpu_b.endswith(b"m") or not mem_b.endswith(b"Ki"):
        return None
    try:
        cpu = int(cpu_b[:-1])
        mem = int(mem_b[:-2])
    except ValueError:
        return None

    pod = PodInfo(
        name=name.decode(),
        namespace=namespace.decode(),
        labels=labels,
        cpu_milli=cpu,
        mem_kib=mem,
        scheduler_name=scheduler_name.decode(),
        node_name=node_name,
    )
    if tracker is not None:
        ns = pod.namespace
        pod.spread_incs = tracker.spread_matches(ns, labels)
        pod.ipa_incs = tracker.affinity_matches(ns, labels)
    return pod


def decode_pod_obj(obj: dict, tracker: ConstraintTracker | None = None) -> PodInfo:
    """dict -> PodInfo (webhook intake already holds the parsed object)."""
    meta = obj.get("metadata", {})
    spec = obj.get("spec", {})
    namespace = meta.get("namespace", "default")
    labels = dict(meta.get("labels", {}))

    cpu = mem = 0
    for c in spec.get("containers", []):
        req = c.get("resources", {}).get("requests", {})
        cpu += parse_cpu(req.get("cpu", 0))
        mem += parse_mem(req.get("memory", 0))

    pod = PodInfo(
        name=meta["name"],
        namespace=namespace,
        labels=labels,
        cpu_milli=cpu,
        mem_kib=mem,
        # Kubernetes semantics: an unset schedulerName belongs to
        # "default-scheduler", NOT to this framework's scheduler — the
        # reference's intake filter only claims explicitly-marked pods
        # (webhook.go:102-125).
        scheduler_name=spec.get("schedulerName", K8S_DEFAULT_SCHEDULER),
        node_name=spec.get("nodeName"),
        # Same forgiving parse as ops/priority.pod_priority_of: a pod
        # with a garbage priority schedules at 0, it is not rejected.
        priority=pod_priority_of(obj),
        node_selector=dict(spec.get("nodeSelector", {})),
        tolerations=[
            Toleration(
                key=t.get("key", ""),
                op=TOL_OP_EXISTS if t.get("operator", "Equal") == "Exists" else TOL_OP_EQUAL,
                value=t.get("value", ""),
                effect=_EFFECTS[t.get("effect", "")],
            )
            for t in spec.get("tolerations", [])
        ],
    )

    aff = spec.get("affinity", {})
    node_aff = aff.get("nodeAffinity", {})
    req = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution", {})
    pod.required_terms = [_decode_term(t) for t in req.get("nodeSelectorTerms", [])]
    pod.preferred_terms = [
        PreferredSchedulingTerm(weight=p.get("weight", 1), term=_decode_term(p["preference"]))
        for p in node_aff.get("preferredDuringSchedulingIgnoredDuringExecution", [])
    ]

    if tracker is not None:
        for sc in spec.get("topologySpreadConstraints", []):
            topo = _TOPO_KEYS.get(sc.get("topologyKey", ""))
            if topo is None:
                raise ValueError(
                    f"pod {pod.key}: unsupported topologyKey {sc.get('topologyKey')!r}"
                )
            selector = dict(sc.get("labelSelector", {}).get("matchLabels", {}))
            cid = tracker.spread_slot(namespace, selector, topo)
            pod.spread_refs.append(
                SpreadConstraintRef(
                    cid=cid,
                    topo=topo,
                    max_skew=sc.get("maxSkew", 1),
                    mode=(
                        SPREAD_SCHEDULE_ANYWAY
                        if sc.get("whenUnsatisfiable") == "ScheduleAnyway"
                        else SPREAD_DO_NOT_SCHEDULE
                    ),
                    self_match=ConstraintTracker.selector_matches(selector, labels),
                )
            )
        for kind in ("podAffinity", "podAntiAffinity"):
            sub = aff.get(kind, {})
            anti = kind == "podAntiAffinity"
            for term in sub.get("requiredDuringSchedulingIgnoredDuringExecution", []):
                pod.affinity_refs.append(
                    _decode_ipa_term(tracker, namespace, labels, term, True, anti, 1)
                )
            for wt in sub.get("preferredDuringSchedulingIgnoredDuringExecution", []):
                pod.affinity_refs.append(
                    _decode_ipa_term(
                        tracker, namespace, labels, wt["podAffinityTerm"],
                        False, anti, wt.get("weight", 1),
                    )
                )
        pod.spread_incs = tracker.spread_matches(namespace, labels)
        pod.ipa_incs = tracker.affinity_matches(namespace, labels)
    return pod


def _decode_ipa_term(
    tracker: ConstraintTracker,
    namespace: str,
    labels: dict[str, str],
    term: dict,
    required: bool,
    anti: bool,
    weight: int,
) -> AffinityTermRef:
    topo = _TOPO_KEYS.get(term.get("topologyKey", ""))
    if topo is None:
        raise ValueError(f"unsupported podAffinity topologyKey {term.get('topologyKey')!r}")
    selector = dict(term.get("labelSelector", {}).get("matchLabels", {}))
    tid = tracker.affinity_slot(namespace, selector, topo)
    return AffinityTermRef(
        tid=tid,
        topo=topo,
        required=required,
        anti=anti,
        weight=weight,
        self_match=ConstraintTracker.selector_matches(selector, labels),
    )
