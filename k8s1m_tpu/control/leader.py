"""Store-backed leader election + coordinator failover.

The reference elects a dist-scheduler leader through client-go's Lease
leaderelection (15s lease / 10s renew / 2s retry, reference
cmd/dist-scheduler/leader_activities.go:34-98); the leader runs the
webhook intake, node labeler, and webhook-Endpoints management, and a
replica that loses the lease steps down so a standby takes over.

Here the same contract runs against the native store: the election
object is a Lease under ``/registry/leases/<ns>/<name>`` and every
transition is a Txn CAS on its mod revision, so two candidates can never
both believe they acquired it (the store is the single arbiter exactly
as the apiserver+etcd pair is upstream).  Time is injected (``now``)
rather than read from the clock — elections are tick-driven like the
KWOK simulator, so failover paths are deterministically testable.

``HACoordinator`` pairs an elector with a Coordinator: only the current
leader bootstraps and drives scheduling cycles; on lease loss it tears
its watches down, and a standby's elector acquires and bootstraps fresh
(scheduler state is all soft — rebuilt from store watches, the same
"reconcile or rebuild" stance as the reference, README.adoc:184-214).
"""

from __future__ import annotations

import dataclasses
import json
import logging

from k8s1m_tpu.control.objects import lease_key
from k8s1m_tpu.obs.metrics import Counter, Gauge
from k8s1m_tpu.store.native import MemStore

log = logging.getLogger("k8s1m.leader")

_TRANSITIONS = Counter(
    "leader_transitions_total", "Leadership acquisitions", ("identity",)
)
_IS_LEADER = Gauge("leader_is_leader", "1 if this elector holds the lease",
                   ("identity",))


@dataclasses.dataclass
class LeaseRecord:
    holder: str
    acquire_time: float
    renew_time: float
    lease_duration_s: float
    transitions: int

    def encode(self) -> bytes:
        return json.dumps(
            {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "spec": {
                    "holderIdentity": self.holder,
                    "acquireTime": self.acquire_time,
                    "renewTime": self.renew_time,
                    "leaseDurationSeconds": self.lease_duration_s,
                    "leaseTransitions": self.transitions,
                },
            },
            separators=(",", ":"),
        ).encode()

    @classmethod
    def decode(cls, data: bytes) -> "LeaseRecord":
        spec = json.loads(data)["spec"]
        return cls(
            holder=spec["holderIdentity"],
            acquire_time=spec["acquireTime"],
            renew_time=spec["renewTime"],
            lease_duration_s=spec["leaseDurationSeconds"],
            transitions=spec.get("leaseTransitions", 0),
        )


class LeaderElector:
    """One candidate's view of a named election.

    Call ``tick(now)`` at least every ``retry_period_s``; it returns True
    while this candidate holds the lease.  Semantics mirror client-go:
    - acquire when the lease is absent, expired, or already ours;
    - renew every ``renew_period_s`` via CAS on the observed revision;
    - a failed CAS (someone else wrote) re-reads and backs off;
    - ``release()`` clears holderIdentity for fast handover on clean
      shutdown (leader_activities.go clears the webhook Endpoints the
      same way).
    """

    def __init__(
        self,
        store: MemStore,
        identity: str,
        *,
        name: str = "dist-scheduler-tpu",
        namespace: str = "kube-system",
        lease_duration_s: float = 15.0,
        renew_period_s: float = 10.0,
        retry_period_s: float = 2.0,
    ):
        self.store = store
        self.identity = identity
        self.key = lease_key(namespace, name)
        self.lease_duration_s = lease_duration_s
        self.renew_period_s = renew_period_s
        self.retry_period_s = retry_period_s
        self.is_leader = False
        self._observed_rev = 0
        self._observed: LeaseRecord | None = None
        self._last_attempt = -1e18

    # ---- internals -----------------------------------------------------

    def _observe(self) -> None:
        kv = self.store.get(self.key)
        if kv is None:
            self._observed, self._observed_rev = None, 0
        else:
            self._observed = LeaseRecord.decode(kv.value)
            self._observed_rev = kv.mod_revision

    def _try_write(self, record: LeaseRecord) -> bool:
        if self._observed_rev == 0:
            ok, rev, _ = self.store.cas(
                self.key, record.encode(), required_mod=0
            )
        else:
            ok, rev, _ = self.store.cas(
                self.key, record.encode(), required_mod=self._observed_rev
            )
        if ok:
            self._observed, self._observed_rev = record, rev
        else:
            self._observe()
        return ok

    # ---- public --------------------------------------------------------

    def tick(self, now: float) -> bool:
        """Advance the election; returns current leadership."""
        if self.is_leader:
            if now - self._observed.renew_time >= self.renew_period_s:
                renewed = self._try_write(
                    dataclasses.replace(self._observed, renew_time=now)
                )
                if not renewed:
                    # Someone stole the lease (we must have been expired).
                    log.warning("%s: lost leadership to %s", self.identity,
                                self._observed.holder if self._observed else "?")
                    self.is_leader = False
                    _IS_LEADER.set(0, identity=self.identity)
            return self.is_leader

        if now - self._last_attempt < self.retry_period_s:
            return False
        self._last_attempt = now
        self._observe()
        rec = self._observed
        expired = rec is None or not rec.holder or (
            now - rec.renew_time >= rec.lease_duration_s
        )
        if not expired and rec.holder != self.identity:
            return False
        acquired = self._try_write(
            LeaseRecord(
                holder=self.identity,
                acquire_time=now,
                renew_time=now,
                lease_duration_s=self.lease_duration_s,
                transitions=(rec.transitions + 1) if rec else 0,
            )
        )
        if acquired:
            self.is_leader = True
            _TRANSITIONS.inc(identity=self.identity)
            _IS_LEADER.set(1, identity=self.identity)
            log.info("%s: acquired leadership", self.identity)
        return self.is_leader

    def release(self) -> None:
        """Voluntarily give up the lease (clean shutdown handover)."""
        if not self.is_leader:
            return
        self.is_leader = False
        _IS_LEADER.set(0, identity=self.identity)
        self._try_write(dataclasses.replace(self._observed, holder=""))


class HACoordinator:
    """Leader-gated coordinator: standby until elected, step while leading.

    The coordinator's watches/table are built on acquisition and torn
    down (watches cancelled) on loss — state is soft, the store is
    authoritative.  ``make_coord`` builds a fresh Coordinator, so a
    re-election never reuses stale snapshot state from a previous reign.

    Webhook intake goes through ``submit_external`` on *this* object —
    a reign-stable sink.  While standby (or between reigns) admitted pods
    are dropped: their store writes arrive via the next leader's watch
    bootstrap, which is exactly the webhook-miss fallback path.
    """

    def __init__(self, elector: LeaderElector, make_coord):
        self.elector = elector
        self.make_coord = make_coord
        self.coord = None

    def submit_external(self, obj: dict, *, admitted: bool = False) -> None:
        """Reign-stable webhook sink: forwards to the current reign's
        coordinator; safe to wire into a long-lived WebhookServer.
        ``admitted`` passes through the webhook's already-ran-admission
        marker (see Coordinator.submit_external)."""
        coord = self.coord
        if coord is not None:
            coord.submit_external(obj, admitted=admitted)

    def tick(self, now: float) -> int:
        """Run one election step and (if leading) one scheduling cycle.
        Returns pods bound this tick."""
        was_leader = self.elector.is_leader
        leading = self.elector.tick(now)
        if leading and not was_leader:
            self.coord = self.make_coord()
            self.coord.bootstrap()
        elif not leading and was_leader:
            self.coord.close()
            self.coord = None
        if not leading:
            return 0
        return self.coord.step()

    def stop(self) -> None:
        self.elector.release()
        if self.coord is not None:
            self.coord.close()
            self.coord = None
