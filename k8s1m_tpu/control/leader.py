"""Store-backed leader election + fenced coordinator failover.

The reference elects a dist-scheduler leader through client-go's Lease
leaderelection (15s lease / 10s renew / 2s retry, reference
cmd/dist-scheduler/leader_activities.go:34-98); the leader runs the
webhook intake, node labeler, and webhook-Endpoints management, and a
replica that loses the lease steps down so a standby takes over.

Here the same contract runs against the native store: the election
object is a Lease under ``/registry/leases/<ns>/<name>`` and every
transition is a Txn CAS on its mod revision, so two candidates can never
both believe they acquired it (the store is the single arbiter exactly
as the apiserver+etcd pair is upstream).  Time is injected (``now``)
rather than read from the clock — elections are tick-driven like the
KWOK simulator, so failover paths are deterministically testable.

``HACoordinator`` pairs an elector with a Coordinator.  Three layers
make a scheduler kill boring (ISSUE 9):

- **Warm standby** (``warm_standby=True``): while NOT leading, the
  replica keeps a *mirror* coordinator following the node/pod watch
  stream — live host mirror, warmed encode cache, pre-compiled device
  step.  Takeover promotes the mirror with a bounded reconcile
  (``Coordinator.promote``: drain the watch backlog, then diff the
  mirror against the store pinned at the lease-acquire revision)
  instead of the cold list+decode+encode+compile boot, and
  ``failover_recovery_seconds{mode}`` records both paths so warm-vs-cold
  stays measurable.
- **Lease-epoch fencing**: every reign hands its coordinator a
  ``LeaseFence`` carrying the acquisition epoch (``leaseTransitions``).
  The coordinator's bind/evict/preempt store writes all flow through
  fenced helpers that consult the fence; once a standby's acquisition
  bumps the epoch (or the local lease expired), the deposed reign's
  in-flight waves drain to requeue — never to the store
  (``fencing_rejected_total{path}``).  The classic deposed-writer gap
  (SIGSTOP past lease expiry, clock-skewed renewals) is exercised by
  the faultline ``pause`` kind on the ``coordinator.lease`` hook.
- **Crash-consistent recovery**: derived state (queue, bound-pod
  ledger, ``_bind_meta``, gang staging) is reconstructed from store
  facts + watch/intake replay; ``Coordinator.recover_gangs`` settles
  gangs the predecessor left partially bound all-or-none.

Webhook intake during a no-leader window is queue-or-429: with a warm
standby the pod stages into the mirror (bounded) and schedules at
takeover; otherwise ``loadshed.Overloaded(reason="no-leader")`` maps to
HTTP 429 + Retry-After at the webhook.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time

from k8s1m_tpu import faultline
from k8s1m_tpu.control.objects import lease_key
from k8s1m_tpu.loadshed import Overloaded
from k8s1m_tpu.obs.metrics import Counter, Gauge, Histogram
from k8s1m_tpu.store.native import MemStore

log = logging.getLogger("k8s1m.leader")

_TRANSITIONS = Counter(
    "leader_transitions_total", "Leadership acquisitions", ("identity",)
)
_IS_LEADER = Gauge("leader_is_leader", "1 if this elector holds the lease",
                   ("identity",))
_TAKEOVERS = Counter(
    "failover_takeovers_total",
    "Coordinator takeovers, by standby mode (warm = promoted mirror, "
    "cold = fresh bootstrap)",
    ("mode",),
)
_RECOVERY = Histogram(
    "failover_recovery_seconds",
    "Lease acquisition to schedulable coordinator, by standby mode",
    ("mode",),
)


@dataclasses.dataclass
class LeaseRecord:
    holder: str
    acquire_time: float
    renew_time: float
    lease_duration_s: float
    transitions: int

    def encode(self) -> bytes:
        return json.dumps(
            {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "spec": {
                    "holderIdentity": self.holder,
                    "acquireTime": self.acquire_time,
                    "renewTime": self.renew_time,
                    "leaseDurationSeconds": self.lease_duration_s,
                    "leaseTransitions": self.transitions,
                },
            },
            separators=(",", ":"),
        ).encode()

    @classmethod
    def decode(cls, data: bytes) -> "LeaseRecord":
        spec = json.loads(data)["spec"]
        return cls(
            holder=spec["holderIdentity"],
            acquire_time=spec["acquireTime"],
            renew_time=spec["renewTime"],
            lease_duration_s=spec["leaseDurationSeconds"],
            transitions=spec.get("leaseTransitions", 0),
        )


class LeaderElector:
    """One candidate's view of a named election.

    Call ``tick(now)`` at least every ``retry_period_s``; it returns True
    while this candidate holds the lease.  Semantics mirror client-go:
    - acquire when the lease is absent, expired, or already ours;
    - renew every ``renew_period_s`` via CAS on the observed revision;
    - a failed CAS (someone else wrote) re-reads and backs off;
    - ``release()`` clears holderIdentity for fast handover on clean
      shutdown (leader_activities.go clears the webhook Endpoints the
      same way).

    Every acquisition (including re-acquiring our own lease after a
    restart) bumps ``leaseTransitions``, so the transitions counter is a
    monotone *epoch*: a write fenced on the acquisition epoch can never
    be mistaken for a later reign's (see ``LeaseFence``).
    """

    def __init__(
        self,
        store: MemStore,
        identity: str,
        *,
        name: str = "dist-scheduler-tpu",
        namespace: str = "kube-system",
        lease_duration_s: float = 15.0,
        renew_period_s: float = 10.0,
        retry_period_s: float = 2.0,
    ):
        self.store = store
        self.identity = identity
        self.key = lease_key(namespace, name)
        self.lease_duration_s = lease_duration_s
        self.renew_period_s = renew_period_s
        self.retry_period_s = retry_period_s
        self.is_leader = False
        self._observed_rev = 0
        self._observed: LeaseRecord | None = None
        self._last_attempt = -1e18
        # Injected-clock bookkeeping for the fence: the most recent
        # ``now`` this elector was ticked with (NOT wall time), and the
        # store revision at which the current reign's lease CAS landed.
        self.last_now = -1e18
        self.acquire_revision = 0

    # ---- internals -----------------------------------------------------

    def _observe(self) -> None:
        kv = self.store.get(self.key)
        if kv is None:
            self._observed, self._observed_rev = None, 0
        else:
            self._observed = LeaseRecord.decode(kv.value)
            self._observed_rev = kv.mod_revision

    def _try_write(self, record: LeaseRecord) -> bool:
        if self._observed_rev == 0:
            ok, rev, _ = self.store.cas(
                self.key, record.encode(), required_mod=0
            )
        else:
            ok, rev, _ = self.store.cas(
                self.key, record.encode(), required_mod=self._observed_rev
            )
        if ok:
            self._observed, self._observed_rev = record, rev
        else:
            self._observe()
        return ok

    # ---- public --------------------------------------------------------

    def tick(self, now: float) -> bool:
        """Advance the election; returns current leadership."""
        self.last_now = now
        if self.is_leader:
            if now - self._observed.renew_time >= self.renew_period_s:
                renewed = self._try_write(
                    dataclasses.replace(self._observed, renew_time=now)
                )
                if not renewed:
                    # Someone stole the lease (we must have been expired).
                    log.warning("%s: lost leadership to %s", self.identity,
                                self._observed.holder if self._observed else "?")
                    self.is_leader = False
                    _IS_LEADER.set(0, identity=self.identity)
            return self.is_leader

        if now - self._last_attempt < self.retry_period_s:
            return False
        self._last_attempt = now
        self._observe()
        rec = self._observed
        expired = rec is None or not rec.holder or (
            now - rec.renew_time >= rec.lease_duration_s
        )
        if not expired and rec.holder != self.identity:
            return False
        acquired = self._try_write(
            LeaseRecord(
                holder=self.identity,
                acquire_time=now,
                renew_time=now,
                lease_duration_s=self.lease_duration_s,
                transitions=(rec.transitions + 1) if rec else 0,
            )
        )
        if acquired:
            self.is_leader = True
            self.acquire_revision = self._observed_rev
            _TRANSITIONS.inc(identity=self.identity)
            _IS_LEADER.set(1, identity=self.identity)
            log.info("%s: acquired leadership (epoch %d)", self.identity,
                     self._observed.transitions)
        return self.is_leader

    def release(self) -> None:
        """Voluntarily give up the lease (clean shutdown handover)."""
        if not self.is_leader:
            return
        self.is_leader = False
        _IS_LEADER.set(0, identity=self.identity)
        self._try_write(dataclasses.replace(self._observed, holder=""))

    def step_down(self) -> None:
        """Local-only stepdown: stop believing leadership WITHOUT
        touching the store — the SIGKILL emulation (a dead process
        cannot release; the lease expires on its own and a standby
        takes over on the crash path)."""
        self.is_leader = False
        _IS_LEADER.set(0, identity=self.identity)

    def current_epoch(self) -> int:
        """The reign's fencing epoch (``leaseTransitions`` of our own
        acquisition); -1 while not leading."""
        if not self.is_leader or self._observed is None:
            return -1
        return self._observed.transitions

    def locally_expired(self) -> bool:
        """True when, by this elector's OWN injected clock, the lease
        duration has elapsed since the last observed renewal — the
        fast local half of the fence (a paused replica whose clock
        stopped is caught by the store check instead)."""
        return (
            self._observed is not None
            and self.last_now - self._observed.renew_time
            >= self.lease_duration_s
        )

    def fence(self) -> "LeaseFence":
        """The fencing token for the CURRENT reign (call at takeover)."""
        return LeaseFence(self, self.current_epoch())


class LeaseFence:
    """Lease-epoch fencing token for one reign (ISSUE 9).

    ``admit()`` gates every bind/evict/preempt store write the
    coordinator retires.  Two checks compose:

    - the LOCAL elector view — refusal is immediate once the elector
      stepped down, a different reign's epoch took over, or the lease
      expired by our own injected clock;
    - the STORE lease record — the single arbiter.  A deposed leader
      whose clock is paused/skewed still believes its local view; the
      store read sees the standby's acquisition (a newer
      ``leaseTransitions``) and refuses the write.  This closes the
      classic fencing-token gap: in-flight waves of a deposed reign
      drain to requeue, never to the store.

    The residual window of any read-then-write fence (an admit that
    races the standby's acquisition CAS) is documented in README
    "Coordinator failover & fencing"; the store-side pod CAS still
    prevents double-binds of a single pod in that window.
    """

    def __init__(self, elector: LeaderElector, epoch: int):
        self.elector = elector
        self.epoch = epoch

    def admit(self) -> bool:
        e = self.elector
        if not e.is_leader or e.current_epoch() != self.epoch:
            return False
        if e.locally_expired():
            return False
        kv = e.store.get(e.key)
        if kv is None:
            return False
        rec = LeaseRecord.decode(kv.value)
        return rec.holder == e.identity and rec.transitions == self.epoch


class HACoordinator:
    """Leader-gated coordinator: standby until elected, step while leading.

    ``make_coord`` builds a fresh Coordinator; with ``warm_standby`` the
    replica keeps one FOLLOWING while not leading (live host mirror,
    warmed caches, pre-compiled step — ``Coordinator.follow``) and
    promotes it at takeover; without, takeover cold-boots.  Either way
    the new reign is handed a ``LeaseFence`` so a deposed predecessor's
    writes can never land behind it, and ``recover_gangs`` settles
    crash-split gangs all-or-none.

    Webhook intake goes through ``submit_external`` on *this* object —
    a reign-stable sink.  During a no-leader window it is queue-or-429:
    queue into the standby mirror while it has room, else raise
    ``loadshed.Overloaded(reason="no-leader")`` (the webhook maps it to
    HTTP 429 + Retry-After).

    The ``coordinator.lease`` faultline hook (op ``tick/<identity>``)
    fires at the top of ``tick``: kind ``kill_process`` emulates SIGKILL
    (``kill()`` — no lease release, no flush; takeover happens on lease
    expiry), kind ``pause`` emulates SIGSTOP *between the leadership
    check and the reign's writes* — the fencing gap's worst case.  The
    drill installs ``on_pause`` to advance the rest of the world
    deterministically while this replica is frozen.
    """

    def __init__(
        self,
        elector: LeaderElector,
        make_coord,
        *,
        warm_standby: bool = False,
        standby_queue_cap: int = 100_000,
    ):
        self.elector = elector
        self.make_coord = make_coord
        self.warm_standby = warm_standby
        self.standby_queue_cap = standby_queue_cap
        self.coord = None
        self._mirror = None
        self._killed = False
        # Pods staged into the standby mirror during the current
        # no-leader window (webhook threads increment under the lock;
        # reset when a reign starts or a fresh mirror is built) — the
        # queue-or-429 bound without a cross-thread read of the
        # mirror's cycle-owned queue.
        self._staged_lock = threading.Lock()
        self._standby_staged = 0
        # Drill hook: called instead of time.sleep on an injected pause
        # so single-threaded tick-driven drills can advance the other
        # replicas while this one is "stopped".
        self.on_pause = None
        # Takeover evidence for drivers (failover_drill reads these).
        self.takeover_mode: str | None = None
        self.last_recovery_s: float | None = None
        self.last_promote_stats: dict | None = None

    def submit_external(self, obj: dict, *, admitted: bool = False) -> None:
        """Reign-stable webhook sink; queue-or-429 during no-leader
        windows.  ``admitted`` passes through the webhook's
        already-ran-admission marker (see Coordinator.submit_external)."""
        coord = self.coord
        if coord is not None:
            coord.submit_external(obj, admitted=admitted)
            return
        mirror = self._mirror
        if mirror is not None:
            # Warm standby: stage into the mirror (it schedules the
            # backlog at takeover; the store watch remains the dedup'd
            # fallback intake).  Bounded — a leaderless window must not
            # buffer unbounded demand — and ``admitted`` passes THROUGH:
            # a pod that has not drawn its admission decision draws it
            # from the mirror's tenancy/loadshed chain (follow() keeps
            # the buckets ticking), so an over-share tenant cannot use
            # a failover window to bypass weighted-fair admission.
            with self._staged_lock:
                if self._standby_staged >= self.standby_queue_cap:
                    raise Overloaded(
                        self.elector.retry_period_s, reason="no-leader"
                    )
                self._standby_staged += 1
            try:
                mirror.submit_external(obj, admitted=admitted)
            except BaseException:
                with self._staged_lock:
                    self._standby_staged -= 1
                raise
            return
        raise Overloaded(self.elector.lease_duration_s, reason="no-leader")

    def tick(self, now: float) -> int:
        """Run one election step and (if leading) one scheduling cycle.
        Returns pods bound this tick."""
        if self._killed:
            return 0
        d = faultline.decide(
            "coordinator.lease", "tick/" + self.elector.identity
        )
        if d is not None and d.kind == "kill_process":
            self.kill()
            return 0
        was_leader = self.elector.is_leader
        leading = self.elector.tick(now)
        if d is not None and d.kind in ("pause", "delay"):
            # SIGSTOP-style freeze AFTER the leadership check and BEFORE
            # any scheduling write: the world moves on (a standby can
            # steal the expired lease) while this replica still believes
            # its pre-pause election observation.  The fence is what
            # keeps its writes out of the store when it resumes.
            if self.on_pause is not None:
                self.on_pause(d)
            else:
                time.sleep(d.delay_s)
        if leading and not was_leader:
            self._become_leader()
        elif not leading and was_leader:
            self._depose()
        if not leading:
            if self.warm_standby:
                self._standby_tick()
            return 0
        return self.coord.step()

    # ---- transitions ---------------------------------------------------

    def _become_leader(self) -> None:
        t0 = time.perf_counter()
        fence = self.elector.fence()
        mirror, self._mirror = self._mirror, None
        with self._staged_lock:
            self._standby_staged = 0
        if mirror is not None:
            mode = "warm"
            mirror.fence = fence
            self.last_promote_stats = mirror.promote(
                acquire_revision=self.elector.acquire_revision
            )
            self.coord = mirror
        else:
            mode = "cold"
            coord = self.make_coord()
            coord.fence = fence
            coord.bootstrap()
            coord.recover_gangs()
            self.last_promote_stats = None
            self.coord = coord
        self.last_recovery_s = time.perf_counter() - t0
        self.takeover_mode = mode
        _TAKEOVERS.inc(mode=mode)
        _RECOVERY.observe(self.last_recovery_s, mode=mode)
        log.info(
            "%s: takeover (%s) in %.3fs", self.elector.identity, mode,
            self.last_recovery_s,
        )

    def _depose(self) -> None:
        coord, self.coord = self.coord, None
        if coord is None:
            return
        try:
            # Deposed: retire the pipeline THROUGH the fence — every
            # in-flight wave's binds are refused (fencing_rejected_total)
            # and its pods drain to requeue, never to the store.
            coord.flush()
        finally:
            coord.close()

    def _standby_tick(self) -> None:
        if self._mirror is None:
            m = self.make_coord()
            m._follower = True
            m.bootstrap()
            with self._staged_lock:
                self._standby_staged = 0
            self._mirror = m
        self._mirror.follow()

    # ---- lifecycle -----------------------------------------------------

    def kill(self) -> None:
        """SIGKILL emulation (faultline kind ``kill_process``): the
        lease is NOT released (a dead process cannot), nothing is
        flushed — in-flight waves die with the process and their pods
        stay pending in the store for the next leader.  Watches are
        cancelled the way a dead process's connections are reaped."""
        self._killed = True
        self.elector.step_down()
        for c in (self.coord, self._mirror):
            if c is not None:
                c.close()
        self.coord = self._mirror = None
        log.warning("%s: killed (lease left to expire)",
                    self.elector.identity)

    def stop(self) -> None:
        """Clean shutdown: retire in-flight work while the lease is
        still ours, then release for fast handover."""
        if self.coord is not None:
            self.coord.flush()
        self.elector.release()
        if self.coord is not None:
            self.coord.close()
            self.coord = None
        if self._mirror is not None:
            self._mirror.close()
            self._mirror = None
