"""The host coordinator: store watches -> snapshot deltas -> TPU cycle -> binds.

This is the process the reference runs as dist-scheduler (289 replicas of
it): watch nodes and pods, keep a node cache current, schedule pending
pods, write binds back (reference SURVEY.md §3.2).  Here one coordinator
drives the whole cluster:

- **Intake** — a store watch on /registry/pods/ replaces both intake paths
  of the reference (the ValidatingWebhook and the fieldSelector pod watch,
  reference pkg/webhook/webhook.go:71-126, cmd/dist-scheduler/pod_watcher.go:20-71):
  every Pending pod with schedulerName=dist-scheduler enters the queue.
- **Node cache** — a watch on /registry/minions/ streams adds/updates/
  removes into NodeTableHost and scatters compiled rows to the device
  table (the informer-cache equivalent, reference scheduler.go:201-219).
  Bound-pod resource accounting is folded in the same way a scheduler
  cache assumes pods.
- **Cycle** — pending pods are drained in batches of PodSpec.batch, padded,
  encoded, and run through engine.schedule_batch; winners are written back
  as spec.nodeName via Txn CAS on the pod's mod revision — the optimistic
  concurrency of the reference's DefaultBinder (conflict -> pod re-queued,
  reference README.adoc:558-560).
- **Ordering** — watch events are applied in revision order (the native
  store's watch dispatch is revision-ordered by construction, like
  mem_etcd's notify thread, reference store.rs:444-533), and binds are
  CAS-guarded, so a concurrent pod update between intake and bind loses
  nothing: the CAS fails and the newer pod revision re-enters via watch.

A pod whose bind CAS fails or that finds no feasible node is re-queued
under the ``coordinator.bind`` RetryPolicy (k8s1m_tpu/faultline/policy.py):
capped exponential backoff with jitter, then parked as unschedulable
after ``max_attempts`` tries (the reference admits first-attempt failures
are not reliably retried, reference RUNNING.adoc:206 — this does better).
Backoff means a CAS-conflict storm surfaces as queue backpressure (pods
waiting out their delay) instead of the same pods tight-looping through
every consecutive wave.  The bind and watch-drain paths are faultline
injection hooks (components ``coordinator.bind`` / ``coordinator.watch``),
so conflict storms and watch loss are reproducible by seed.

**Overload control** (k8s1m_tpu/loadshed, opt-in via the ``loadshed`` /
``breaker`` constructor args): a HealthController ticked once per cycle
turns queue/backoff depth, conflict rate, cycle latency and resyncs
into HEALTHY/DEGRADED/SHEDDING; DEGRADED shrinks the score window and
drops constraint *scoring* (filtering always stays) and widens batch
windows, SHEDDING additionally makes ``submit_external`` reject
lowest-priority pods first; a CircuitBreaker around device dispatch
falls back to the host-side oracle scheduler while open, so scheduling
never fully stops (see tools/overload_drill.py for the drill that
proves all of it).

**Snapshot epochs & quiesce-free pipelining**: node churn no longer
retires the pipeline.  Node events classify at the row level
(_drain_node_events): capacity-only updates scatter feature columns
into the live device table between in-flight waves, structural adds
append fresh rows, and removes tombstone their row into a wave-epoch
quarantine (snapshot/node_table.py) so no in-flight wave can alias a
reused row; a wave that retires onto a tombstoned row retries the pod
like a CAS conflict.  The pipeline quiesces only for resync, a tripped
breaker, adaptive partial buckets, or quarantine exhaustion —
``pipeline_quiesce_total{reason}`` counts each, and under pure
capacity churn the structural reason stays 0 (tier-1 asserted via
``sched_bench --node-churn``).

**Host feed & encode cache** (snapshot/hotfeed.py): every encoder this
coordinator owns shares one shape-keyed template cache (invalidated by
``Vocab.generation()``), so batches full of shape-sharing pods fill in
vectorized per-shape writes rather than per-pod Python; with
``hotfeed`` on (default: follows ``pipeline``) a worker thread encodes
the NEXT full batch while the current wave is in flight and the
dispatch claims the pre-staged ``PackedPodBatch`` — discarded, never
trusted, if the queue prefix or the vocab generation moved
(``hotfeed_stale_batches_total{reason}``).  The degraded loadshed path
and ``_process_adjusts`` re-encodes ride the same cache, so CAS-
rollback storms re-encode against warm templates.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import heapq
import json
import logging
import os
import random
import threading
import time
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from k8s1m_tpu import faultline
from k8s1m_tpu.config import DEFAULT_SCHEDULER, PodSpec, TableSpec
from k8s1m_tpu.faultline import RetryPolicy, note_give_up, note_retry, policy_for
from k8s1m_tpu.lint import THREAD_OWNER, guarded_by, racy_read
from k8s1m_tpu.control.objects import (
    decode_node,
    decode_pod,
    decode_pod_obj,
    node_key,
    pod_key,
    pod_key_str_of_obj,
)
from k8s1m_tpu.engine.cycle import (
    Wave,
    adjust_constraints,
    adjust_constraints_impl,
    commit_fields_np,
    fill_shape_planes,
    sample_offset_for,
    sample_rows_for,
    schedule_batch_delta,
    schedule_batch_packed,
)
from k8s1m_tpu.engine.deltacache import (
    DeltaPlaneCache,
    note_index_oversized,
    note_index_wave,
    resolve_deltasched,
)
from k8s1m_tpu.loadshed import CircuitBreaker, HealthController, Signals
from k8s1m_tpu.loadshed import CLOSED as BREAKER_CLOSED
from k8s1m_tpu.loadshed.breaker import FALLBACK_BINDS
from k8s1m_tpu.obs.metrics import Counter, Gauge, Histogram, LevelTimer
from k8s1m_tpu.obs.podtrace import NULL_TRACER
from k8s1m_tpu.obs.trace import FlightRecorder
from k8s1m_tpu.ops.priority import pod_priority_of
from k8s1m_tpu.oracle import oracle_feasible, oracle_score
from k8s1m_tpu.plugins.registry import Profile, degraded_profile
from k8s1m_tpu.snapshot.constraints import ConstraintTracker, empty_constraints
from k8s1m_tpu.snapshot.hotfeed import (
    PLAIN,
    EncodeCache,
    HostFeed,
    HotPodBatchHost,
    ShardedHostFeed,
    cache_counts,
    encode_batch,
    shape_key,
)
from k8s1m_tpu.snapshot.node_table import (
    ALL_COLUMNS,
    CAP_COLUMNS,
    NodeTableHost,
    RowsExhausted,
    scatter_rows,
)
from k8s1m_tpu.snapshot.packing import (
    PackingOverflow,
    build_packing_spec,
    donation_inplace,
    donation_probe,
    hbm_bytes,
    is_packed,
    pack_row_delta,
    pack_table_host,
    resolve_packing,
)
from k8s1m_tpu.snapshot.pod_encoding import PodBatchHost, PodInfo
from k8s1m_tpu.tenancy.gang import note_gang
from k8s1m_tpu.tenancy.policy import (
    gang_of_labels,
    tenant_of_key,
    tenant_of_obj,
    tenant_of_pod,
)
from k8s1m_tpu.tenancy.preempt import (
    Victim,
    note_eviction,
    select_preemption,
)
from k8s1m_tpu.snapshot.bulkload import BulkNodeLoader
from k8s1m_tpu.store.native import (
    BIND_INVALID,
    POD_CANONICAL,
    POD_HAS_NODE,
    POD_SCHED_MATCH,
    CompactedError,
    FutureRevError,
    MemStore,
    Watcher,
    drain_events_light,
    list_prefix,
    list_prefix_sharded,
    list_prefix_values,
    prefix_end,
)

log = logging.getLogger("k8s1m.coordinator")

NODES_PREFIX = b"/registry/minions/"
# Tick-driven consumers drain once per cycle, so the watch queue must
# absorb a full inter-cycle burst (creates + deletes + bind echoes);
# the native default of 10K (reference store.rs:27) assumes a
# continuously-draining consumer.
DEEP_WATCH_QUEUE = 1 << 20
PODS_PREFIX = b"/registry/pods/"

_PODS_SCHEDULED = Counter(
    "coordinator_pods_scheduled_total", "Pods bound, by outcome", ("outcome",)
)
_DECODE_ERRORS = Counter(
    "coordinator_decode_errors_total", "Objects that failed to decode", ("kind",)
)
_CYCLE_TIME = Histogram(
    "coordinator_cycle_seconds", "Scheduling cycle latency by stage", ("stage",)
)
_QUEUE_DEPTH = Gauge("coordinator_queue_depth", "Pending pods queued", ())
_BACKOFF_DEPTH = Gauge(
    "coordinator_backoff_depth",
    "Pods waiting out a retry backoff (conflict-storm backpressure)", (),
)
_RESYNCS = Counter(
    "coordinator_resyncs_total", "Full relist+rewatch recoveries", ()
)
_NODE_COUNT = Gauge("coordinator_node_count", "Nodes in the snapshot", ())
_COLD_BUILD = Gauge(
    "megarow_cold_build_seconds",
    "Wall seconds of the last store->watch->table cold build "
    "(bootstrap's node relist + bulk ingest + device table build) — "
    "a first-class metric so a 1M-row build is a number, not a silent "
    "multi-minute stall", (),
)
# All live coordinators in this process; gauges aggregate over them so a
# discarded instance neither pins memory nor clobbers the live one's stats.
# Scrape-thread reads of cycle-thread-owned state go through racy_read:
# a deliberate, audited-as-exempt torn-snapshot read (a monitoring len()
# must neither block on the cycle nor count as a discipline violation).
# Follower mirrors (warm standby, control/leader.py) shadow the leader's
# whole intake — summing them would double every depth, so the
# aggregates skip them; the standby's own health is standby_mirror_lag.
_LIVE: weakref.WeakSet = weakref.WeakSet()


def _live_primaries():
    return (c for c in _LIVE if not racy_read(c, "_follower"))


_NODE_COUNT.set_function(
    lambda: sum(len(racy_read(c.host, "_row_of")) for c in _live_primaries())
)
_QUEUE_DEPTH.set_function(
    lambda: sum(len(racy_read(c, "queue")) for c in _live_primaries())
)
_BACKOFF_DEPTH.set_function(
    lambda: sum(len(racy_read(c, "_backoff")) for c in _live_primaries())
)

_PIPE_QUIESCE = Counter(
    "pipeline_quiesce_total",
    "Forced full pipeline retires, by reason (capacity-only node churn "
    "never quiesces; structural = free-row quarantine exhausted)",
    ("reason",),
)
_PIPE_DEPTH = Gauge(
    "pipeline_inflight_depth", "Device waves currently in flight", ()
)
_PIPE_DEPTH.set_function(
    lambda: sum(len(racy_read(c, "_inflights")) for c in _LIVE)
)
_PIPE_OVERLAP = Counter(
    "pipeline_stage_overlap_seconds_total",
    "Host-stage seconds split by whether device waves were in flight "
    "(inflight=yes means the stage's cost hid behind device work)",
    ("stage", "inflight"),
)
# Stages instrumented with the overlap split (drives the bench's
# overlap-ratio report; keep in sync with _stage call sites).
_OVERLAP_STAGES = ("drain", "encode", "sync", "sync_out", "bind")

# ---- mesh execution (parallel/): the dp x sp sharded cycle ------------
_MESH_DEVICES = Gauge(
    "mesh_devices",
    "Devices along each mesh axis across live mesh coordinators "
    "(0 = every coordinator runs single-device)",
    ("axis",),
)
for _axis in ("dp", "sp"):
    _MESH_DEVICES.set_function(
        lambda _a=_axis: sum(
            c.mesh.shape[_a] for c in _LIVE if c.mesh is not None
        ),
        axis=_axis,
    )
_MESH_SCATTER = Counter(
    "mesh_sharded_scatter_total",
    "Dirty-row scatters dispatched against the sp-sharded device table, "
    "by column class (full = host-authoritative row re-upload, cap = "
    "capacity/feature columns only) — each one lands mid-flight with no "
    "quiesce and no reshard (make_sharded_scatter pins the row sharding)",
    ("cols",),
)
_MESH_FEED_DEPTH = Gauge(
    "mesh_feed_staged_depth",
    "Batches staged or encoding across per-dp-shard host feeds "
    "(snapshot/hotfeed.ShardedHostFeed; up to dp per mesh coordinator)",
    (),
)
_MESH_FEED_DEPTH.set_function(
    lambda: sum(
        c._feed.depth() for c in _LIVE
        if isinstance(getattr(c, "_feed", None), ShardedHostFeed)
    )
)

# ---- device memory (devicestate): packed snapshot + donation evidence --
_TABLE_BYTES = Gauge(
    "device_table_bytes",
    "HBM bytes of the device node table by layout (snapshot/packing.py; "
    "the packed production layout holds the cold columns bit/byte-packed "
    "so more nodes fit per chip)",
    ("layout",),
)
_DONATION = Counter(
    "commit_donation_total",
    "Per-wave table commits through the donating executable, split by "
    "whether the runtime honored the donation in place (inplace=no means "
    "the buffers were copied — e.g. another live reference pinned them)",
    ("inplace",),
)
_PACKING_FALLBACK = Counter(
    "device_packing_fallback_total",
    "Fail-closed packed-layout rebuilds, by reason (the field that "
    "overflowed its static bit budget — vocab drift — or 'taint_slots' "
    "for a spec the meta word cannot hold); the coordinator widens the "
    "layout ONCE, host-side and mesh-global, never truncates and never "
    "decides per-shard",
    ("reason",),
)

# ---- failover (ISSUE 9): fencing + warm-standby evidence ---------------
_FENCE_REJECTED = Counter(
    "fencing_rejected_total",
    "Store writes refused by the lease-epoch fence, by path — a deposed "
    "or paused reign's in-flight waves draining to requeue instead of "
    "the store (control/leader.LeaseFence)",
    ("path",),
)
_MIRROR_LAG = Gauge(
    "standby_mirror_lag_rows",
    "Watch events the warm-standby mirror had not yet applied at its "
    "last follow tick (0 = the mirror tracks the store tick-for-tick; "
    "bounds the takeover reconcile)",
    (),
)
_RECONCILE_REPAIRS = Counter(
    "failover_reconcile_repairs_total",
    "Mirror-vs-store divergences repaired during takeover reconcile, by "
    "kind (normally 0: the watch stream already carried every fact)",
    ("kind",),
)

_BIND_LATENCY = Histogram(
    "coordinator_schedule_to_bind_seconds",
    "Intake-to-bind latency per pod",
    (),
    # Finer than the default pow2 ladder in the SLO range: the default's
    # 164ms -> 328ms jump makes a ~170ms p50 report as 328.
    buckets=(
        0.005, 0.01, 0.02, 0.03, 0.04, 0.05, 0.06, 0.07, 0.08, 0.09,
        0.1, 0.11, 0.13, 0.165, 0.2, 0.25, 0.33, 0.42, 0.55, 0.7, 0.9,
        1.2, 1.6, 2.1, 2.8, 3.7, 5.0, 8.0, 15.0, 30.0, 60.0,
    ),
)


@dataclasses.dataclass(slots=True)
class PendingPod:
    # None = native-intake fast lane: the pod is canonical and label-less
    # (store/native.py poll_pods parsed it in C), so the full PodInfo is
    # materialized only if a slow path actually needs it (ensure_pod).
    pod: PodInfo | None
    # None = webhook intake: the object wasn't persisted at admission
    # time, so the bind path resolves the live revision instead.
    mod_revision: int | None
    enqueued_at: float
    # Scheduling-relevant scalars, always populated (from the native
    # parse or from the PodInfo) so the hot bind path never touches pod.
    cpu_milli: int = 0
    mem_kib: int = 0
    key_str: str = ""        # "<ns>/<name>"
    attempts: int = 0
    # Raw stored bytes at intake revision — lets the bind CAS splice
    # nodeName into the bytes without a JSON decode/encode round trip.
    raw: bytes | None = None
    # Store key bytes, captured at intake so the bind wave never
    # re-formats /registry/pods/<ns>/<name> per pod.
    key_bytes: bytes = b""
    # Earliest perf_counter() time this pod may re-enter a batch after a
    # retry (RetryPolicy backoff; 0 = immediately eligible).
    not_before: float = 0.0
    # spec.priority — admission/preemption only (never encoded).  0 for
    # native fast-lane pods: the canonical label-less shape cannot carry
    # a priority, so the hot path needs no decode to know it.
    priority: int = 0
    # Gang membership (tenancy/gang.py): namespace-qualified gang id and
    # declared size; "" / 0 = not a gang pod.
    gang_id: str = ""
    gang_size: int = 0

    def peek_pod(self) -> PodInfo:
        """The PodInfo WITHOUT caching it on the record — the hotfeed
        worker's form (a peeked pod still belongs to the cycle thread's
        queue; assigning ``self.pod`` there would be a cross-thread
        write on shared state)."""
        if self.pod is not None:
            return self.pod
        ns, name = self.key_str.split("/", 1)
        return PodInfo(
            name=name, namespace=ns,
            cpu_milli=self.cpu_milli, mem_kib=self.mem_kib,
        )

    def ensure_pod(self) -> PodInfo:
        if self.pod is None:
            self.pod = self.peek_pod()
        return self.pod


# Structural splice marker: encode_pod always opens spec with
# schedulerName, and this byte pattern cannot occur inside any JSON
# string literal (the quotes would be \"-escaped), so its first
# occurrence is the real spec object.
_SPEC_MARK = b'"spec":{"schedulerName":'


def splice_node_name(raw: bytes, node_name: str) -> bytes | None:
    """Insert spec.nodeName into encoded pod bytes; None if the object
    isn't in our canonical shape (caller falls back to the JSON path)."""
    idx = raw.find(_SPEC_MARK)
    if idx < 0 or b'"nodeName"' in raw:
        return None
    cut = idx + 8  # len(b'"spec":{')
    return b'%s"nodeName":%s,%s' % (
        raw[:cut], json.dumps(node_name).encode(), raw[cut:]
    )


_UNSPLICE_MARK = b'"spec":{"nodeName":"'


def unsplice_node_name(raw: bytes) -> bytes | None:
    """Inverse of ``splice_node_name``: remove the spliced spec.nodeName,
    restoring the pre-bind bytes EXACTLY — the eviction path's byte-
    identity half (an evicted pod's stored object equals its pre-bind
    encoding, so evict+rebind replays are bytewise checkable).  None if
    the object isn't in the spliced canonical shape (escaped name,
    nodeName written elsewhere) — the caller falls back to the JSON
    path."""
    idx = raw.find(_UNSPLICE_MARK)
    if idx < 0:
        return None
    start = idx + 8                    # keep b'"spec":{'
    i = idx + len(_UNSPLICE_MARK)      # first byte of the name
    j = raw.find(b'"', i)
    if j < 0 or raw[j + 1 : j + 2] != b"," or b"\\" in raw[i:j]:
        return None
    return raw[:start] + raw[j + 2:]


class _VictimRows:
    """Row-keyed view over the coordinator's incremental by-node victim
    index — the ``victims_by_row`` mapping ``select_preemption``
    consumes, built per wave in O(nodes-with-victims) instead of the
    old O(bound pods) ledger scan.

    Only the row -> node-name resolution is materialized up front
    (ints; victims whose node left the snapshot drop out exactly like
    the old scan's ``row_of.get``).  ``get`` reads the live per-node
    dict fresh on every call, so evictions during the same wave
    (``_evict_bound`` pops the index) are visible to later preemptors
    with no manual bookkeeping; rows are patched into the returned
    Victims for the replay log's benefit.
    """

    __slots__ = ("_by_node", "_name_at", "_max_seq")

    def __init__(self, by_node: dict, row_of: dict, max_seq: int) -> None:
        self._by_node = by_node
        self._name_at = {
            row_of[name]: name for name in by_node if name in row_of
        }
        # Bind-sequence fence: only pods bound BEFORE this view was
        # built are victims.  Without it, a preemptor's own host-side
        # bind (inserted live into the by-node index) would be visible
        # to later preemptors of the SAME wave — same-wave eviction
        # thrash the old snapshot index structurally excluded.
        self._max_seq = max_seq

    def get(self, row: int, default=()):
        name = self._name_at.get(row)
        if name is None:
            return default
        d = self._by_node.get(name)
        if not d:
            return default
        out = [
            dataclasses.replace(v, row=row)
            for v in d.values() if v.seq <= self._max_seq
        ]
        return out or default

    def items(self):
        """Materialized (row, victims) pairs — the replay-log dump."""
        return [(row, self.get(row)) for row in sorted(self._name_at)]

    def values(self):
        return [vs for _row, vs in self.items()]

    def __eq__(self, other):
        # Dict-shaped for consumers (and tests) that compare against
        # the materialized per-row index.
        if isinstance(other, (dict, _VictimRows)):
            return dict(self.items()) == (
                other if isinstance(other, dict) else dict(other.items())
            )
        return NotImplemented

    __hash__ = None


@guarded_by(
    # Webhook-thread <-> cycle-thread boundary: the staging list is the
    # ONLY coordinator state server threads may touch, and only under
    # its lock (lint/guards.py; audited by tests/test_guard_stress.py).
    _external="_external_lock",
    # Cycle-thread-confined state: the wave pipeline, the backoff heap,
    # the pod queue and the dirty-row sets all belong to whichever
    # thread drives step()/flush() — never to a server thread.
    _inflights=THREAD_OWNER,
    _backoff=THREAD_OWNER,
    queue=THREAD_OWNER,
    _queued_keys=THREAD_OWNER,
    _dirty_rows=THREAD_OWNER,
    _dirty_caps=THREAD_OWNER,
    _midflight_rows=THREAD_OWNER,
    # Tenancy state (gang staging/parking, per-bind priority metadata):
    # cycle-thread-owned like the queue it feeds.
    _gang_staging=THREAD_OWNER,
    _gang_parked=THREAD_OWNER,
    _bind_meta=THREAD_OWNER,
    # The incremental preemption-victims index mirrors _bound/_bind_meta
    # (same insert/delete sites, same cycle-thread confinement).
    _victims_by_node=THREAD_OWNER,
    # The incremental fallback NodeInfo index is maintained at the node
    # watch-drain sites (cycle-thread) and read by _fallback_nodes.
    _node_infos=THREAD_OWNER,
    _trace_gaveup=THREAD_OWNER,
)
class Coordinator:
    """Single-process scheduling coordinator over an in-process store."""

    def __init__(
        self,
        store: MemStore,
        table_spec: TableSpec,
        pod_spec: PodSpec,
        profile: Profile,
        *,
        chunk: int = 16384,
        k: int = 4,
        with_constraints: bool = True,
        max_attempts: int = 5,
        retry_policy: RetryPolicy | None = None,
        scheduler_name: str = DEFAULT_SCHEDULER,
        seed: int = 0,
        flight_recorder: FlightRecorder | None = None,
        # Sampling profiler (obs/profiler.py) to dump alongside a slow-
        # cycle flight dump — the reference's always-answerable "where
        # did the time go" (parca-agent.tf, scheduler_metrics.go:68-74).
        profiler=None,
        # Per-pod lifecycle tracing (obs/podtrace.py): a PodTracer
        # head-samples 1-in-N pods (deterministic by pod-key hash) and
        # records their whole journey as a contiguous span chain —
        # admit, gang staging, queue wait, encode (cache attrs),
        # dispatch wait, device (wave epoch / depth / delta-vs-full),
        # bind CAS incl. retries, preemption/eviction, failover
        # requeue.  None (the default) installs the null tracer: every
        # emit site is behind a single ``enabled`` read, so tracing off
        # is free (enforced by the trace-lazy-emit lint pass).  A pod
        # whose schedule-to-bind exceeds the flight recorder's
        # threshold dumps the ring WITH its span chain attached.
        tracer=None,
        backend: str = "xla",
        pipeline: bool = False,
        depth: int = 2,
        adaptive_batch: bool = False,
        watch_queue_cap: int = DEEP_WATCH_QUEUE,
        score_pct: int = 100,
        intake_filter=None,
        mesh=None,
        # Overload control (k8s1m_tpu/loadshed): a HealthController makes
        # submit_external shed past its watermarks and degrades the cycle
        # (smaller score window, filter-only constraint plugins, widened
        # batch windows) while pressure lasts; a CircuitBreaker guards
        # device dispatch and falls back to the host-side oracle
        # scheduler while open.  None (the default) = none of that runs.
        loadshed: HealthController | None = None,
        breaker: CircuitBreaker | None = None,
        # Tenancy (k8s1m_tpu/tenancy.TenancyController): weighted-fair
        # per-tenant admission at submit_external (replacing loadshed's
        # global priority floor), priority preemption (evict + requeue
        # lower-priority bound pods when a high-priority pod finds no
        # feasible row), and all-or-none gang scheduling.  When set
        # without an explicit ``loadshed``, its HealthController is
        # adopted as the loadshed controller too — one state machine
        # drives degraded knobs and per-tenant gates.
        tenancy=None,
        # Host feed (snapshot/hotfeed.py): encode batch N+1 in a worker
        # thread while batch N's wave is in flight, so encode_packed
        # leaves the cycle's serial section whenever the queue is deep
        # enough to stage a full batch ahead.  None = follow `pipeline`
        # (the overlap only pays when waves overlap host work).  The
        # shape-keyed encode CACHE is always on — it is byte-identical
        # to the uncached encode by construction (tests/test_hotfeed.py).
        hotfeed: bool | None = None,
        # Lease-epoch fencing token (control/leader.LeaseFence): when
        # set, every bind/evict/preempt store write flows through the
        # fenced helpers and is refused once the reign is deposed —
        # in-flight waves drain to requeue, never to the store.  None
        # (standalone coordinators, tests) = writes always admitted.
        fence=None,
        # Device-snapshot layout (snapshot/packing.py): "packed" holds
        # the cold node-table columns bit/byte-packed in HBM (labels
        # fused, taint effects + validity in one meta word, narrow
        # zone/region/pods planes) and decodes per chunk on device —
        # byte-identical binds, >=2x less cold-column HBM.  None defers
        # to the K8S1M_PACKING env var ("off" default).  Fail-closed:
        # vocab drift past the static bit budget rebuilds under a wider
        # layout (device_packing_fallback_total) — the widening decision
        # is made ONCE on the host, so a mesh coordinator never diverges
        # per-shard.  Composes with ``mesh`` (meshpack): the packed
        # planes shard over sp like the plain columns and decode inside
        # the shard-local chunk slice.
        packing: str | None = None,
        # Incremental scheduling (engine/deltacache.py): cache each pod
        # shape's feasibility/score plane in HBM and run the full
        # filter+score kernel only over dirty rows ∪ in-flight bind
        # rows when every shape in a wave hits — byte-identical binds,
        # O(batch × dirty) steady-state device work.  None defers to
        # the K8S1M_DELTASCHED env var ("off" default).  Engages only
        # for full-scan XLA waves (score_pct 100, no row mask, not
        # degraded); everything else takes the ordinary full pass.
        deltacache: str | bool | None = None,
        delta_slots: int = 64,
        # Score-stratified candidate index (engine/deltacache.py): keep
        # a per-resident-slot top-K row index in HBM so an all-hit wave
        # with a small dirty set derives candidates from index + dirty
        # rows and skips the full-plane scan — O(dirty + K·batch)
        # instead of O(batch × N).  0 (default) = planes only.  The
        # index keys on class_key(score, column, stratum_bits): with
        # stratum_bits=0 it fails closed whenever scores tie at the
        # floor (homogeneous clusters), so saturated drills set
        # stratum_bits to split score levels into hash strata whose
        # order is wave-invariant.  Byte-identical either way.
        delta_index_k: int = 0,
        stratum_bits: int = 0,
        delta_index_dirty_cap: int | None = None,
    ):
        self.store = store
        self.table_spec = table_spec
        self.pod_spec = pod_spec
        self.profile = profile
        self.chunk = chunk
        self.k = k
        # One resilience policy for the bind/requeue path; max_attempts
        # stays the constructor-level knob (it predates the policy and
        # every harness passes it), overriding the default's budget.
        self.retry_policy = dataclasses.replace(
            retry_policy or policy_for("coordinator.bind"),
            max_attempts=max_attempts,
        )
        self.max_attempts = max_attempts
        self.scheduler_name = scheduler_name
        self.flight = flight_recorder
        self.profiler = profiler
        self._tracer = tracer if tracer is not None else NULL_TRACER
        # Pods that spent their retry budget THIS wave (populated only
        # while tracing): the wave-retire pass closes their chains
        # AFTER the device/bind spans land, so an unschedulable pod's
        # final wave is attributed to device/bind, not lumped into its
        # terminal requeue span (the give-up sites run mid-bind-loop,
        # before the retire pass, and cannot stamp those spans).
        self._trace_gaveup: set[str] = set()
        self._profile_dumps = 0
        self.backend = backend
        from k8s1m_tpu.ops.priority import JITTER_BITS

        if not 0 <= stratum_bits <= JITTER_BITS:
            raise ValueError(
                f"stratum_bits must be in [0, {JITTER_BITS}], "
                f"got {stratum_bits}"
            )
        self.stratum_bits = stratum_bits
        self.pipeline = pipeline
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.depth = depth
        self.watch_queue_cap = watch_queue_cap
        self._inflights: list = []
        # percentageOfNodesToScore (the reference's production config
        # scores 5% of nodes per pod at 1M scale, README.adoc:525-531;
        # terraform tfvars percentageOfNodesToScore: 5).  Each cycle
        # filters+scores one rotating chunk-aligned window of the table.
        if not 1 <= score_pct <= 100:
            raise ValueError(f"score_pct must be in [1, 100], got {score_pct}")
        # Mesh scale-out (the reference's "more replicas" axis): the node
        # table's rows shard over ``sp`` devices, the pod batch over
        # ``dp``; the device step becomes the shard_mapped
        # make_sharded_packed_step and percentageOfNodesToScore windows
        # rotate SHARD-LOCALLY (each device samples its own rows, like
        # each dist-scheduler replica samples the nodes it owns).
        # ``mesh`` accepts a built jax Mesh, a spec string ("2x4",
        # "auto", "none"), or None — which defers to the K8S1M_MESH env
        # var (unset = single-device), so deployments flip the
        # production path on without touching construction sites.
        if mesh is None or isinstance(mesh, str):
            from k8s1m_tpu.parallel.mesh import resolve_mesh

            mesh = resolve_mesh(
                mesh, batch=pod_spec.batch,
                max_nodes=table_spec.max_nodes, chunk=chunk,
            )
        self.mesh = mesh
        if mesh is not None:
            dp_size, sp_size = mesh.shape["dp"], mesh.shape["sp"]
            local_rows = table_spec.max_nodes // sp_size
            if local_rows * sp_size != table_spec.max_nodes:
                raise ValueError(
                    f"max_nodes {table_spec.max_nodes} not divisible by "
                    f"sp={sp_size}"
                )
            if local_rows % chunk:
                raise ValueError(
                    f"rows-per-shard {local_rows} not divisible by "
                    f"chunk {chunk}"
                )
            if pod_spec.batch % dp_size:
                raise ValueError(
                    f"batch {pod_spec.batch} not divisible by dp={dp_size}"
                )
            self._window_nodes = local_rows
        else:
            self._window_nodes = table_spec.max_nodes
        self._sample_rows = sample_rows_for(
            self._window_nodes, score_pct, chunk
        )
        self._window_i = 0
        # Overload control: degraded-mode knobs are precomputed so the
        # mode switch is a cached-executable swap, never a reconfigure
        # (warm both modes before a latency-sensitive window — each is
        # its own compiled step).
        self.tenancy = tenancy
        if tenancy is not None:
            if loadshed is None:
                loadshed = tenancy.controller
            elif loadshed is not tenancy.controller:
                # A second controller would never be ticked: its
                # _admitted_since_tick would grow forever and hard-fail
                # every admission with "cap" once it crossed queue_cap,
                # while its state stayed HEALTHY so per-tenant fairness
                # silently never engaged.
                raise ValueError(
                    "tenancy and loadshed must share one "
                    "HealthController: pass loadshed=tenancy.controller "
                    "or omit loadshed"
                )
        self.loadshed = loadshed
        self.breaker = breaker
        if loadshed is not None:
            self._sample_rows_degraded = sample_rows_for(
                self._window_nodes,
                min(score_pct, loadshed.config.degraded_score_pct),
                chunk,
            )
            self._profile_degraded = degraded_profile(profile)
        else:
            self._sample_rows_degraded = self._sample_rows
            self._profile_degraded = profile
        self._last_cycle_s = 0.0
        # Signal baselines for the per-cycle controller tick.  The
        # counters are process-global: with several live coordinators the
        # deltas mix their traffic, which only ever over-reports pressure
        # (the conservative direction for an overload signal).
        self._sig_conflicts = _PODS_SCHEDULED.value(outcome="conflict")
        self._sig_resyncs = _RESYNCS.value()
        # Breaker-open oracle fallback: decoded NodeInfo cache, generation-
        # keyed on applied node events so node churn invalidates it.
        self._fallback_cache: tuple[int, list] | None = None
        self._node_gen = 0
        # Incremental NodeInfo index under it: maintained at the watch-
        # drain decode sites (zero added decode cost — the NodeInfo is
        # already in hand there), lazily seeded from one store decode
        # for rows that arrived via the bulk ingest lane (bootstrap and
        # resync never build per-node objects), cleared on resync (the
        # bulk relist refreshes rows without decoding).  Keeps the
        # emergency path off the O(N)-per-node-gen store decode
        # (ROADMAP item 1 leftover).
        self._node_infos: dict[str, object] = {}

        # Packed snapshot mode; the PackingSpec itself is built lazily at
        # first table upload so the label-fusion fail-closed decision
        # sees the bootstrap vocab, not an empty one.
        self._packing_mode = resolve_packing(packing)
        self._packing_spec = None
        # Buffer donation: every execution path donates the table (and
        # constraint) buffers so per-wave commits are in-place in HBM —
        # the mesh executables pin their out_shardings AND donate
        # (pinning and donation compose; XLA aliases shard-by-shard).
        self._donate = True
        self._donation_inplace: bool | None = None
        self._packing_rebuilding = False

        self.host = NodeTableHost(table_spec)
        # Bulk cold-relist lane (snapshot/bulkload.py): templates and
        # the bytes->str memo persist across bootstrap and resyncs.
        self._bulk = BulkNodeLoader(self.host)
        self.tracker = ConstraintTracker(table_spec)
        # One shape-keyed template cache shared by every encoder this
        # coordinator owns (inline buckets, the feed's worker, the
        # adjust path) — templates carry no batch dimension, and cache
        # reuse across the paths is what makes a CAS-rollback storm's
        # re-encodes near-free (the shapes were all seen at intake).
        self.encode_cache = EncodeCache()
        self.encoder = HotPodBatchHost(
            pod_spec, table_spec, self.host.vocab, cache=self.encode_cache
        )
        if hotfeed is None:
            hotfeed = pipeline
        dp_shards = self.mesh.shape["dp"] if self.mesh is not None else 1
        if not hotfeed:
            self._feed = None
        elif dp_shards > 1:
            # One HostFeed per dp shard: dp workers encode the wave's
            # contiguous batch slices concurrently (sharing the one
            # template cache) and claim() merges them byte-identically
            # to the inline encode — the overlap survives sharding AND
            # the fill parallelizes like the device work it hides behind.
            self._feed = ShardedHostFeed([
                HotPodBatchHost(
                    dataclasses.replace(
                        pod_spec, batch=pod_spec.batch // dp_shards
                    ),
                    table_spec, self.host.vocab,
                    cache=self.encode_cache, path="feed",
                )
                for _ in range(dp_shards)
            ])
        else:
            self._feed = HostFeed(HotPodBatchHost(
                pod_spec, table_spec, self.host.vocab,
                cache=self.encode_cache, path="feed",
            ))
        if self._feed is not None:
            # A coordinator dropped without close() must not leak the
            # parked worker thread (the thread's bound target pins the
            # feed, encoder, and arena forever otherwise).
            weakref.finalize(self, self._feed.close)
        # Reusable scratch for _process_adjusts (allocated lazily at
        # first use; zeroed per chunk) — the per-call np.zeros were
        # measurable during rollback storms.
        self._adjust_scratch: dict | None = None
        # Adaptive batch buckets: a shallow queue schedules in a smaller
        # power-of-two batch instead of waiting out a full wave's worth
        # of padding — the lever that keeps p50 schedule-to-bind low at
        # light load while deep queues still ride the big batch.  Each
        # bucket is its own compiled executable, so this is opt-in: warm
        # EVERY bucket before a latency-sensitive window or a mid-run
        # compile (tens of seconds on TPU) lands in the tail.
        # min 64: wave cost is ~linear in B down to a small fixed floor
        # (measured round 5: 31ms at B=64 vs 82ms at B=256, 131K/pct5
        # CPU), so smaller buckets directly cut the sub-knee p50.
        self.adaptive_batch = adaptive_batch
        self.min_batch = min(64, pod_spec.batch)
        self._encoders = {pod_spec.batch: self.encoder}
        self.table = None           # device NodeTable, built lazily
        self.constraints = (
            empty_constraints(table_spec) if with_constraints else None
        )
        self._table_sharding = None
        # Dirty-row scatters donate on both paths (in-place updates);
        # the mesh override below additionally pins the row sharding.
        self._scatter = _scatter_rows_donated
        self._adjust = adjust_constraints
        if mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            if adaptive_batch and self.min_batch % mesh.shape["dp"]:
                raise ValueError(
                    f"adaptive min batch {self.min_batch} not divisible "
                    f"by dp={mesh.shape['dp']}"
                )
            self._table_sharding = NamedSharding(mesh, P("sp"))
            # Dirty-row scatters must not let the partitioner drift the
            # table off its row sharding (a replicated output here would
            # silently serialize every later wave).
            from k8s1m_tpu.parallel.sharded_cycle import make_sharded_scatter

            self._scatter = make_sharded_scatter(self._table_sharding)
            if self.constraints is not None:
                from k8s1m_tpu.parallel.mesh import constraint_specs

                cons_shardings = jax.tree.map(
                    lambda s: NamedSharding(mesh, s),
                    constraint_specs(self.constraints),
                )
                self.constraints = jax.device_put(
                    self.constraints, cons_shardings
                )
                # Same drift guard as _scatter: out-of-step constraint
                # corrections (deletes, CAS rollbacks) must hand the
                # state back sharded, or every later wave reshards it —
                # and, like the scatter, they donate the constraint
                # buffers (the coordinator always reassigns
                # self.constraints from the return).
                self._adjust = jax.jit(
                    adjust_constraints_impl, static_argnames=("sign",),
                    donate_argnums=(0,),
                    out_shardings=cons_shardings,
                )
        # Delta-plane cache (deltasched): built after the mesh/sharding
        # decisions so the plane buffers land row-sharded over sp like
        # every other packed plane.  The fill encoder shares the one
        # template cache — shape representatives were all seen at
        # intake, so fills re-encode against warm templates.
        self._delta: DeltaPlaneCache | None = None
        self._delta_fill_enc: HotPodBatchHost | None = None
        if resolve_deltasched(deltacache) == "on":
            plane_sharding = None
            if mesh is not None:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                plane_sharding = NamedSharding(mesh, P(None, "sp"))
            self._delta = DeltaPlaneCache(
                table_spec.max_nodes, slots=delta_slots,
                sharding=plane_sharding,
                index_k=delta_index_k, stratum_bits=stratum_bits,
                index_dirty_cap=delta_index_dirty_cap,
            )
            self._delta_fill_enc = HotPodBatchHost(
                dataclasses.replace(
                    pod_spec, batch=self._delta.fill_batch
                ),
                table_spec, self.host.vocab, cache=self.encode_cache,
            )
        elif delta_index_k:
            # Same fail-loud rationale as resolve_deltasched: an index
            # with no delta cache would silently never engage while the
            # run is labeled "index on".
            raise ValueError(
                "delta_index_k requires deltacache='on' (the candidate "
                "index rides the delta-plane cache)"
            )
        self.key = jax.random.key(seed)

        self.queue: collections.deque[PendingPod] = collections.deque()
        self._queued_keys: set[str] = set()
        # Retrying pods waiting out their backoff: (not_before, seq, pod)
        # min-heap, released into the queue by _release_backoff.  Their
        # keys stay in _queued_keys so watch echoes don't re-add them.
        self._backoff: list[tuple[float, int, PendingPod]] = []
        self._backoff_seq = 0
        # Gang staging (tenancy/gang.py): gid -> (declared size, members
        # by key).  Members enter the queue contiguously only when the
        # whole gang is present; incomplete gangs hold no capacity.
        self._gang_staging: dict[str, tuple[int, dict[str, PendingPod]]] = {}
        # Gangs waiting out a whole-group retry backoff:
        # (not_before, seq, members) min-heap, released contiguously.
        self._gang_parked: list[tuple[float, int, list[PendingPod]]] = []
        self._gang_oversize: set[str] = set()
        # Per-bound-pod preemption metadata:
        # key -> (priority, bind seq, tenant, gang id).  Parallel to
        # _bound (same insert/delete sites) so victim selection never
        # decodes stored objects; the tenant is captured at bind time
        # (the label override would otherwise be lost for pods whose
        # PodInfo is not retained), and a nonempty gang id marks the
        # pod unpreemptable — evicting one member would strand the rest
        # of its gang bound, the exact state gangs exist to prevent.
        self._bind_meta: dict[str, tuple[int, int, str, str]] = {}
        self._bind_seq = 0
        # Incremental preemption-victims index: node name -> {pod key ->
        # Victim}, maintained at the same insert/delete sites as _bound
        # (_victims_note/_victims_drop) so victim selection never scans
        # the full bound-pod ledger per wave — the O(bound pods) scan
        # the 1M-pod shape cannot afford (ISSUE 14).  Only maintained
        # when preemption can actually run; rows resolve lazily at wave
        # time (_VictimRows) so node remove/re-add never stales it.
        self._track_victims = bool(
            tenancy is not None and tenancy.policy.preempt_enabled
        )
        self._victims_by_node: dict[str, dict[str, Victim]] = {}
        # Replayable preemption evidence (populated only when
        # tenancy.policy.log_preemptions; bounded).
        self.preempt_log: list[dict] = []
        # Seeded jitter stream so a replayed fault plan replays the same
        # backoff schedule (determinism-by-seed, faultline contract).
        self._retry_rng = random.Random(seed ^ 0xFA017)
        self._sched_bytes = scheduler_name.encode()
        self._name_bytes: list[bytes] = []
        # Per-namespace tracker matches for the EMPTY label set, keyed by
        # the tracker's registration counts (registration only grows).
        # Label-less pods can still match constraints whose selector is
        # empty; the fast lane must not lose those.
        self._empty_incs_cache: dict[tuple[int, int, str], tuple] = {}
        # Webhook-intake staging: appended from server threads, drained
        # into the queue at the top of each cycle (deque+set aren't
        # thread-safe to mutate from the handler directly).
        self._external: list[dict] = []
        self._external_lock = threading.Lock()
        # Bound-pod record per pod key: (node, cpu, mem, zone, region, pod?).
        # The PodInfo is retained only for constraint-carrying pods — it is
        # needed to decrement count tables on deletion; plain pods stay
        # compact (the 1M-pod case must not hold 1M PodInfos).
        self._bound: dict[str, tuple] = {}
        # Constraint-count corrections awaiting a batched device scatter:
        # (pod, node_name, zone, region, sign).  sign=+1 for externally
        # bound pods entering the snapshot, -1 for deletions.
        self._pending_adjusts: list[tuple[PodInfo, str, int, int, int]] = []
        # Bound pods whose node is not in the snapshot yet (bootstrap
        # list/watch interleaving); accounted when the node arrives.
        self._orphan_bound: dict[str, PodInfo] = {}
        # Two dirty classes (snapshot/node_table.py column split):
        # _dirty_rows re-uploads the FULL row (host authoritative for
        # request totals too: CAS rollbacks, external binds, deletes,
        # tombstones, fresh/reused rows); _dirty_caps re-uploads only the
        # capacity/feature columns — a node update for a row the table
        # already holds — leaving the device's in-flight assume chain on
        # the request columns intact, which is what makes capacity churn
        # scatter-safe while waves are in flight.
        self._dirty_rows: set[int] = set()
        self._dirty_caps: set[int] = set()
        # Rows whose FULL scatter happened while waves were in flight:
        # the upload erased those waves' device-side assumes, so each
        # retiring wave re-dirties the rows it bound here (the host
        # mirror, which just learned the binds, repairs the device).
        # Cleared when the pipeline fully drains.
        self._midflight_rows: set[int] = set()
        # Whether the LAST node drain actually applied anything — the
        # pending probe for watcher types without a cheap .pending.
        self._last_node_drain = 0
        # Time-weighted in-flight depth (obs/metrics.py LevelTimer):
        # sched_bench reads this for the sustained-depth evidence.
        self.depth_timer = LevelTimer()
        # Binds retired by a flush OUTSIDE step()'s own accounting (the
        # exhaustion quiesce inside _drain_node_events, a defensive
        # resync flush): credited to the next step() so drivers summing
        # its return value never lose them.
        self._deferred_binds = 0
        # Seconds of nested out-of-band work to subtract from the
        # enclosing _stage observation (see _stage).
        self._stage_excluded = 0.0
        self._nodes_watch: Watcher | None = None
        self._pods_watch: Watcher | None = None
        # True when the store's bind_batch can suppress our own watch
        # echo (native store only; set at bootstrap once the pods watch
        # exists — its id is read at every bind so resync stays correct).
        self._bind_excludes = False
        self.unschedulable: dict[str, PodInfo] = {}
        # Shard-set hooks (control/shardset.py): pods whose key fails the
        # intake filter are another shard's to schedule (their binds are
        # still tracked as external); the row mask restricts candidate
        # rows to this shard's slice of the node space.
        self.intake_filter = intake_filter
        self._row_mask_np: np.ndarray | None = None
        self._row_mask_dev = None
        # Failover state (ISSUE 9): the reign's fencing token, the
        # warm-standby follower flag (mirrors never schedule and are
        # excluded from the depth gauges), and the one-shot device-step
        # pre-compile latch the standby warms ahead of takeover.
        self.fence = fence
        self._follower = False
        self._warmed = False

        _LIVE.add(self)

    def set_row_mask(self, mask: np.ndarray | None) -> None:
        """Install (or clear) the owned-node mask for sharded scheduling.

        The mask is a traced argument of the packed step, so rebalancing
        (flipping bits) never recompiles — the TPU re-expression of the
        reference's node-label rebalancer moving nodes between replicas
        (reference cmd/dist-scheduler/leader_activities.go:227-343)."""
        if self.mesh is not None and mask is not None:
            raise ValueError(
                "row masks (process-level node sharding) and a device "
                "mesh are different scale-out axes; compose them across "
                "processes, not inside one coordinator"
            )
        # The breaker-fallback node cache bakes the mask in: a rebalance
        # must invalidate it or an open-breaker wave binds onto rows
        # this shard no longer owns.
        self._fallback_cache = None
        if mask is None:
            self._row_mask_np = None
            self._row_mask_dev = None
            return
        mask = np.ascontiguousarray(np.asarray(mask, bool))
        if mask.shape != (self.table_spec.max_nodes,):
            raise ValueError(
                f"row mask shape {mask.shape} != ({self.table_spec.max_nodes},)"
            )
        self._row_mask_np = mask
        self._row_mask_dev = jax.device_put(mask)

    # ---- bootstrap -----------------------------------------------------

    def _relist_nodes(self) -> tuple[list, int]:
        """Full node relist for bootstrap/resync, returning ``(values,
        revision)`` — the bulk ingest lane reads node names out of the
        objects, so the keys (and their per-KV wrappers) are never
        materialized.  The in-process store takes the values-only light
        parse serially (its page parse is GIL-bound — sharding buys
        nothing); wire stores fan the value fetch over key-range shards
        so round trips and proto decode overlap
        (store/native.list_prefix_sharded)."""
        if isinstance(self.store, MemStore):
            return list_prefix_values(self.store, NODES_PREFIX)
        kvs, rev = list_prefix_sharded(self.store, NODES_PREFIX, shards=8)
        return [kv.value for kv in kvs], rev

    def bootstrap(self) -> None:
        """List+watch: load current state, then stream deltas from there.

        The watch starts at the list revision + 1, the same
        resourceVersion handoff kube informers perform.  The node
        relist feeds the bulk ingest lane (snapshot/bulkload.py) —
        byte-identical to the per-node upsert loop it replaced, minus
        the per-node wall — and the whole store->table build is timed
        into ``megarow_cold_build_seconds``.
        """
        t_cold = time.perf_counter()
        with _CYCLE_TIME.time(stage="bootstrap"):
            values, rev = self._relist_nodes()
            self._bulk.ingest(values)
            del values
            self._nodes_watch = self.store.watch(
                NODES_PREFIX, prefix_end(NODES_PREFIX),
                start_revision=rev + 1, queue_cap=self.watch_queue_cap,
            )
            pod_kvs, pod_rev = list_prefix(self.store, PODS_PREFIX)
            for kv in pod_kvs:
                self._on_pod_put(kv.value, kv.mod_revision)
            self._pods_watch = self.store.watch(
                PODS_PREFIX, prefix_end(PODS_PREFIX),
                start_revision=pod_rev + 1, queue_cap=self.watch_queue_cap,
            )
            self._bind_excludes = isinstance(self._pods_watch, Watcher)
            self.table = self._table_to_device()
        _COLD_BUILD.set(time.perf_counter() - t_cold)

    # ---- watch delta application --------------------------------------

    @staticmethod
    def _constraintful(pod: PodInfo) -> bool:
        return bool(
            pod.spread_incs
            or pod.ipa_incs
            or any(r.required and r.anti for r in pod.affinity_refs)
        )

    def _victims_note(
        self, key: str, node_name: str, cpu: int, mem: int,
        priority: int, seq: int, tenant: str, gang: str,
    ) -> None:
        """Insert one bound pod into the incremental victims index —
        called at BOTH _bound insert sites (_note_bound and the native
        bind-batch retire).  Gang members are excluded exactly like the
        old per-wave scan: evicting one would strand its gang bound.
        ``row`` is carried as -1; _VictimRows resolves it lazily against
        the live row mapping at wave time."""
        if not self._track_victims or gang:
            return
        self._victims_by_node.setdefault(node_name, {})[key] = Victim(
            key, node_name, -1, cpu, mem, priority, seq, tenant,
        )

    def _victims_drop(self, key: str, node_name: str) -> None:
        if not self._track_victims:
            return
        d = self._victims_by_node.get(node_name)
        if d is not None and d.pop(key, None) is not None and not d:
            del self._victims_by_node[node_name]

    def _note_bound(self, pod: PodInfo, node_name: str, *, external: bool) -> None:
        row = self.host.row_of(node_name)
        zone, region = int(self.host.zone[row]), int(self.host.region[row])
        keep = pod if self._constraintful(pod) else None
        self._bound[pod.key] = (node_name, pod.cpu_milli, pod.mem_kib, zone, region, keep)
        self._bind_seq += 1
        gang = gang_of_labels(pod.labels, pod.namespace)
        gang_id = gang[0] if gang is not None else ""
        tenant = tenant_of_pod(pod)
        self._bind_meta[pod.key] = (
            pod.priority, self._bind_seq, tenant, gang_id,
        )
        self._victims_note(
            pod.key, node_name, pod.cpu_milli, pod.mem_kib,
            pod.priority, self._bind_seq, tenant, gang_id,
        )
        if external and keep is not None and self.constraints is not None:
            # An externally bound pod contributes to domain counts exactly
            # like upstream's cache AddPod feeds plugin pre-state.
            self._pending_adjusts.append((keep, node_name, zone, region, 1))

    def _on_pod_put(self, data: bytes, mod_revision: int, key: bytes = b"") -> None:
        # Fast path for the watch echo of our own binds: the object has a
        # nodeName and its key is in _bound — half of all pod events in
        # steady state.  Skip the JSON decode entirely (the byte pattern
        # check is conservative: a false positive just takes the slow
        # path below).
        if key and b'"nodeName"' in data:
            pod_key_str = key[len(PODS_PREFIX):].decode()
            if pod_key_str in self._bound:
                self._queued_keys.discard(pod_key_str)
                return
        try:
            pod = decode_pod(data, self.tracker)
        except Exception:
            # One malformed object must not poison the event stream — the
            # rest of the polled batch would be lost and the snapshot
            # would silently diverge.  Quarantine and move on.
            _DECODE_ERRORS.inc(kind="pod")
            log.exception("undecodable pod object; skipping")
            return
        if pod.node_name:
            # Someone's bind (ours echoing back, or an external writer):
            # account it if we haven't already.
            if pod.key not in self._bound:
                if pod.node_name in self.host._row_of:
                    self._orphan_bound.pop(pod.key, None)
                    self.host.add_pod(pod.node_name, pod.cpu_milli, pod.mem_kib)
                    self._dirty_rows.add(self.host.row_of(pod.node_name))
                    self._note_bound(pod, pod.node_name, external=True)
                else:
                    # Bound to a node we have not seen yet (list/watch
                    # interleaving at bootstrap); account when it arrives.
                    self._orphan_bound[pod.key] = pod
            self._queued_keys.discard(pod.key)
            return
        if pod.scheduler_name != self.scheduler_name:
            # Not ours to schedule (the reference's webhook/watch intake
            # applies the same schedulerName filter, webhook.go:102-125).
            return
        if self.intake_filter is not None and not self.intake_filter(pod.key):
            # Another shard's pod (pod-hash intake partition); its bind
            # arrives via watch and is accounted as external above.
            return
        if pod.key in self._queued_keys or pod.key in self._bound:
            # _bound: a webhook-intake pod can bind before its original
            # create event arrives via watch; re-enqueuing that stale
            # revision would double-account the pod in the batch it rides
            # (commit_binds assumes, CAS rolls back — but batch-mates
            # would have been placed against inflated usage meanwhile).
            return
        self._queued_keys.add(pod.key)
        self._stage_or_queue(
            PendingPod(
                pod, mod_revision, time.perf_counter(),
                cpu_milli=pod.cpu_milli, mem_kib=pod.mem_kib,
                key_str=pod.key, raw=data,
                key_bytes=key or pod_key(pod.namespace, pod.name),
                priority=pod.priority,
            ),
            pod,
        )

    def _on_pod_delete(self, key: bytes) -> None:
        pod_key_str = key[len(PODS_PREFIX):].decode()
        tracer = self._tracer
        if tracer.enabled:
            # A pod deleted while pending closes its chain here (a
            # bound pod's trace already closed at bind; this no-ops).
            tracer.finish(pod_key_str, "requeue", outcome="deleted")
        self._queued_keys.discard(pod_key_str)
        self._orphan_bound.pop(pod_key_str, None)
        self._bind_meta.pop(pod_key_str, None)
        if self._gang_staging:
            # A deleted member must leave gang staging too: a leaked
            # record would count into the load signal forever and, if
            # the gang later completed, ride a wave as a dead pod.
            for gid, (_size, members) in list(self._gang_staging.items()):
                if members.pop(pod_key_str, None) is not None:
                    if not members:
                        del self._gang_staging[gid]
                    break
        bound = self._bound.pop(pod_key_str, None)
        if bound is not None:
            node_name, cpu, mem, zone, region, keep = bound
            self._victims_drop(pod_key_str, node_name)
            if node_name in self.host._row_of:
                self.host.remove_pod(node_name, cpu, mem)
                self._dirty_rows.add(self.host.row_of(node_name))
            if keep is not None and self.constraints is not None:
                self._pending_adjusts.append((keep, node_name, zone, region, -1))

    def _adopt_orphans(self, node_name: str) -> None:
        for key, pod in list(self._orphan_bound.items()):
            if pod.node_name == node_name:
                del self._orphan_bound[key]
                self.host.add_pod(node_name, pod.cpu_milli, pod.mem_kib)
                self._dirty_rows.add(self.host.row_of(node_name))
                self._note_bound(pod, node_name, external=True)

    def drain_watches(self, max_events: int = 10000) -> int:
        """Apply pending node/pod deltas; returns number of events.

        A watcher that overflowed its native queue (10,000 events) has
        silently lost deltas — the snapshot would diverge from the store
        forever.  Detect it and relist, the same way a kube reflector
        handles 410 Gone.
        """
        if self._watch_fault():
            # Injected watch loss (disconnect / drop / stale_revision):
            # the graceful-degradation contract is relist from current
            # state — exactly the overflow response below.
            return self.resync()
        if self._nodes_watch.dropped or self._pods_watch.dropped:
            log.warning(
                "watch overflow (nodes dropped=%d pods dropped=%d); resyncing",
                self._nodes_watch.dropped, self._pods_watch.dropped,
            )
            return self.resync()
        # A server-side cancel (compaction past our revision, shutdown,
        # tier restart) ends the stream without setting dropped; without a
        # resync the drains below would poll empty batches forever and
        # intake would silently stall.
        if getattr(self._nodes_watch, "canceled", False) or getattr(
            self._pods_watch, "canceled", False
        ):
            log.warning(
                "watch canceled server-side (nodes=%s pods=%s); resyncing",
                getattr(self._nodes_watch, "canceled", False),
                getattr(self._pods_watch, "canceled", False),
            )
            return self.resync()
        n = self._drain_node_events(max_events)
        n += self._drain_pod_events(max_events)
        return n

    @staticmethod
    def _watch_fault() -> bool:
        """Faultline hook on the intake watch drain (component
        ``coordinator.watch``, op ``poll``).  ``delay`` sleeps; any
        failure kind means the watch tier is gone from this consumer's
        perspective — True tells the caller to resync (relist from
        current store state + rewatch), which recovers every lost event
        by construction."""
        d = faultline.decide("coordinator.watch", "poll")
        if d is None:
            return False
        if d.kind == "delay":
            time.sleep(d.delay_s)
            return False
        log.warning("injected %s on watch drain; resyncing", d.kind)
        return True

    @contextlib.contextmanager
    def _stage(self, stage: str):
        """Stage timer that also feeds the overlap split: host-stage
        seconds labeled by whether device waves were in flight when the
        stage ran (inflight=yes time is hidden behind device work).
        Out-of-band work that runs nested inside a stage (the exhaustion
        quiesce's flush mid-drain) adds its duration to _stage_excluded
        so the same seconds are not counted into two stages; the inflight
        label is latched at entry (a rare-path approximation)."""
        inflight = "yes" if self._inflights else "no"
        t0 = time.perf_counter()
        excl0 = self._stage_excluded
        try:
            yield
        finally:
            dt = time.perf_counter() - t0 - (self._stage_excluded - excl0)
            _CYCLE_TIME.observe(dt, stage=stage)
            _PIPE_OVERLAP.inc(dt, stage=stage, inflight=inflight)

    def _upsert_node(self, node) -> int:
        """host.upsert with the one structural quiesce left: allocation
        hitting a full table whose only free rows sit in the wave-epoch
        quarantine retires the pipeline, releases them, and retries."""
        try:
            return self.host.upsert(node)
        except RowsExhausted as e:
            if not e.quarantined:
                raise           # genuinely full; re-bucket TableSpec
            if self._inflights:
                _PIPE_QUIESCE.inc(reason="structural")
                # Retiring releases the quarantine; credit the binds to
                # the next step()/flush() return.  Plain assignment:
                # flush() already folds prior deferred credit into its
                # return (+= would re-add the stale loaded value).  The
                # flush runs nested inside the drain stage timer, so its
                # wall time is excluded from the drain observation (the
                # retired waves' sync_out/bind stages record it).
                t0 = time.perf_counter()
                self._deferred_binds = self.flush()
                self._stage_excluded += time.perf_counter() - t0
            self.host.release_rows(None)
            return self.host.upsert(node)

    def _drain_node_events(self, max_events: int = 10000) -> int:
        """Apply node deltas — pipeline-safe.

        Events classify at the row level: an update to a node the table
        already holds (capacity, labels, taints, zone — same row, same
        name) is capacity-only and lands in _dirty_caps, scattered into
        the live device table while waves are in flight; a new node
        allocates a fresh row past the high-water mark (or reuses a
        quarantine-released one) and a remove tombstones its row into
        the wave-epoch quarantine (node_table.py) — both structural
        shapes that no longer need the pipeline quiesced.  Only
        quarantine exhaustion (_upsert_node) still retires it."""
        if not self._inflights:
            # Idle pipeline: every launched wave has retired, so all
            # quarantined rows are past their hazard window.
            self.host.release_rows(None)
        n = 0
        row_of = self.host._row_of
        with self._stage("drain"):
            for etype, key, value, _mrev in drain_events_light(
                self._nodes_watch, max_events
            ):
                n += 1
                if etype == 0:
                    try:
                        node = decode_node(value)
                    except Exception:
                        _DECODE_ERRORS.inc(kind="node")
                        log.exception("undecodable node object; skipping")
                        continue
                    if node.name in row_of:
                        self._dirty_caps.add(self._upsert_node(node))
                    else:
                        self._dirty_rows.add(self._upsert_node(node))
                        self._adopt_orphans(node.name)
                    self._node_infos[node.name] = node
                else:
                    name = key[len(NODES_PREFIX):].decode()
                    self._node_infos.pop(name, None)
                    if name in row_of:
                        self._dirty_rows.add(self.host.remove(name))
        self._node_gen += n
        self._last_node_drain = n
        return n

    def _drain_pod_events(self, max_events: int = 10000) -> int:
        """Apply pod deltas.  Touches capacity accounting only — never
        the row->node mapping — so it is safe to run while a wave is in
        flight.  Drain to (momentarily) empty: a single capped poll per
        cycle would let backlog accumulate into an overflow resync under
        heavy churn; the per-call bound keeps the cycle live against a
        producer that outruns the decode pass.

        Both watcher types expose poll_pods — the native store drains
        AND parses in one C call; RemoteWatcher runs its buffered wire
        events through the same parser (ms_parse_pod_events) — so the
        columnar fast lane serves in-process and deployed topologies
        alike.  The per-event fallback below remains for third-party
        watcher implementations without poll_pods."""
        if getattr(self._pods_watch, "poll_pods", None) is not None:
            n = 0
            batch = min(max_events, 10000)
            with self._stage("drain"):
                while True:
                    evb = self._pods_watch.poll_pods(
                        batch, self._sched_bytes
                    )
                    if evb.n:
                        self._apply_pod_batch(evb)
                        n += evb.n
                    if evb.n < batch or n >= 20 * max_events:
                        return n
        n = 0
        with self._stage("drain"):
            for etype, key, value, mrev in drain_events_light(
                self._pods_watch, max_events
            ):
                n += 1
                if etype == 0:
                    self._on_pod_put(value, mrev, key)
                else:
                    self._on_pod_delete(key)
        return n

    def _apply_pod_batch(self, evb) -> None:
        """Apply one columnar poll_pods drain (store/native.py
        PodEventBatch).  Flag semantics decided natively: CANONICAL means
        the C parser accepted the exact encode_pod shape (label-less);
        everything else falls back to _on_pod_put's full decode."""
        plen = len(PODS_PREFIX)
        koff = evb.koff.tolist()
        kb = evb.key_blob
        etype = evb.etype
        flags = evb.flags
        # The fast lane: canonical pending pods for this scheduler.
        fast = POD_CANONICAL | POD_SCHED_MATCH
        fastmask = (etype == 0) & (
            (flags & (fast | POD_HAS_NODE)) == fast
        )
        now = time.perf_counter()
        tracer = self._tracer
        tr_on = tracer.enabled
        tr = self.tracker
        has_constraints = bool(tr._spread or tr._affinity)
        if fastmask.all() and not has_constraints:
            # Pure create wave (the make_pods steady state): one batched
            # tolist per column, no per-event branching.
            cpu_l = evb.cpu.tolist()
            mem_l = evb.mem.tolist()
            mrev_l = evb.mrev.tolist()
            queued = self._queued_keys
            bound = self._bound
            q = self.queue
            filt = self.intake_filter
            for i in range(evb.n):
                key = kb[koff[i] : koff[i + 1]]
                ks = key[plen:].decode()
                if ks in queued or ks in bound:
                    continue
                if filt is not None and not filt(ks):
                    continue
                queued.add(ks)
                q.append(PendingPod(
                    None, mrev_l[i], now,
                    cpu_milli=cpu_l[i], mem_kib=mem_l[i],
                    key_str=ks, key_bytes=key,
                ))
                if tr_on:
                    tracer.begin(ks, now, source="intake")
            return
        aoff = evb.aoff.tolist()
        ab = evb.aux_blob
        cpu_l = evb.cpu.tolist()
        mem_l = evb.mem.tolist()
        mrev_l = evb.mrev.tolist()
        flags_l = flags.tolist()
        etype_l = etype.tolist()
        for i in range(evb.n):
            key = kb[koff[i] : koff[i + 1]]
            if etype_l[i] == 1:
                self._on_pod_delete(key)
                continue
            f = flags_l[i]
            if not f & POD_CANONICAL:
                self._on_pod_put(ab[aoff[i] : aoff[i + 1]], mrev_l[i], key)
                # decode_pod may have interned a new constraint whose
                # empty selector matches later canonical pods in this
                # same batch — refresh the snapshot.
                has_constraints = bool(tr._spread or tr._affinity)
                continue
            ks = key[plen:].decode()
            if f & POD_HAS_NODE:
                # A bind: ours echoing back (suppressed at the store for
                # native binds, but the slow _bind path still echoes), or
                # an external writer's.
                if ks in self._bound:
                    self._queued_keys.discard(ks)
                    continue
                node_name = ab[aoff[i] : aoff[i + 1]].decode()
                ns, name = ks.split("/", 1)
                pod = PodInfo(
                    name=name, namespace=ns,
                    cpu_milli=cpu_l[i], mem_kib=mem_l[i],
                    node_name=node_name,
                )
                if has_constraints:
                    si, ii = self._empty_incs(ns)
                    pod.spread_incs = list(si)
                    pod.ipa_incs = list(ii)
                if node_name in self.host._row_of:
                    self._orphan_bound.pop(ks, None)
                    self.host.add_pod(node_name, pod.cpu_milli, pod.mem_kib)
                    self._dirty_rows.add(self.host.row_of(node_name))
                    self._note_bound(pod, node_name, external=True)
                else:
                    self._orphan_bound[ks] = pod
                self._queued_keys.discard(ks)
                continue
            if not f & POD_SCHED_MATCH:
                continue
            if ks in self._queued_keys or ks in self._bound:
                continue
            if self.intake_filter is not None and not self.intake_filter(ks):
                continue
            pod = None
            if has_constraints:
                ns, name = ks.split("/", 1)
                si, ii = self._empty_incs(ns)
                if si or ii:
                    # Matches an empty-selector constraint: not plain.
                    pod = PodInfo(
                        name=name, namespace=ns,
                        cpu_milli=cpu_l[i], mem_kib=mem_l[i],
                    )
                    pod.spread_incs = list(si)
                    pod.ipa_incs = list(ii)
            self._queued_keys.add(ks)
            self.queue.append(PendingPod(
                pod, mrev_l[i], now,
                cpu_milli=cpu_l[i], mem_kib=mem_l[i],
                key_str=ks, key_bytes=key,
            ))
            if tr_on:
                tracer.begin(ks, now, source="intake")

    def _node_name_bytes(self) -> list:
        """Encoded node names, index-parallel with vocab.node_names
        (extended lazily; names never leave the vocab)."""
        nb = self._name_bytes
        tv = self.host.vocab.node_names._to_val
        while len(nb) < len(tv):
            v = tv[len(nb)]
            nb.append(v.encode() if isinstance(v, str) else b"")
        return nb

    def _empty_incs(self, namespace: str) -> tuple:
        """Cached tracker matches for a label-less pod in ``namespace``
        (cache key includes the registration counts, which only grow)."""
        tr = self.tracker
        key = (len(tr._spread), len(tr._affinity), namespace)
        incs = self._empty_incs_cache.get(key)
        if incs is None:
            if len(self._empty_incs_cache) >= 1024:
                # Bounded like _gang_oversize: namespaces churn on long
                # soaks, and the registration counts in the key retire
                # every older entry each time a constraint registers —
                # unbounded, the dead generations pile up forever.
                # Clearing just re-derives a live namespace's matches
                # once more.
                self._empty_incs_cache.clear()
            incs = (
                tuple(tr.spread_matches(namespace, {})),
                tuple(tr.affinity_matches(namespace, {})),
            )
            self._empty_incs_cache[key] = incs
        return incs

    def resync(self) -> int:
        """Full relist after watch overflow: reconcile host state against
        the store and restart both watches from the list revisions."""
        _RESYNCS.inc()
        self._node_gen += 1
        # The bulk relist below refreshes every row WITHOUT building
        # per-node objects; a kept index would serve pre-outage
        # NodeInfos for rows whose values changed while the watch was
        # broken.  Drop it wholesale — the next fallback call re-seeds
        # lazily from the store.
        self._node_infos.clear()
        if self._inflights:
            # Call sites quiesce first; this is the defensive backstop
            # (a driver calling drain_watches mid-flight) — the relist
            # below rebuilds the row mapping, which no wave may straddle.
            # Plain assignment — _quiesce's flush() already folds prior
            # deferred credit into its return (+= would double-count it),
            # and the inflights guard above means it really flushes.
            self._deferred_binds = self._quiesce("resync")
        # The pipeline is idle: the quarantine's hazard window is over,
        # and the relist may need rows.
        self.host.release_rows(None)
        self._midflight_rows.clear()
        if self._delta is not None:
            # The relist rebuilds the row->node mapping wholesale; no
            # row set bounds what a cached plane may now mis-describe.
            self._delta.drop_all("resync")
        with _CYCLE_TIME.time(stage="resync"):
            self._nodes_watch.cancel()
            self._pods_watch.cancel()

            values, rev = self._relist_nodes()
            rows = self._bulk.ingest(values)
            del values
            self._dirty_rows.update(rows.tolist())
            # Listed names read back from the ingested rows (the
            # object's metadata.name, exactly what the old decode loop
            # collected), so a writer whose key disagrees with its
            # object cannot desync the removal sweep.
            nv = self.host.vocab.node_names._to_val
            listed = {nv[i] for i in self.host.name_id[rows].tolist()}
            stale = [
                name for name in self.host._row_of if name not in listed
            ]
            for name in stale:
                self._dirty_rows.add(self.host.remove(name))
            self._nodes_watch = self.store.watch(
                NODES_PREFIX, prefix_end(NODES_PREFIX),
                start_revision=rev + 1, queue_cap=self.watch_queue_cap,
            )

            pod_kvs, pod_rev = list_prefix(self.store, PODS_PREFIX)
            seen = set()
            for kv in pod_kvs:
                seen.add(kv.key[len(PODS_PREFIX):].decode())
                self._on_pod_put(kv.value, kv.mod_revision)
            for key in list(self._bound):
                if key not in seen:
                    ns, name = key.split("/", 1)
                    self._on_pod_delete(pod_key(ns, name))
            self._orphan_bound = {
                k: v for k, v in self._orphan_bound.items() if k in seen
            }
            self._pods_watch = self.store.watch(
                PODS_PREFIX, prefix_end(PODS_PREFIX),
                start_revision=pod_rev + 1, queue_cap=self.watch_queue_cap,
            )
        return len(listed) + len(seen)

    # ---- warm standby: follow / promote / crash-consistent recovery ----
    # (ISSUE 9; driven by control/leader.HACoordinator)

    def follow(self) -> int:
        """One standby-mirror tick: apply the world's deltas and keep
        every cache warm — NEVER schedule, never write to the store.

        The mirror's derived state (queue, bound-pod ledger,
        ``_bind_meta``, gang staging, host mirror, device table, encode
        templates, compiled step) is thereby a CONTINUOUS reconstruction
        from store facts + intake replay — exactly the state
        ``promote()`` inherits at takeover, which is why takeover is a
        bounded reconcile instead of a cold boot.  Returns events
        applied this tick."""
        lag = 0
        for w in (self._nodes_watch, self._pods_watch):
            p = getattr(w, "pending", None)
            if p:
                lag += int(p)
        _MIRROR_LAG.set(lag)
        self._drain_external()
        n = self.drain_watches()
        self._sync_table()
        self._process_adjusts()
        # Keep the mirror's queue ≈ the TRUE pending backlog: entries
        # the leader already bound would otherwise accumulate all
        # standby long and poison the load signal below (and promote's
        # first waves).  Thresholded so steady follow ticks stay O(1).
        if len(self.queue) >= 2 * max(
            self.pod_spec.batch, len(self._queued_keys) - len(self._backoff)
        ):
            self._purge_settled_queue()
        # Tick the overload/tenancy chain too: HACoordinator stages
        # no-leader webhook pods into this mirror THROUGH admission, so
        # the per-tenant buckets must keep refilling (and the health
        # state must track the real backlog) while standby.
        self._loadshed_tick()
        self.warm_compile()
        return n

    def _purge_settled_queue(self) -> int:
        """Drop queue records whose pods are already settled: a
        follower learns of the leader's binds AFTER queueing the same
        pods, so its queue holds stale records for bound keys
        (``_queued_keys`` was discarded; the deque entry was not).
        Returns the number purged."""
        stale = sum(
            1 for p in self.queue
            if p.key_str not in self._queued_keys or p.key_str in self._bound
        )
        if stale:
            self.queue = collections.deque(
                p for p in self.queue
                if p.key_str in self._queued_keys
                and p.key_str not in self._bound
            )
        return stale

    def warm_compile(self) -> bool:
        """Pre-compile the device step ahead of takeover: run one wave
        over the live table shapes and DISCARD every output — no store
        write, no host accounting, no RNG stream consumed.  Encodes the
        mirror's own queued pods (peeked, never popped) so the compiled
        (groups, shape) executable variant matches the traffic the
        first post-takeover wave will actually carry; retries each
        follow tick until representative pods exist, then latches."""
        if self._warmed or self.table is None:
            return False
        pods = []
        for p in self.queue:
            pods.append(p.peek_pod())
            if len(pods) >= self.pod_spec.batch:
                break
        if not pods:
            return False
        batch = self.encoder.encode_packed(pods)
        # The production executable donates its inputs: warm it against
        # throwaway COPIES so the live mirror table (and constraint
        # state) survive this discarded dispatch.
        tbl, cons = self.table, self.constraints
        if self._donate:
            tbl = jax.tree.map(jnp.array, tbl)
            if cons is not None:
                cons = jax.tree.map(jnp.array, cons)
        _t, _c, _asg, rows_dev = schedule_batch_packed(
            tbl, batch, jax.random.key(0),
            profile=self.profile, constraints=cons,
            chunk=self.chunk, k=self.k, backend=self.backend,
            sample_rows=self._sample_rows, sample_offset=0,
            row_mask=self._row_mask_dev, mesh=self.mesh,
            donate=self._donate,
        )
        jax.block_until_ready(rows_dev)
        self._warmed = True
        return True

    def promote(self, *, acquire_revision: int = 0) -> dict:
        """Warm-standby takeover: turn a following mirror into the
        leader with a bounded reconcile.

        1. Drain the watch backlog (bounded by the mirror's lag; a
           broken/overflowed watch falls back to a full ``resync`` —
           still warm: vocab, encode templates and the compiled step
           survive).
        2. Diff the mirror against the store pinned at the
           lease-acquire revision (``_reconcile_at``): every divergence
           is repaired through the ordinary intake paths and counted —
           crash consistency does not depend on the watch stream having
           been perfect.
        3. Settle gangs the predecessor left partially bound
           all-or-none (``recover_gangs``).
        4. Push repairs to the device and drop follower status.

        Rows whose accounting changed during the reconcile ride the
        normal dirty-row machinery, and the mirror has no in-flight
        waves by construction — so the wave-epoch quarantine starts the
        new reign empty: nothing the predecessor's unretired waves
        touched can alias a row (their store writes were fenced; their
        device-side assumes died with their table).

        Returns the evidence dict drivers commit (repair counts)."""
        stats: dict = {"resync": 0, "repairs": {}, "gangs_released": 0}
        nw, pw = self._nodes_watch, self._pods_watch
        broken = (
            nw is None or pw is None
            or nw.dropped or pw.dropped
            or getattr(nw, "canceled", False)
            or getattr(pw, "canceled", False)
        )
        if broken:
            self.resync()
            stats["resync"] = 1
        else:
            for _ in range(64):
                n = self.drain_watches()
                if n:
                    continue
                # Remote watchers expose the highest revision BUFFERED
                # off the wire (RemoteWatcher.seen_revision): keep
                # pumping while the stream demonstrably has not covered
                # the acquire revision yet (events can be in flight
                # with pending == 0).  A quiet prefix never reaches the
                # acquire revision — the loop cap bounds that, and the
                # current-state reads in _reconcile_at repair whatever
                # a still-in-flight event would have delivered.
                seen = getattr(self._pods_watch, "seen_revision", None)
                if seen is None or seen >= acquire_revision:
                    break
            self._drain_external()
            repairs = self._reconcile_at(acquire_revision)
            stats["resync"] = repairs.pop("resync", 0)
            stats["repairs"] = repairs
        # Purge queue entries the predecessor already settled: dropping
        # them spares the first post-takeover waves a conflict storm of
        # already-bound pods — and keeps recover_gangs from reading a
        # fully-bound gang as still pending.
        stats["stale_queue_purged"] = self._purge_settled_queue()
        stats["gangs_released"] = self.recover_gangs()
        self._sync_table()
        self._process_adjusts()
        self._follower = False
        _MIRROR_LAG.set(0)
        return stats

    def _reconcile_at(self, revision: int) -> dict:
        """Crash-consistency audit: list both prefixes PINNED at the
        lease-acquire revision (follow-mode relist-from-revision,
        store/native.list_prefix) and diff against the mirror.

        The mirror has already drained its watches PAST the pin, so a
        pin-vs-mirror mismatch is ambiguous on its own: either the
        watch stream missed the fact (repair it) or the mirror
        legitimately advanced beyond the pin (leave it alone).  Every
        candidate repair therefore re-reads the store's CURRENT state
        before mutating — the pin bounds WHAT to audit (a stable
        iteration set as of acquisition), the current read decides the
        repair.  Facts the watch already delivered cost a set probe
        each; actual repairs go through the ordinary intake handlers
        (``_on_pod_put`` / ``_on_pod_delete`` / ``_upsert_node``) so
        repair and live intake can never disagree, and each is counted
        in ``failover_reconcile_repairs_total``."""
        rep = {"nodes_added": 0, "nodes_removed": 0, "pods_replayed": 0,
               "binds_adopted": 0, "pods_dropped": 0}
        try:
            kvs, _ = list_prefix(
                self.store, NODES_PREFIX, revision=revision
            )
            pod_kvs, _ = list_prefix(
                self.store, PODS_PREFIX, revision=revision
            )
        except (CompactedError, FutureRevError):
            # The acquire revision is outside the store's window (long
            # pause + compaction): the pinned diff is impossible, fall
            # back to the full relist.
            self.resync()
            return {"resync": 1}
        row_of = self.host._row_of
        listed = set()
        for kv in kvs:
            name = kv.key[len(NODES_PREFIX):].decode()
            listed.add(name)
            if name in row_of:
                continue
            # In the pin but not the mirror: a missed add — unless the
            # node was deleted after the pin (the mirror is right).
            cur = self.store.get(kv.key)
            if cur is None:
                continue
            try:
                node = decode_node(cur.value)
            except Exception:
                _DECODE_ERRORS.inc(kind="node")
                log.exception("undecodable node in reconcile; skipping")
                continue
            self._dirty_rows.add(self._upsert_node(node))
            self._node_infos[node.name] = node
            self._adopt_orphans(name)
            rep["nodes_added"] += 1
        for name in list(row_of):
            if name in listed:
                continue
            # In the mirror but not the pin: a missed delete — unless
            # the node was created after the pin (the mirror is right).
            if self.store.get(node_key(name)) is not None:
                continue
            self._node_infos.pop(name, None)
            self._dirty_rows.add(self.host.remove(name))
            rep["nodes_removed"] += 1
        seen = set()
        for kv in pod_kvs:
            k = kv.key[len(PODS_PREFIX):].decode()
            seen.add(k)
            pinned_bound = b'"nodeName"' in kv.value
            mirror_bound = k in self._bound
            if pinned_bound == mirror_bound:
                continue
            # Pin and mirror disagree: the CURRENT store state decides
            # whether the watch missed a fact or the mirror advanced.
            cur = self.store.get(kv.key)
            if cur is None:
                continue        # deleted meanwhile; the delete echo or
                                # the _bound sweep below settles it
            cur_bound = b'"nodeName"' in cur.value
            if cur_bound and not mirror_bound:
                # A bind the mirror never saw: adopt it as external.
                self._on_pod_put(cur.value, cur.mod_revision, kv.key)
                rep["binds_adopted"] += 1
            elif not cur_bound and mirror_bound:
                # An eviction echo the mirror never saw: undo the
                # accounting and replay the pending object.
                self._on_pod_delete(kv.key)
                self._on_pod_put(cur.value, cur.mod_revision, kv.key)
                rep["pods_replayed"] += 1
        # Intake the mirror missed entirely (pinned pending, tracked
        # nowhere) — replay only if the pod still exists and is still
        # pending NOW.
        for kv in pod_kvs:
            k = kv.key[len(PODS_PREFIX):].decode()
            if (
                b'"nodeName"' in kv.value
                or k in self._queued_keys or k in self._bound
            ):
                continue
            cur = self.store.get(kv.key)
            if cur is None or b'"nodeName"' in cur.value:
                continue
            self._on_pod_put(cur.value, cur.mod_revision, kv.key)
            rep["pods_replayed"] += 1
        for k in list(self._bound):
            if k in seen:
                continue
            ns, name = k.split("/", 1)
            kb = pod_key(ns, name)
            # Absent from the PINNED list but maybe newer than the pin
            # (bound after acquisition): only the store's CURRENT state
            # decides a drop.
            if self.store.get(kb) is None:
                self._on_pod_delete(kb)
                rep["pods_dropped"] += 1
        for kind, n in rep.items():
            if n:
                _RECONCILE_REPAIRS.inc(n, kind=kind)
        return rep

    def recover_gangs(self) -> int:
        """Crash half of gang all-or-none (takeover): a predecessor
        that died between a wave's bind CASes and its gang settlement
        leaves a gang PARTIALLY bound in the store.  Any gang with both
        bound members and pending members releases the bound ones
        (fenced evict — we hold the lease now) back through gang
        staging, so the whole gang re-rides one wave; gangs whose every
        member is bound are honored via the store untouched.  Returns
        binds released."""
        if self.tenancy is None or not self.tenancy.policy.gang_enabled:
            return 0
        bound_gangs: dict[str, list[str]] = {}
        for key, meta in self._bind_meta.items():
            if meta[3] and key in self._bound:
                bound_gangs.setdefault(meta[3], []).append(key)
        if not bound_gangs:
            return 0
        pending_gangs = set(self._gang_staging)
        for p in self.queue:
            # Only genuinely-pending members count: a follower's queue
            # can hold stale records for keys the predecessor already
            # bound (settled gangs must read as fully bound, not split).
            if (
                p.gang_id and p.key_str in self._queued_keys
                and p.key_str not in self._bound
            ):
                pending_gangs.add(p.gang_id)
        for _, _, members in self._gang_parked:
            for p in members:
                if p.gang_id:
                    pending_gangs.add(p.gang_id)
        released = 0
        for gid, keys in bound_gangs.items():
            if gid not in pending_gangs:
                continue        # fully bound: store facts are honored
            for key in keys:
                evicted, rec = self._evict_bound(
                    key, count_eviction=False, path="evict"
                )
                if not evicted:
                    log.warning(
                        "gang %s member %s could not be released at "
                        "takeover (CAS lost); leaving it bound", gid, key,
                    )
                    continue
                released += 1
                if rec is not None:
                    pod = rec.pod
                    g = gang_of_labels(pod.labels, pod.namespace)
                    if g is not None:
                        rec.gang_id, rec.gang_size = g
                    tracer = self._tracer
                    if tracer.enabled:
                        # Takeover requeue: the released member's chain
                        # re-anchors under the new reign before
                        # _stage_or_queue's generic begin can label it
                        # as ordinary intake.
                        tracer.begin(
                            rec.key_str, rec.enqueued_at,
                            source="failover",
                        )
                    self._stage_or_queue(rec, pod)
            note_gang("recovered")
            log.info(
                "takeover released partially-bound gang %s "
                "(%d members back to staging)", gid, len(keys),
            )
        return released

    @staticmethod
    def _pad_rows(rows: np.ndarray) -> np.ndarray:
        """Sorted, power-of-two-padded scatter indices.  Sorted first:
        np.fromiter over a set is arbitrary-order, which would make the
        padded scatter input nondeterministic across runs (and hurt
        gather locality); padding then repeats the last row — scattering
        identical values to the same index is idempotent.  The pow2
        bucket keeps jax.jit at a handful of shapes, not one trace per
        distinct dirty-row count."""
        rows.sort()
        cap = 1 << max(0, int(rows.size - 1).bit_length())
        if cap != rows.size:
            rows = np.concatenate(
                [rows, np.repeat(rows[-1:], cap - rows.size)]
            )
        return rows

    def _sync_table(self) -> None:
        """Scatter dirty host rows into the device table — safe to run
        while waves are in flight.

        The scatter consumes the latest table future, so it executes
        on-stream after every dispatched wave (no host sync, no
        quiesce).  Capacity-only rows (_dirty_caps) upload the feature
        columns alone, leaving the device's in-flight request assumes
        intact; full rows (_dirty_rows) upload everything — host
        authoritative — and are noted in _midflight_rows so retiring
        waves can repair the assumes the upload erased (see _complete).
        """
        if self.table is None:
            self.table = self._table_to_device()
            self._dirty_rows.clear()
            self._dirty_caps.clear()
            return
        if self._packing_rebuilding:
            # Mid-rebuild retires re-enter here; the wholesale re-upload
            # at the end of _packing_rebuild subsumes every dirty row.
            return
        if not self._dirty_rows and not self._dirty_caps:
            return
        with self._stage("sync"):
            if self._delta is not None:
                # Journal the rows BEFORE the scatters dispatch: a delta
                # wave enqueued after this point recomputes them from
                # the post-scatter table (stream order), so version <=
                # journal stamp <= device truth holds per row.  Both
                # dirty classes ride one recompute — re-deriving a full
                # row's plane columns is exact for a capacity-only
                # change too, just conservative.
                self._delta.note_rows(self._dirty_rows)
                self._delta.note_rows(self._dirty_caps)
            if self._dirty_rows:
                # A row needing the full upload supersedes its
                # capacity-only entry (the full delta includes CAP cols).
                self._dirty_caps -= self._dirty_rows
                rows = self._pad_rows(
                    np.fromiter(self._dirty_rows, np.int32)
                )
                try:
                    delta = self._row_delta(rows, ALL_COLUMNS)
                except PackingOverflow as e:
                    self._packing_rebuild(e)
                    return
                if self._inflights:
                    self._midflight_rows.update(self._dirty_rows)
                self._dirty_rows.clear()
                self.table = self._scatter(self.table, rows, delta)
                if self.mesh is not None:
                    _MESH_SCATTER.inc(cols="full")
            if self._dirty_caps:
                rows = self._pad_rows(
                    np.fromiter(self._dirty_caps, np.int32)
                )
                try:
                    delta = self._row_delta(rows, CAP_COLUMNS)
                except PackingOverflow as e:
                    self._packing_rebuild(e)
                    return
                self._dirty_caps.clear()
                self.table = self._scatter(self.table, rows, delta)
                if self.mesh is not None:
                    _MESH_SCATTER.inc(cols="cap")

    # ---- device-snapshot layout (snapshot/packing.py) ------------------

    def _table_to_device(self):
        """Build (or rebuild) the device table under the active layout,
        recording the HBM evidence gauge."""
        if self._packing_mode == "packed":
            if self._packing_spec is None:
                # Built against the CURRENT vocab so the label-fusion
                # fail-closed decision is made with real ids in view.
                self._packing_spec = build_packing_spec(
                    self.table_spec, self.host.vocab
                )
                if self._packing_spec is None:
                    # taint_slots too wide for the meta word.
                    _PACKING_FALLBACK.inc(reason="taint_slots")
                    self._packing_mode = "off"
            if self._packing_spec is not None:
                try:
                    table = pack_table_host(
                        self.host, self._packing_spec, self._table_sharding
                    )
                    self._note_table_bytes(table)
                    return table
                except PackingOverflow as e:
                    self._packing_fallback(e)
                    if self._packing_mode == "packed":
                        # Widened (label words split) — one retry.  A
                        # SECOND overflow on another field (e.g. a node
                        # past the int16 pods budget in the same rebuild
                        # window) must also fail closed to unpacked, not
                        # escape into the cycle loop.
                        try:
                            table = pack_table_host(
                                self.host, self._packing_spec,
                                self._table_sharding,
                            )
                            self._note_table_bytes(table)
                            return table
                        except PackingOverflow as e2:
                            self._packing_fallback(e2)
        table = self.host.to_device(self._table_sharding)
        self._note_table_bytes(table)
        return table

    @property
    def donation_inplace(self) -> bool | None:
        """Whether the runtime honored per-wave buffer donation in place
        (None until the first donating wave's probe runs).  On the mesh
        the probe is per-shard: it collects every shard's buffer
        pointers before the first wave and reports in-place when ANY
        shard's post-step buffer set overlaps the probed set
        (snapshot/packing.donation_probe).  The public read for bench/
        report surfaces — `commit_donation_total{inplace}` is the
        per-wave counter."""
        return self._donation_inplace

    @property
    def delta_enabled(self) -> bool:
        """Whether the delta-plane cache (engine/deltacache.py) is
        wired into this coordinator.  The public read for bench/report
        surfaces — `deltasched_waves_total{path}` is the per-wave
        counter."""
        return self._delta is not None

    def _note_table_bytes(self, table) -> None:
        layout = "packed" if is_packed(table) else "unpacked"
        other = "unpacked" if layout == "packed" else "packed"
        _TABLE_BYTES.set(hbm_bytes(table), layout=layout)
        _TABLE_BYTES.set(0, layout=other)

    def _row_delta(self, rows, columns) -> dict:
        """Dirty-row scatter payload under the live table's layout.
        Raises PackingOverflow when a packed width no longer fits
        (vocab drift) — the caller rebuilds fail-closed."""
        if is_packed(self.table):
            return pack_row_delta(self.host, rows, self.table.spec, columns)
        out = {}
        for c in columns:
            arr = getattr(self.host, c)[rows]
            if arr.dtype != np.bool_ and arr.dtype != np.int32:
                # Narrow mirror columns (node_table.mirror_dtype) widen
                # back to the unpacked device layout's int32.
                arr = arr.astype(np.int32)
            out[c] = arr
        return out

    def _packing_fallback(self, e: PackingOverflow) -> None:
        """Fail-closed layout widening (the vocab-drift gate, hotfeed's
        shape): label overflow splits the fused words (still packed);
        anything else drops to the unpacked layout.  Never truncates —
        the cost is one recompile under the wider layout."""
        _PACKING_FALLBACK.inc(reason=e.field)
        if (
            e.field in ("label_key", "label_val")
            and self._packing_spec is not None
            and self._packing_spec.fuse_labels
        ):
            log.warning("packed snapshot: %s; splitting label words", e)
            self._packing_spec = dataclasses.replace(
                self._packing_spec, fuse_labels=False
            )
        else:
            log.warning("packed snapshot: %s; falling back to unpacked", e)
            self._packing_mode = "off"
            self._packing_spec = None

    def _packing_rebuild(self, e: PackingOverflow) -> None:
        """A dirty-row delta no longer fits the packed layout: widen the
        layout, retire the pipeline (the host mirror is authoritative
        for everything EXCEPT the in-flight assume chain, so the waves
        must land before a wholesale re-upload), and rebuild.

        Cross-shard widening protocol (meshpack): the widening decision
        — split label words vs drop to unpacked — happens ONCE, here on
        the host (_packing_fallback mutates the one PackingSpec every
        shard shares), never per-shard; the quiesce retires every
        in-flight donating wave, and on the mesh the rebuild then
        BLOCKS on the retired table so every shard's in-flight donated
        buffers have settled before the wholesale re-upload replaces
        them — a shard still executing against donated HBM while the
        re-upload lands would be a per-shard layout skew."""
        self._packing_fallback(e)
        self._packing_rebuilding = True
        try:
            self._quiesce("packing")
        finally:
            self._packing_rebuilding = False
        if self.mesh is not None and self.table is not None:
            jax.block_until_ready(jax.tree.leaves(self.table))
        self._dirty_rows.clear()
        self._dirty_caps.clear()
        if self._delta is not None:
            # The wholesale re-upload resets the device request columns
            # to host truth — a state no journaled row set describes
            # (deltasched invalidation contract: packing rebuilds drop
            # the cache wholesale).
            self._delta.drop_all("packing")
        self.table = self._table_to_device()

    # ---- the cycle -----------------------------------------------------

    def _process_adjusts(self) -> None:
        """Batch-apply queued constraint-count corrections.

        Runs through the hotfeed encode cache (the pods being adjusted
        were all encoded at intake, so a CAS-rollback storm's re-encodes
        are template hits) and reuses one scratch arena instead of five
        fresh ``np.zeros`` per chunk — this path fires exactly when the
        system is already struggling (rollback storms, deletions), so
        its constant cost matters most."""
        if not self._pending_adjusts or self.constraints is None:
            return
        b = self.pod_spec.batch
        pending, self._pending_adjusts = self._pending_adjusts, []
        scr = self._adjust_scratch
        if scr is None:
            scr = self._adjust_scratch = {
                "node_row": np.zeros(b, np.int32),
                "zone": np.zeros(b, np.int32),
                "region": np.zeros(b, np.int32),
                "mask_node": np.zeros(b, bool),
                "mask_dom": np.zeros(b, bool),
            }
        for sign in (1, -1):
            group = [a for a in pending if a[4] == sign]
            for off in range(0, len(group), b):
                chunk = group[off : off + b]
                batch = self.encoder.encode_packed([g[0] for g in chunk])
                fields = commit_fields_np(batch.fields)
                for arr in scr.values():
                    arr[:] = 0
                node_row = scr["node_row"]
                zone = scr["zone"]
                region = scr["region"]
                mask_node = scr["mask_node"]
                mask_dom = scr["mask_dom"]
                for i, (_, node_name, z, r, _s) in enumerate(chunk):
                    row = self.host._row_of.get(node_name)
                    if row is not None:
                        node_row[i] = row
                        mask_node[i] = True
                    zone[i], region[i] = z, r
                    mask_dom[i] = True
                # jnp.array (copy=True), NOT asarray: CPU jax may alias
                # numpy memory zero-copy, and the scratch is mutated for
                # the next chunk while this dispatch is still in flight.
                self.constraints = self._adjust(
                    self.constraints, fields,
                    jnp.array(node_row), jnp.array(zone), jnp.array(region),
                    jnp.array(mask_node), jnp.array(mask_dom), sign=sign,
                )

    def submit_external(self, obj: dict, *, admitted: bool = False) -> None:
        """Thread-safe webhook-intake sink (control/webhook.py).

        The pod is staged and enters the queue at the next cycle; the
        store watch remains the fallback intake, deduplicated by key.

        With a loadshed controller installed this is an admission point:
        past the overload watermarks it raises ``loadshed.Overloaded``
        (lowest ``spec.priority`` shed first, hard ``queue_cap`` bound).
        ``admitted=True`` is the webhook's already-ran-admission marker
        (it checks pre-response so it can answer 429) — one pod must
        never draw, and count, two admission decisions.

        With a tenancy controller installed, admission is the
        weighted-fair per-tenant form (tenancy/admission.py): the
        global priority floor is replaced by token buckets, so overload
        degrades the over-share tenant instead of the cluster.
        """
        tracer = self._tracer
        t_in = time.perf_counter() if tracer.enabled else 0.0
        if not admitted:
            if self.tenancy is not None:
                self.tenancy.admission.check_admit_obj(
                    obj, point="coordinator"
                )
            elif self.loadshed is not None:
                self.loadshed.check_admit(
                    pod_priority_of(obj), point="coordinator"
                )
        if tracer.enabled:
            # The admit span anchors the trace at intake entry and
            # covers the admission decision; the tenant's bucket level
            # is the "how close to shed" evidence.  begin() no-ops when
            # the webhook already opened this trace at receipt — the
            # admit span is emitted EITHER way (it closes against
            # whichever anchor is live).
            key = pod_key_str_of_obj(obj)
            tracer.begin(key, t_in, source="external")
            attrs = {"point": "webhook" if admitted else "coordinator"}
            if self.tenancy is not None:
                tenant = tenant_of_obj(obj)
                attrs["tenant"] = tenant
                attrs["bucket"] = self.tenancy.admission.bucket_level(
                    tenant
                )
            tracer.emit(key, "admit", **attrs)
        with self._external_lock:
            self._external.append(obj)

    def _external_pending(self) -> int:
        """Staged webhook pods (locked read — the unlocked peek this
        replaced was a benign race on CPython, but the guard audit is
        only meaningful if the annotated discipline has no exceptions)."""
        with self._external_lock:
            return len(self._external)

    def _drain_external(self) -> None:
        with self._external_lock:
            if not self._external:
                return
            staged, self._external = self._external, []
        for obj in staged:
            try:
                pod = decode_pod_obj(obj, self.tracker)
            except Exception:
                _DECODE_ERRORS.inc(kind="pod")
                log.exception("undecodable webhook pod; skipping")
                continue
            if pod.node_name or pod.scheduler_name != self.scheduler_name:
                continue
            if self.intake_filter is not None and not self.intake_filter(
                pod.key
            ):
                continue
            if pod.key in self._queued_keys or pod.key in self._bound:
                continue
            self._queued_keys.add(pod.key)
            self._stage_or_queue(
                PendingPod(
                    pod, None, time.perf_counter(),
                    cpu_milli=pod.cpu_milli, mem_kib=pod.mem_kib,
                    key_str=pod.key,
                    key_bytes=pod_key(pod.namespace, pod.name),
                    priority=pod.priority,
                ),
                pod,
            )

    # ---- tenancy: gang staging, eviction, preemption --------------------

    def _stage_or_queue(self, rec: PendingPod, pod: PodInfo | None) -> None:
        """Queue a decoded intake pod — via gang staging when it carries
        gang labels and tenancy is on.  A gang's members enter the queue
        contiguously only once ALL are present; until then they hold no
        queue slot and no capacity.  Oversize gangs (bigger than one
        wave) degrade to plain scheduling, counted once per gang."""
        tracer = self._tracer
        if tracer.enabled:
            # No-op for a webhook pod (its trace opened at admission).
            tracer.begin(rec.key_str, rec.enqueued_at, source="intake")
        tn = self.tenancy
        if tn is not None and tn.policy.gang_enabled and pod is not None:
            g = gang_of_labels(pod.labels, pod.namespace)
            if g is not None:
                gid, size = g
                if size > self.pod_spec.batch:
                    if gid not in self._gang_oversize:
                        if len(self._gang_oversize) >= 1024:
                            # Bounded dedup memory: gang ids churn with
                            # namespaces; resetting just re-counts a
                            # repeat offender once more.
                            self._gang_oversize.clear()
                        self._gang_oversize.add(gid)
                        note_gang("oversize")
                        log.warning(
                            "gang %s size %d exceeds wave batch %d; "
                            "scheduling its pods as plain",
                            gid, size, self.pod_spec.batch,
                        )
                else:
                    rec.gang_id, rec.gang_size = gid, size
                    st = self._gang_staging.get(gid)
                    if st is None:
                        st = self._gang_staging[gid] = (size, {})
                    st[1][rec.key_str] = rec
                    if len(st[1]) >= st[0]:
                        del self._gang_staging[gid]
                        if tracer.enabled:
                            # Staging wait ends for every member the
                            # moment the last one completes the gang.
                            for m in st[1].values():
                                tracer.emit(
                                    m.key_str, "gang_stage",
                                    gang=gid, size=st[0],
                                )
                        self.queue.extend(st[1].values())
                    return
        self.queue.append(rec)

    def _gang_staged(self) -> int:
        """Pods parked in gang staging (counts toward the load signal —
        they are demand the cluster has accepted but not yet queued)."""
        return sum(len(st[1]) for st in self._gang_staging.values())

    def _evict_bound(
        self,
        key_str: str,
        *,
        into: PendingPod | None = None,
        adjust: bool = True,
        count_eviction: bool = True,
        path: str = "evict",
    ) -> PendingPod | None:
        """CAS a bound pod's stored object back to pending and undo its
        host-mirror accounting — the eviction half of preemption and of
        gang all-or-none release.

        The byte-level inverse of the bind: a spliced object is
        un-spliced (stored bytes return EXACTLY to their pre-bind
        encoding), anything else takes the JSON path.  The freed row is
        marked dirty so the next sync re-uploads host truth — in-flight
        waves keep their pipedream guarantees (a reclaimed row is never
        aliased: rows are not removed here, only their usage shrinks,
        which is the conservative direction for any wave in flight).

        Returns ``(evicted, rec)``: ``evicted`` reports whether the
        bind was actually reverted (callers MUST account on this flag —
        a post-eviction deletion still reverted the bind even though no
        requeue record exists); ``rec`` is the requeue-ready PendingPod
        at the post-eviction revision (``into`` refreshed in place when
        given), or None when there is nothing left to requeue (already
        unbound, deleted, or a persistent concurrent writer — the watch
        stream settles whatever remains).  The CAS retries a few times
        against fresh revisions so a racing status writer cannot leave
        a gang member half-released.  ``adjust=False`` is for
        wave-local gang release, where the caller rolls the device
        constraint commit back through the wave's own failed-mask
        instead.
        """
        rec = self._bound.get(key_str)
        if rec is None:
            return False, None
        node_name, cpu, mem, zone, region, keep = rec
        ns, name = key_str.split("/", 1)
        kb = pod_key(ns, name)
        ok = False
        for _attempt in range(3):
            cur = self.store.get(kb)
            if cur is None:
                return False, None
            value = unsplice_node_name(cur.value)
            if value is None:
                try:
                    obj = json.loads(cur.value)
                except Exception:
                    _DECODE_ERRORS.inc(kind="pod")
                    log.exception(
                        "undecodable bound pod at eviction; skipping"
                    )
                    return False, None
                obj.get("spec", {}).pop("nodeName", None)
                value = json.dumps(obj, separators=(",", ":")).encode()
            ok, _, _ = self._fenced_cas(
                kb, value, required_mod=cur.mod_revision, path=path
            )
            if ok:
                break
        if not ok:
            return False, None
        self._bound.pop(key_str, None)
        self._bind_meta.pop(key_str, None)
        self._victims_drop(key_str, node_name)
        if node_name in self.host._row_of:
            self.host.remove_pod(node_name, cpu, mem)
            self._dirty_rows.add(self.host.row_of(node_name))
        if adjust and keep is not None and self.constraints is not None:
            self._pending_adjusts.append((keep, node_name, zone, region, -1))
        if count_eviction:
            note_eviction()
        fresh = self.store.get(kb)
        if fresh is None:
            # Deleted between the CAS and the re-get: the bind WAS
            # reverted; there is just nothing to requeue.
            return True, None
        p = into
        if p is None:
            pod = decode_pod(fresh.value, self.tracker)
            p = PendingPod(
                pod, fresh.mod_revision, time.perf_counter(),
                cpu_milli=pod.cpu_milli, mem_kib=pod.mem_kib,
                key_str=key_str, raw=fresh.value, key_bytes=kb,
                priority=pod.priority,
            )
        else:
            p.mod_revision = fresh.mod_revision
            p.raw = fresh.value
        self._queued_keys.add(key_str)
        return True, p

    def _preempt_eligible(self, p: PendingPod) -> bool:
        """Cheap gates before any preemption work happens for a pod."""
        tn = self.tenancy
        return (
            tn is not None
            and tn.policy.preempt_enabled
            and p.priority >= tn.policy.preempt_min_priority
            and p.attempts + 1 >= tn.policy.preempt_after_attempts
        )

    def _victims_index(self) -> _VictimRows:
        """Per-wave view of all preemptable bound pods grouped by row —
        built at most ONCE per wave from the incrementally-maintained
        by-node index (select_preemption applies the per-preemptor
        priority filter itself).  Gang-bound pods were excluded at
        insert time: evicting one member would strand its gang bound —
        the exact partial state gangs exist to prevent.  The current
        bind sequence fences the view: this wave's own preemption
        binds (noted later) never become victims within the wave."""
        return _VictimRows(
            self._victims_by_node, self.host._row_of, self._bind_seq,
        )

    def _victims_index_full(self) -> dict[int, list[Victim]]:
        """The pre-megarow full ``_bound.items()`` scan, kept as the
        differential reference: the incremental index must materialize
        to exactly this (tests/test_megarow.py gates it under a
        preemption drill).  Never called on the wave path."""
        victims_by_row: dict[int, list[Victim]] = {}
        row_of = self.host._row_of
        for key, rec in self._bound.items():
            meta = self._bind_meta.get(key)
            if meta is None:
                prio, seq, tenant, gang = 0, 0, tenant_of_key(key), ""
            else:
                prio, seq, tenant, gang = meta
            if gang:
                continue
            node_name = rec[0]
            row = row_of.get(node_name)
            if row is None:
                continue
            victims_by_row.setdefault(row, []).append(Victim(
                key, node_name, row, rec[1], rec[2], prio, seq, tenant,
            ))
        return victims_by_row

    def _try_preempt(
        self, p: PendingPod, victims_by_row: _VictimRows
    ) -> bool:
        """Preemption for a pod the wave found no feasible row for:
        select victims (tenancy/preempt.py — lowest priority first,
        other-tenant before same-tenant, newest bind first; gang-bound
        pods never selected), evict them through the store CAS +
        dirty-row machinery, bind the preemptor host-side on the
        cleared node (argmax-free: the selected node IS the placement,
        a pure function of the host mirror, which is what makes the
        drill's byte-identical replay possible), and requeue every
        victim.  ``victims_by_row`` is the caller's per-wave index
        (_victims_index); successfully evicted victims are removed from
        it so later preemptors in the same wave see current state.
        Returns True when the preemptor bound."""
        tn = self.tenancy
        pod = p.ensure_pod()
        tenant = tenant_of_pod(pod)
        nodes = self._fallback_nodes()
        if not nodes:
            return False
        host = self.host
        usage = {
            row: (
                int(host.cpu_req[row]), int(host.mem_req[row]),
                int(host.pods_req[row]),
            )
            for row, _ in nodes
        }
        choice = select_preemption(
            pod, tenant, p.priority, nodes, usage, victims_by_row,
        )
        if choice is None:
            return False
        if tn.policy.log_preemptions and len(self.preempt_log) < 1024:
            self.preempt_log.append({
                "pod": p.key_str,
                "priority": p.priority,
                "tenant": tenant,
                "node": choice.node,
                "row": choice.row,
                "victims": [v.key for v in choice.victims],
                "usage": {str(r): list(u) for r, u in usage.items()},
                "candidates": {
                    str(r): [dataclasses.astuple(v) for v in vs]
                    for r, vs in victims_by_row.items()
                },
            })
        tracer = self._tracer
        for v in choice.victims:
            evicted, rec = self._evict_bound(v.key, path="preempt")
            if not evicted:
                # A persistent concurrent writer beat the eviction CAS:
                # abort this attempt (capacity already freed stays
                # freed — the requeued victims rebind elsewhere); the
                # preemptor retries through the normal path.
                return False
            if rec is not None:
                if tracer.enabled:
                    # The evicted victim re-enters the lifecycle: a
                    # fresh chain anchored at its requeue time.
                    tracer.begin(
                        rec.key_str, rec.enqueued_at, source="evict",
                    )
                self.queue.append(rec)
            # The eviction already dropped this pod from the by-node
            # index (_evict_bound -> _victims_drop), and the per-wave
            # _VictimRows view reads that index live — later preemptors
            # in the same wave see current state with no manual repair.
        if not self._bind(p, choice.node):
            return False
        _BIND_LATENCY.observe(time.perf_counter() - p.enqueued_at)
        if tracer.enabled:
            # Host-side preemption bind: the chain closes here (the
            # wave's retire pass will find no live trace and skip it).
            tracer.finish(
                p.key_str, "bind", outcome="preempt",
                victims=len(choice.victims),
            )
        # The device never committed this bind: same repair contract as
        # the breaker fallback — dirty the row, queue the constraint
        # correction a device commit would have applied.
        self._dirty_rows.add(choice.row)
        if self.constraints is not None:
            rec = self._bound.get(p.key_str)
            if rec is not None and rec[5] is not None:
                self._pending_adjusts.append(
                    (rec[5], rec[0], rec[3], rec[4], 1)
                )
        return True

    def _wave_fail(self, p: PendingPod) -> None:
        """Per-pod wave failure: gang members defer to the gang's
        all-or-none settlement (_resolve_gangs requeues the group as a
        unit); everything else takes the normal retry/backoff path."""
        if self.tenancy is not None and p.gang_id:
            return
        self._retry(p)

    def _resolve_gangs(self, batch_pods, bound_ok, rows, failed) -> int:
        """All-or-none gang settlement at wave retire: a gang with every
        member bound is admitted; any failure releases every provisional
        bind (store CAS back to pending, host accounting undone) and
        requeues the gang as a unit — partial capacity never survives
        the wave-epoch window this wave retired in.  Returns the number
        of reverted binds (the caller subtracts them from its bound
        count so drivers' ledgers stay truthful).

        ``rows`` distinguishes device-committed binds (row >= 0: the
        wave's constraint commit is rolled back via ``failed``) from
        host-side preemption binds (row < 0: rolled back through the
        queued-adjust path, mirroring the +1 the preempt bind queued).
        """
        if self.tenancy is None or not self.tenancy.policy.gang_enabled:
            return 0
        gangs: dict[str, list[int]] = {}
        for i, p in enumerate(batch_pods):
            if p.gang_id:
                gangs.setdefault(p.gang_id, []).append(i)
        reverted = 0
        for idxs in gangs.values():
            if all(bound_ok[i] for i in idxs):
                note_gang("bound")
                continue
            members = []
            for i in idxs:
                p = batch_pods[i]
                if bound_ok[i]:
                    device_committed = bool(rows[i] >= 0)
                    evicted, _rec = self._evict_bound(
                        p.key_str, into=p,
                        adjust=not device_committed,
                        count_eviction=False,
                    )
                    if evicted:
                        # Settle on the FLAG, not the requeue record: a
                        # member deleted right after the eviction CAS
                        # still had its bind (and constraint commit)
                        # reverted and must not stay counted as bound.
                        reverted += 1
                        bound_ok[i] = False
                        if device_committed:
                            failed[i] = True
                    elif p.key_str in self._bound:
                        # Eviction persistently lost: the member stays
                        # bound — keep it OUT of the requeue so the
                        # all-or-none contract degrades loudly instead
                        # of double-scheduling a still-bound pod.
                        log.warning(
                            "gang member %s could not be released "
                            "(eviction CAS lost); leaving it bound",
                            p.key_str,
                        )
                        continue
                members.append(p)
            self._requeue_gang(members)
        return reverted

    def _requeue_gang(self, members: list[PendingPod]) -> None:
        """Requeue a failed gang as a unit: refresh every member from
        the store (same contract as _retry — a stale revision or an
        external bind must not ride into the next wave), then either
        park the whole gang unschedulable (retry budget spent) or heap
        it for a shared backoff and contiguous re-entry."""
        alive: list[PendingPod] = []
        for p in members:
            p.attempts += 1
            cur = self.store.get(p.key_bytes)
            if cur is None:
                self._queued_keys.discard(p.key_str)
                continue
            fresh = decode_pod(cur.value, self.tracker)
            if fresh.node_name:
                # Bound externally while we were settling: theirs now.
                self._queued_keys.discard(p.key_str)
                continue
            p.pod = fresh
            p.cpu_milli = fresh.cpu_milli
            p.mem_kib = fresh.mem_kib
            p.mod_revision = cur.mod_revision
            p.raw = cur.value
            p.priority = fresh.priority
            alive.append(p)
        if not alive:
            return
        pol = self.retry_policy
        worst = max(p.attempts for p in alive)
        if worst >= pol.max_attempts:
            for p in alive:
                if self._tracer.enabled:
                    self._trace_gaveup.add(p.key_str)
                _PODS_SCHEDULED.inc(outcome="unschedulable")
                note_give_up("coordinator.bind")
                self.unschedulable[p.key_str] = p.ensure_pod()
                # Keys stay held: the eviction echo of a released
                # provisional bind must not resurrect a parked gang
                # member as a plain pod (deletion still clears the key).
                self._queued_keys.add(p.key_str)
            note_gang("parked")
            return
        for p in alive:
            _PODS_SCHEDULED.inc(outcome="retry")
            note_retry("coordinator.bind")
            self._queued_keys.add(p.key_str)
        self._backoff_seq += 1
        heapq.heappush(self._gang_parked, (
            time.perf_counter() + pol.delay_for(worst, self._retry_rng),
            self._backoff_seq, alive,
        ))
        note_gang("requeued")

    def _encoder_for(self, n: int) -> PodBatchHost:
        """Smallest power-of-two batch bucket holding n pods (clamped to
        pod_spec.batch, which need not be a power of two)."""
        if not self.adaptive_batch:
            return self.encoder
        if self.loadshed is not None and self.loadshed.degraded:
            # Overload: widen the batch window.  Small buckets buy p50
            # latency at the cost of waves-per-pod — exactly the wrong
            # trade while the queue is the problem.
            return self.encoder
        b = self.min_batch
        while b < n:
            b <<= 1
        if b > self.pod_spec.batch:
            return self.encoder
        enc = self._encoders.get(b)
        if enc is None:
            enc = HotPodBatchHost(
                dataclasses.replace(self.pod_spec, batch=b),
                self.table_spec, self.host.vocab,
                cache=self.encode_cache,
            )
            self._encoders[b] = enc
        return enc

    def _release_backoff(self) -> None:
        """Move retrying pods (and whole parked gangs) whose backoff has
        expired into the queue; gang members re-enter contiguously so
        they still ride one wave."""
        if not self._backoff and not self._gang_parked:
            return
        now = time.perf_counter()
        while self._backoff and self._backoff[0][0] <= now:
            _, _, p = heapq.heappop(self._backoff)
            self.queue.append(p)
        while self._gang_parked and self._gang_parked[0][0] <= now:
            _, _, members = heapq.heappop(self._gang_parked)
            self.queue.extend(members)

    def backoff_wait_s(self) -> float | None:
        """Seconds until the earliest parked retry (pod or gang) is due
        (None when nothing is backing off) — drivers idle-wait on this
        instead of spinning cycles against an empty queue."""
        heads = []
        if self._backoff:
            heads.append(self._backoff[0][0])
        if self._gang_parked:
            heads.append(self._gang_parked[0][0])
        if not heads:
            return None
        return max(0.0, min(heads) - time.perf_counter())

    def _take_batch(self):
        """Pop and encode up to one batch of pending pods; (None, None)
        when the queue is empty.  A feed-staged batch (encoded in the
        worker while the last wave was in flight) is claimed first; the
        claim fails closed — queue prefix changed, vocab generation
        moved, worker error — and the inline cached encode covers it."""
        self._release_backoff()
        if not self.queue:
            return None, None
        batch_pods: list[PendingPod] = []
        cur_gang = ""
        while self.queue and len(batch_pods) < self.pod_spec.batch:
            head = self.queue[0]
            if (
                head.gang_id
                and head.gang_id != cur_gang
                and head.gang_size > self.pod_spec.batch - len(batch_pods)
            ):
                # A gang never splits across a batch boundary: close the
                # batch early and let the gang open the next wave whole.
                break
            cur_gang = head.gang_id
            batch_pods.append(self.queue.popleft())
        if not batch_pods:
            return None, None
        # graftlint: disable=hotfeed-no-per-pod-python (O(pods) set bookkeeping for popped keys)
        for p in batch_pods:
            self._queued_keys.discard(p.key_str)
        tracer = self._tracer
        tr_on = tracer.enabled
        if tr_on:
            t_pop = time.perf_counter()
            hits0, miss0 = cache_counts()
        claimed = False
        with self._stage("encode"):
            batch = None
            if self._feed is not None:
                batch = self._feed.claim(
                    batch_pods, self.host.vocab.feed_generation()
                )
                claimed = batch is not None
            if batch is None:
                batch = encode_batch(
                    self._encoder_for(len(batch_pods)), batch_pods
                )
        if tr_on:
            t_enc = time.perf_counter()
            hits1, miss1 = cache_counts()
            path = "feed" if claimed else "inline"
            dh, dm = hits1 - hits0, miss1 - miss0
            # graftlint: disable=hotfeed-no-per-pod-python (behind the tracer.enabled guard; O(pods) span bookkeeping on sampled runs only)
            for p in batch_pods:
                tracer.emit(
                    p.key_str, "queue_wait", t=t_pop, attempts=p.attempts
                )
                tracer.emit(
                    p.key_str, "encode", t=t_enc, path=path,
                    cache_hits=dh, cache_misses=dm,
                )
        return batch_pods, batch

    def _next_window(self, rows: int) -> int:
        i = self._window_i
        self._window_i += 1
        return sample_offset_for(i, self._window_nodes, rows)

    def _active_knobs(self):
        """(profile, sample_rows) for the next wave: the configured pair
        when HEALTHY, the degraded pair (filter-only constraint plugins,
        shrunken score window) while the controller reports pressure."""
        if self.loadshed is not None and self.loadshed.degraded:
            self.loadshed.note_degraded_cycle()
            return self._profile_degraded, self._sample_rows_degraded
        return self.profile, self._sample_rows

    # ---- deltasched: plane-cached waves (engine/deltacache.py) ---------

    @staticmethod
    def _delta_key(p: PendingPod):
        """The pod's plane-cache shape key (snapshot/hotfeed.shape_key),
        or None for uncacheable shapes.  Native fast-lane pods
        (pod=None) are canonical label-less plain pods by construction
        — their key needs no PodInfo materialization at all."""
        if p.pod is None:
            return (PLAIN, p.cpu_milli, p.mem_kib)
        return shape_key(p.pod)

    def _plan_delta(self, batch_pods, batch):
        """Plan this wave's delta path: shape-key lookups, plane fills
        for recurring cold shapes (dispatched here, BEFORE the wave, so
        a filled wave can still go delta), and the journaled dirty
        slice.  Returns the WavePlan when the wave may run the delta
        step, None for the ordinary full pass."""
        cache = self._delta
        gen = self.host.vocab.generation()
        cache.check_generation(gen)
        plan = cache.plan(
            [self._delta_key(p) for p in batch_pods], batch.batch
        )
        if plan.fill_idx:
            try:
                reps = [batch_pods[i].ensure_pod() for i in plan.fill_idx]
                fill_pb = self._delta_fill_enc.encode_packed(reps)
            except ValueError:
                # Representative shapes overflowed a fill-batch bound
                # (e.g. distinct selector keys past PodSpec.query_keys
                # across shapes): un-allocate and take the full pass —
                # never guess at a partial fill.
                cache.abort_fills(plan)
                return None
            fs = np.full(cache.fill_batch, cache.slots, np.int32)
            fs[: len(plan.fill_slots)] = plan.fill_slots
            try:
                planes = fill_shape_planes(
                    self.table, fill_pb, jnp.asarray(fs),
                    cache.planes(gen),
                    profile=self.profile, chunk=self.chunk, mesh=self.mesh,
                )
            except Exception:
                # The fill executable donates the plane buffers; after a
                # failed dispatch they are in an unknown consumed state.
                # Reset fail-closed and re-raise for the breaker.
                cache.reset("fill-error")
                raise
            cache.commit(*planes)
            cache.note_fill(plan)
        return plan if plan.slot_ids is not None else None

    def _launch_delta(self, batch, subkey, plan):
        """Dispatch the delta-wave executable: full kernel over the
        dirty slice ∪ in-flight bind rows (each unretired wave's
        device-resident rows_dev — consumed on-stream, no host sync),
        scatter-merged into the cached planes, hashed top-k over the
        merged planes, shared greedy/commit epilogue.  Constraint state
        is untouched: delta waves carry only constraint-termless pods,
        whose commit increments are identically zero.

        Returns (table, asg, rows_dev, index_flag_dev, attempted,
        touched): the last three feed the wave's retire-time
        ``deltasched_index_*`` metric stamping (flag is a device scalar
        — fetched only at _complete, never here)."""
        cache = self._delta
        gen = self.host.vocab.generation()
        planes = cache.planes(gen)
        index = flag = None
        attempted = False
        touched = (0, 0)
        if cache.index_k:
            index = cache.index_state(gen)
            # Whether the in-step index update will run is a trace-time
            # SHAPE decision inside the executable (pow2-padded dirty
            # width vs the cap); mirror it host-side for the metric —
            # an oversized wave runs the plane tail + rebuild, never
            # the index tail, so it is not an "attempt".
            dirty_w = len(plan.dirty) + sum(
                int(w.rows_dev.shape[0]) for w in self._inflights
            )
            attempted = dirty_w <= cache.index_dirty_cap
            if not attempted:
                note_index_oversized()
            # Touched-rows accounting for the sublinear claim (sched_bench
            # --delta-profile): index tail visits the dirty slice plus K
            # index entries per pod; the plane tail scans all N rows plus
            # the dirty slice.
            touched = (
                dirty_w + cache.index_k * batch.batch,
                cache.num_rows + dirty_w,
            )
        try:
            out = schedule_batch_delta(
                self.table, batch, subkey,
                profile=self.profile,
                slot_ids=jnp.asarray(plan.slot_ids),
                planes=planes,
                dirty=jnp.asarray(plan.dirty),
                inflight_rows=tuple(w.rows_dev for w in self._inflights),
                chunk=self.chunk, k=self.k,
                mesh=self.mesh, donate=self._donate,
                backend=self.backend,
                stratum_bits=self.stratum_bits,
                index=index,
                rep_idx=(
                    jnp.asarray(plan.rep_idx) if index is not None else None
                ),
                rebuild_slots=(
                    jnp.asarray(plan.rebuild_slots)
                    if index is not None else None
                ),
                index_dirty_cap=cache.index_dirty_cap,
            )
        except Exception:
            # Donated buffers are in an unknown state after a failed
            # dispatch; reset fail-closed and re-raise for the breaker.
            cache.reset("dispatch-error")
            raise
        if index is not None:
            table, asg, rows_dev, planes, index, flag = out
            cache.commit(planes[0], planes[1], plan, index=index)
        else:
            table, asg, rows_dev, planes = out
            cache.commit(planes[0], planes[1], plan)
        return table, asg, rows_dev, flag, attempted, touched

    def _launch(self, batch_pods, batch):
        """Enqueue the device step for an encoded batch (async — no
        device→host transfer is forced).  Faultline hook
        ``coordinator.cycle``/``dispatch`` fires here: ``slow_cycle`` /
        ``delay`` lengthen the cycle (feeding the loadshed latency
        signal); every failure kind — ``stall`` is the canonical one —
        raises before the device is touched, so the caller's breaker
        accounting sees a clean dispatch failure with no state to roll
        back."""
        t_start = time.perf_counter()
        if faultline.active_injector().plan.faults:
            d = faultline.decide("coordinator.cycle", "dispatch")
            if d is not None:
                if d.kind in ("delay", "slow_cycle"):
                    time.sleep(d.delay_s)
                else:
                    raise faultline.InjectedFault(d)
        profile, sample_rows = self._active_knobs()
        self.key, subkey = jax.random.split(self.key)
        delta_plan = None
        if (
            self._delta is not None
            and sample_rows is None
            and self._row_mask_dev is None
            and profile is self.profile
            and self.table is not None
        ):
            # Delta eligibility is wave-local and conservative: only the
            # full-scan production shape reuses planes (sampled windows,
            # degraded profiles and masked candidate views all compute
            # DIFFERENT planes than the cache holds).  Both backends
            # qualify — the pallas delta tail (delta_plane_topk) landed
            # with the candidate index.
            delta_plan = self._plan_delta(batch_pods, batch)
        probe_ptr = None
        if self._donate and self._donation_inplace is None:
            # One-time donation probe (first wave): did the runtime alias
            # the donated hot planes in place?  Reading the output
            # pointers below syncs on that wave once — never again.
            try:
                probe_ptr = donation_probe(self.table)
            except Exception:  # graftlint: disable=broad-except (probe is evidence-only; any exotic array type just reports inplace=no)
                self._donation_inplace = False
        idx_flag = None
        idx_attempted = False
        idx_touched = (0, 0)
        with _CYCLE_TIME.time(stage="device"):
            if delta_plan is not None:
                (
                    self.table, asg, rows_dev,
                    idx_flag, idx_attempted, idx_touched,
                ) = self._launch_delta(batch, subkey, delta_plan)
            else:
                self.table, self.constraints, asg, rows_dev = schedule_batch_packed(
                    self.table, batch, subkey,
                    profile=profile, constraints=self.constraints,
                    chunk=self.chunk, k=self.k, backend=self.backend,
                    sample_rows=sample_rows,
                    sample_offset=(
                        self._next_window(sample_rows) if sample_rows else 0
                    ),
                    row_mask=self._row_mask_dev,
                    mesh=self.mesh,
                    donate=self._donate,
                    stratum_bits=self.stratum_bits,
                )
        if probe_ptr is not None:
            try:
                self._donation_inplace = donation_inplace(
                    self.table, probe_ptr
                )
            except Exception:  # graftlint: disable=broad-except (probe is evidence-only)
                self._donation_inplace = False
        if self._donate:
            _DONATION.inc(
                inplace="yes" if self._donation_inplace else "no"
            )
        # Start the device->host copy of the bind decision now: by the
        # time _complete runs (a drain + encode later), the bytes are
        # already on the host and device_get returns without paying the
        # relay round trip.
        try:
            rows_dev.copy_to_host_async()
        # Best-effort prefetch: some array types/backends simply lack the
        # async copy; the sync device_get in _complete is the fallback.
        except Exception:  # graftlint: disable=broad-except
            pass
        # begin_wave stamps the snapshot epoch AFTER the dispatch above:
        # rows removed from here on quarantine until this wave retires.
        wave = Wave(
            batch_pods, batch, asg, rows_dev, t_start,
            epoch=self.host.begin_wave(),
            depth=len(self._inflights) + 1,
            path="delta" if delta_plan is not None else "full",
            index_flag_dev=idx_flag,
            index_attempted=idx_attempted,
            index_touched=idx_touched,
        )
        tracer = self._tracer
        if tracer.enabled:
            # Encode end -> dispatch: the pipeline-slot wait (in the
            # pipelined cycle this includes retiring the oldest wave).
            for p in batch_pods:
                tracer.emit(p.key_str, "dispatch_wait", t=t_start)
        return wave

    def _loadshed_tick(self) -> None:
        """Feed the health controller one cycle's signals (no-op without
        a controller).  Runs after the intake drains so queue depth is
        current, before _take_batch so this wave already schedules with
        the state the signals imply."""
        ls = self.loadshed
        if ls is None:
            return
        conflicts = _PODS_SCHEDULED.value(outcome="conflict")
        resyncs = _RESYNCS.value()
        ls.tick(Signals(
            # Staged gang members are accepted demand too — a thousand
            # half-assembled gangs must register as load, not hide.
            queue_depth=(
                len(self.queue) + self._external_pending()
                + self._gang_staged()
            ),
            backoff_depth=(
                len(self._backoff)
                + sum(len(m) for _, _, m in self._gang_parked)
            ),
            conflicts=int(conflicts - self._sig_conflicts),
            resyncs=int(resyncs - self._sig_resyncs),
            cycle_s=self._last_cycle_s,
        ))
        self._sig_conflicts = conflicts
        self._sig_resyncs = resyncs
        if self.tenancy is not None:
            # Refill the per-tenant admission buckets: this cycle's
            # admit budget is one wave's worth of pods, split by weight
            # over the tenants that actually offered load.
            self.tenancy.admission.tick(capacity=self.pod_spec.batch)

    def _requeue_front(self, batch_pods) -> None:
        """Put an un-launched batch back at the head of the queue (the
        pods were popped by _take_batch but never reached a device wave,
        so no accounting exists to undo)."""
        for p in reversed(batch_pods):
            self._queued_keys.add(p.key_str)
            self.queue.appendleft(p)

    def _take_pods(self, n: int) -> list[PendingPod]:
        """Pop up to ``n`` pending pods WITHOUT encoding them — the
        open-breaker fallback path never touches the device, so paying
        a full-batch encode only to discard it would tax exactly the
        cycles where the system is already struggling."""
        self._release_backoff()
        pods: list[PendingPod] = []
        cur_gang = ""
        rotated: set[str] = set()
        while self.queue and len(pods) < n:
            head = self.queue[0]
            if (
                head.gang_id
                and head.gang_id != cur_gang
                and head.gang_size > n - len(pods)
            ):
                if pods or head.gang_id in rotated:
                    break
                # Emergency lane: a gang that can NEVER fit this cap
                # (fallback_batch < gang size) must not wedge the queue
                # behind it for the whole breaker-open window — rotate
                # it to the back intact and keep draining.  Once per
                # gang per call, so a gang-only queue still terminates.
                rotated.add(head.gang_id)
                moved: list[PendingPod] = []
                while self.queue and self.queue[0].gang_id == head.gang_id:
                    moved.append(self.queue.popleft())
                self.queue.extend(moved)
                continue
            cur_gang = head.gang_id
            p = self.queue.popleft()
            self._queued_keys.discard(p.key_str)
            pods.append(p)
        return pods

    def _fallback_nodes(self) -> list:
        """Decoded ``(row, NodeInfo)`` candidates for the breaker-open
        oracle fallback, ascending row (ties break earlier-row like the
        device path's earlier-index rule).

        Built from the incremental ``_node_infos`` index (maintained at
        the watch-drain decode sites), so a node-gen bump costs
        O(changed rows), not an O(N) store decode per generation.  Rows
        the index has never seen — the bulk-ingest remainder from
        bootstrap/resync — are seeded from ONE store decode, paid once
        ever (per resync), after which churn keeps the index current
        event by event.  Differentially gated against the full decode
        (``_fallback_nodes_full``) in tests/test_loadshed.py."""
        if (
            self._fallback_cache is not None
            and self._fallback_cache[0] == self._node_gen
        ):
            return self._fallback_cache[1]
        row_of = self.host._row_of
        infos = self._node_infos
        missing = {name for name in row_of if name not in infos}
        if missing:
            kvs, _ = list_prefix(self.store, NODES_PREFIX)
            for kv in kvs:
                name = kv.key[len(NODES_PREFIX):].decode()
                if name not in missing:
                    continue
                try:
                    infos[name] = decode_node(kv.value)
                except Exception:
                    # Same quarantine contract as the watch drains: one
                    # malformed object must not silently shrink the
                    # emergency fallback's candidate set.
                    _DECODE_ERRORS.inc(kind="node")
                    log.exception(
                        "undecodable node in fallback seed; skipping"
                    )
        out = []
        mask = self._row_mask_np
        for name, row in row_of.items():
            nd = infos.get(name)
            if nd is None:
                continue
            if mask is not None and not mask[row]:
                continue
            out.append((row, nd))
        out.sort(key=lambda t: t[0])
        self._fallback_cache = (self._node_gen, out)
        return out

    def _fallback_nodes_full(self) -> list:
        """The pre-watchplane full store decode, kept UNCACHED as the
        differential oracle for the incremental index (the victims-
        index precedent: megarow's ``_victims_index_full``)."""
        out = []
        kvs, _ = list_prefix(self.store, NODES_PREFIX)
        mask = self._row_mask_np
        for kv in kvs:
            try:
                nd = decode_node(kv.value)
            except Exception:
                _DECODE_ERRORS.inc(kind="node")
                log.exception("undecodable node in fallback list; skipping")
                continue
            row = self.host._row_of.get(nd.name)
            if row is None:
                continue
            if mask is not None and not mask[row]:
                continue
            out.append((row, nd))
        out.sort(key=lambda t: t[0])
        return out

    def _fallback_schedule(self, batch_pods) -> int:
        """Breaker-open path: bind a small batch through the host-side
        oracle scheduler (k8s1m_tpu/oracle) so scheduling never fully
        stops while the device is wedged.  Greedy and sequential against
        the live host usage — for a given snapshot the choices are a
        pure function of the pod order (argmax oracle_score, earlier row
        wins ties), which is what makes the drill's byte-identical
        replay check possible.  Pods past ``fallback_batch`` go back to
        the queue head; binds mark their row dirty so the device table
        learns the usage at the next sync (the device never saw these
        binds commit)."""
        cap = (
            self.breaker.config.fallback_batch
            if self.breaker is not None else len(batch_pods)
        )
        take = batch_pods[:cap]
        self._requeue_front(batch_pods[len(take):])
        nodes = self._fallback_nodes()
        host = self.host
        weights = (
            self.profile.least_allocated, self.profile.balanced_allocation,
            self.profile.taint_toleration, self.profile.node_affinity,
        )
        nbound = 0
        bound_ok = np.zeros(len(take), bool)
        with _CYCLE_TIME.time(stage="fallback"):
            for pi, p in enumerate(take):
                pod = p.ensure_pod()
                best_row, best_score, best_name = -1, -1, None
                for row, nd in nodes:
                    req = (
                        int(host.cpu_req[row]), int(host.mem_req[row]),
                        int(host.pods_req[row]),
                    )
                    if not oracle_feasible(nd, pod, req):
                        continue
                    s = oracle_score(
                        nd, pod, req,
                        taint_slots=self.table_spec.taint_slots,
                        weights=weights,
                    )
                    if s > best_score:
                        best_row, best_score, best_name = row, s, nd.name
                if best_name is None or not self._bind(p, best_name):
                    self._wave_fail(p)
                    continue
                nbound += 1
                bound_ok[pi] = True
                FALLBACK_BINDS.inc()
                _BIND_LATENCY.observe(time.perf_counter() - p.enqueued_at)
                tracer = self._tracer
                if tracer.enabled:
                    # Breaker-open oracle bind: no wave ever launched,
                    # so the whole journey settles in one bind span.
                    tracer.finish(p.key_str, "bind", outcome="fallback")
                # The device table never committed this bind: dirty the
                # row so the next sync re-uploads the host truth, and
                # queue the constraint-count correction a device commit
                # would have applied.
                self._dirty_rows.add(best_row)
                if self.constraints is not None:
                    rec = self._bound.get(p.key_str)
                    if rec is not None and rec[5] is not None:
                        self._pending_adjusts.append(
                            (rec[5], rec[0], rec[3], rec[4], 1)
                        )
            # Fallback binds are host-side (no device commit): gang
            # settlement releases through the queued-adjust path.
            nbound -= self._resolve_gangs(
                take, bound_ok,
                np.full(len(take), -1, np.int64),
                np.zeros(len(take), bool),
            )
            tracer = self._tracer
            if tracer.enabled:
                # No wave-retire pass runs on the breaker path: close
                # the chains of pods that spent their retry budget here.
                for p in take:
                    if p.key_str in self._trace_gaveup:
                        self._trace_gaveup.discard(p.key_str)
                        tracer.finish(
                            p.key_str, "requeue",
                            outcome="unschedulable", attempts=p.attempts,
                        )
        return nbound

    def _complete(self, inflight: Wave) -> int:
        """Bind half: sync the assignment to host, CAS the binds back,
        roll back conflicts (CAS losses, rows tombstoned mid-flight)."""
        batch_pods, batch, asg, rows_dev, t_start = (
            inflight.batch_pods, inflight.batch, inflight.asg,
            inflight.rows_dev, inflight.t_start,
        )
        with self._stage("sync_out"):
            # ONE device_get per wave: through a remote relay each fetch
            # is a full round trip (~tens of ms), so the bind decision
            # comes back as a single packed i32[B] (-1 = unbound).
            node_row = jax.device_get(rows_dev)
        t_sync = time.perf_counter()
        if inflight.index_flag_dev is not None:
            # The which-tail-ran flag is fetched at retire (the wave's
            # sync point) so the launch path never blocks on it.
            note_index_wave(
                int(jax.device_get(inflight.index_flag_dev)),
                inflight.index_attempted,
                *inflight.index_touched,
            )

        nbound = 0
        failed = np.zeros(batch.batch, bool)
        # Per-pod settled outcome (True = the bind stuck), consumed by
        # the gang all-or-none settlement after the bind stage.
        bound_ok = np.zeros(batch.batch, bool)
        bind_batch = getattr(self.store, "bind_batch", None)
        host = self.host
        with self._stage("bind"):
            # One native call binds the whole wave: splice + CAS happen
            # inside the store against the bytes it already holds
            # (ms_bind_batch), so the per-pod Python cost collapses to
            # bookkeeping — itself vectorized below (per-pod np scalar
            # indexing and metric calls were ~12us/pod).  Pods the native
            # path can't take (webhook intake with no observed revision,
            # non-canonical objects) fall back to the per-pod path.
            nb = len(batch_pods)
            rows = node_row[:nb]
            bound_idx = np.nonzero(rows >= 0)[0]
            if self._delta is not None and bound_idx.size:
                # This wave's device-side assumes are now host-visible:
                # journal its bound rows so later delta waves recompute
                # their plane columns.  While the wave was IN flight the
                # same rows reached delta waves on-stream via rows_dev
                # (engine/deltacache.combine_dirty) — this retire stamp
                # closes the window for waves launched from here on.
                # CAS conflicts and tombstoned rows additionally ride
                # the ordinary dirty-row re-upload below.
                self._delta.note_rows(rows[bound_idx])
            # No-feasible-row pods are settled AFTER the wave's binds
            # land in the host mirror (below): preemption's usage
            # snapshot must include this wave's own placements, or the
            # preemptor can overcommit a node the wave is about to fill.
            nofit = np.nonzero(rows < 0)[0].tolist()
            brows = rows[bound_idx]
            # Rows tombstoned while this wave was in flight: the node is
            # gone (quarantine guarantees no reuse before this retire, so
            # an invalid row can't alias a new node) — treat like a CAS
            # conflict: retry the pod, roll back the wave's optimistic
            # constraint commit.  No dirty-marking: the tombstone scatter
            # already uploaded the zeroed row.
            if bound_idx.size:
                alive = host.valid[brows]
                if not alive.all():
                    for i in bound_idx[~alive].tolist():
                        failed[i] = True
                        self._wave_fail(batch_pods[i])
                    bound_idx = bound_idx[alive]
                    brows = brows[alive]
            nbytes = self._node_name_bytes()
            ids_l = host.name_id[brows].tolist()
            brows_l = brows.tolist()
            zones = host.zone[brows].tolist()
            regions = host.region[brows].tolist()
            bound_l = bound_idx.tolist()

            # Index-parallel wave: wave_j[k] is the position in bound_l
            # of the k-th native-path record (per-pod tuple building and
            # name.encode were a measurable slice of the bind stage).
            wave_j: list[int] = []
            entries: list[tuple[bytes, int, bytes]] = []
            native = bind_batch is not None
            # Hot path stays injection-free unless a plan is installed.
            inj_active = bool(faultline.active_injector().plan.faults)
            for j, i in enumerate(bound_l):
                p = batch_pods[i]
                if native and p.mod_revision is not None:
                    # One fault decision per CAS attempt: native-wave
                    # records are checked here (their CAS runs inside
                    # bind_batch); slow-path pods are checked inside
                    # _bind so they never consume two draws per attempt.
                    if inj_active and self._bind_fault():
                        # Forced conflict: identical accounting to the
                        # real CAS-conflict branch below.
                        name = nbytes[ids_l[j]].decode()
                        self._dirty_rows.add(host.row_of(name))
                        failed[i] = True
                        self._wave_fail(p)
                        continue
                    wave_j.append(j)
                    entries.append((p.key_bytes, p.mod_revision, nbytes[ids_l[j]]))
                    continue
                name = nbytes[ids_l[j]].decode()
                if self._bind(p, name):
                    nbound += 1
                    bound_ok[i] = True
                    _BIND_LATENCY.observe(time.perf_counter() - p.enqueued_at)
                    if brows_l[j] in self._midflight_rows:
                        # A mid-flight full scatter erased this wave's
                        # device-side assume on the row; the host mirror
                        # just learned the bind — re-upload repairs it.
                        self._dirty_rows.add(brows_l[j])
                    continue
                # CAS conflict: the device table already assumed this
                # bind (commit_binds), but the host mirror — which is
                # authoritative — was never incremented.  Marking the
                # row dirty re-uploads the host values, undoing the
                # device-side assume; the constraint-count commit is
                # rolled back below in one signed scatter.
                self._dirty_rows.add(host.row_of(name))
                failed[i] = True
                self._wave_fail(p)
            if entries:
                results = self._fenced_bind_batch(
                    entries,
                    self._pods_watch.id if self._bind_excludes else None,
                )
                now = time.perf_counter()
                ok_rows: list[int] = []
                ok_cpu: list[int] = []
                ok_mem: list[int] = []
                lats: list[float] = []
                bound_dict = self._bound
                nv = host.vocab.node_names._to_val
                for j, rev in zip(wave_j, results):
                    i = bound_l[j]
                    p = batch_pods[i]
                    if rev > 0:
                        bound_ok[i] = True
                        ok_rows.append(brows_l[j])
                        ok_cpu.append(p.cpu_milli)
                        ok_mem.append(p.mem_kib)
                        lats.append(now - p.enqueued_at)
                        keep = (
                            p.pod
                            if p.pod is not None and self._constraintful(p.pod)
                            else None
                        )
                        node_name = nv[ids_l[j]]
                        bound_dict[p.key_str] = (
                            node_name, p.cpu_milli, p.mem_kib,
                            zones[j], regions[j], keep,
                        )
                        self._bind_seq += 1
                        # bind_batch takes ANY pod with an observed
                        # revision, decoded or not: a decoded PodInfo
                        # supplies the label-aware tenant; the true
                        # fast-lane (pod=None) is label-less canonical,
                        # so its key namespace IS the tenant.
                        tenant = (
                            tenant_of_pod(p.pod) if p.pod is not None
                            else tenant_of_key(p.key_str)
                        )
                        self._bind_meta[p.key_str] = (
                            p.priority, self._bind_seq, tenant, p.gang_id,
                        )
                        self._victims_note(
                            p.key_str, node_name, p.cpu_milli, p.mem_kib,
                            p.priority, self._bind_seq, tenant, p.gang_id,
                        )
                        continue
                    name = nbytes[ids_l[j]].decode()
                    if rev == BIND_INVALID and self._bind(p, name):
                        nbound += 1
                        bound_ok[i] = True
                        _BIND_LATENCY.observe(now - p.enqueued_at)
                        if brows_l[j] in self._midflight_rows:
                            self._dirty_rows.add(brows_l[j])
                        continue
                    if rev != BIND_INVALID:
                        _PODS_SCHEDULED.inc(outcome="conflict")
                    self._dirty_rows.add(host.row_of(name))
                    failed[i] = True
                    self._wave_fail(p)
                if ok_rows:
                    # Duplicate rows (two pods on one node) accumulate
                    # correctly under np.add.at.
                    r = np.asarray(ok_rows, np.int32)
                    np.add.at(host.cpu_req, r, np.asarray(ok_cpu, host.cpu_req.dtype))
                    np.add.at(host.mem_req, r, np.asarray(ok_mem, host.mem_req.dtype))
                    np.add.at(host.pods_req, r, 1)
                    nbound += len(ok_rows)
                    _PODS_SCHEDULED.inc(len(ok_rows), outcome="bound")
                    _BIND_LATENCY.observe_many(lats)
                    if self._midflight_rows:
                        # Same repair as the slow path: rows a mid-flight
                        # full scatter clobbered get the host truth (now
                        # including this wave's binds) re-uploaded.
                        self._dirty_rows.update(
                            rr for rr in ok_rows
                            if rr in self._midflight_rows
                        )
            # Preemption pass — after every CAS bind above, so the host
            # mirror (and so the feasibility snapshot) reflects this
            # wave's placements.  The victims index is built lazily, at
            # most once per wave, and kept current across this wave's
            # preemptions.
            vindex = None
            for i in nofit:
                p = batch_pods[i]
                if self._preempt_eligible(p):
                    if vindex is None:
                        vindex = self._victims_index()
                    if self._try_preempt(p, vindex):
                        bound_ok[i] = True
                        nbound += 1
                        continue
                self._wave_fail(p)
            # Gang all-or-none settlement — inside the wave-epoch window
            # (before this retire returns): partially-bound gangs release
            # every provisional bind and requeue whole.  Runs before the
            # failed-mask rollback below so released device-committed
            # binds ride the same signed constraint scatter.
            nbound -= self._resolve_gangs(batch_pods, bound_ok, rows, failed)
        if failed.any() and self.constraints is not None:
            m = jnp.asarray(failed)
            self.constraints = self._adjust(
                self.constraints, commit_fields_np(batch.fields),
                asg.node_row, asg.zone, asg.region, m, m, sign=-1,
            )
        if self._tracer.enabled:
            self._trace_retire(inflight, rows, bound_ok, t_sync)

        cycle_s = time.perf_counter() - t_start
        self._last_cycle_s = cycle_s
        # This wave retired: rows removed at or before the oldest
        # still-in-flight wave's launch are past their aliasing hazard.
        if self._inflights:
            self.host.release_rows(self._inflights[0].epoch)
        else:
            self.host.release_rows(None)
            # Every wave a mid-flight scatter could have clobbered has
            # now retired and repaired; stop tracking those rows.
            self._midflight_rows.clear()
        self.depth_timer.set_level(len(self._inflights))
        if self.breaker is not None:
            # Success is a RETIRED wave — the device returned data — not
            # an accepted dispatch (async dispatch accepts work a wedged
            # runtime never finishes).  A half-open probe still resolves
            # promptly: while the breaker is not CLOSED, step() quiesces
            # the pipeline, which completes the probe right here.
            self.breaker.record_success()
        if self.flight is not None:
            self.flight.record(
                "cycle",
                cycle_s,
                pods=len(batch_pods),
                bound=nbound,
                queue=len(self.queue),
            )
            if (
                self.profiler is not None
                and cycle_s > self.flight.threshold_s
                # Same cap discipline as the flight recorder: sustained
                # slow cycles must not fill the disk, and the dump cost
                # itself lengthens cycles (self-amplifying otherwise).
                and self._profile_dumps < self.flight.max_dumps
            ):
                self._profile_dumps += 1
                # The flight dump says WHAT was slow; the profile dump
                # says WHERE the window's time went.
                self.profiler.dump(
                    os.path.join(
                        self.flight.dump_dir,
                        # graftlint: disable=no-wall-clock (epoch-ms dump name, correlates with flight dumps)
                        f"profile-slowcycle-{int(time.time() * 1e3)}"
                        f"-{self._profile_dumps}.json",
                    )
                )
        return nbound

    def _trace_retire(self, inflight: Wave, rows, bound_ok, t_sync: float) -> None:
        """Wave-retire observability pass (runs only while tracing is
        enabled — tracing off keeps the flight recorder's historical
        slow-CYCLE behavior exactly): close every sampled pod's span
        chain — the device span stamped with the wave's epoch, pipeline
        depth and delta-vs-full pass, the bind span with the settled
        outcome — and give any TRACED pod whose schedule-to-bind
        exceeded the flight threshold the reference's per-slow-pod
        flight dump with its span chain attached (scheduler.go:556-565).
        Traced pods only, by design: the dump budget (max_dumps) is
        shared with the slow-cycle dumps, so an untraced backlog —
        where every pod's queue wait clears the per-op threshold — must
        not be able to drain it 1-in-1."""
        tracer = self._tracer
        if not tracer.enabled:
            return
        flight = self.flight
        now = time.perf_counter()
        for i, p in enumerate(inflight.batch_pods):
            ok = bool(bound_ok[i])
            done = None
            tracer.emit(
                p.key_str, "device", t=t_sync,
                wave_epoch=inflight.epoch, depth=inflight.depth,
                path=inflight.path,
            )
            if ok:
                done = tracer.finish(
                    p.key_str, "bind", t=now, outcome="bound"
                )
            else:
                tracer.emit(
                    p.key_str, "bind", t=now,
                    outcome="nofit" if rows[i] < 0 else "conflict",
                    attempts=p.attempts,
                )
                if p.key_str in self._trace_gaveup:
                    # Retry budget spent during this wave's settlement:
                    # close the chain HERE, after its device/bind spans.
                    self._trace_gaveup.discard(p.key_str)
                    tracer.finish(
                        p.key_str, "requeue",
                        outcome="unschedulable", attempts=p.attempts,
                    )
            if done is not None and flight is not None:
                lat = now - p.enqueued_at
                if lat > flight.threshold_s:
                    flight.dump(
                        reason=(
                            f"pod {p.key_str} schedule-to-bind "
                            f"{lat * 1e3:.1f}ms"
                        ),
                        extra={
                            "pod": p.key_str,
                            "pod_spans": done.doc()["spans"],
                        },
                    )

    def step(self) -> int:
        """One scheduling cycle; returns number of pods bound.

        With ``pipeline=True`` the returned count is the *previous*
        dispatch's binds: batch N's device work executes while the
        caller does its inter-step work (producers, kwok ticks), hiding
        the device→host sync latency.  Snapshot churn no longer drains
        the pipeline: capacity-only node deltas scatter on-stream while
        waves are in flight, removes tombstone into the wave-epoch
        quarantine, and the oldest wave is still completed BEFORE this
        step's sync+dispatch so its bind accounting lands in the host
        mirror ahead of the dirty-row re-upload the next launch
        consumes.  Call ``flush()`` (or ``run_until_idle``) to retire
        the tail.
        """
        if not self.pipeline:
            self._drain_external()
            self.drain_watches()
            self._sync_table()
            self._process_adjusts()
            self._loadshed_tick()
            if (
                self.breaker is not None
                and self.breaker.state != BREAKER_CLOSED
            ):
                self._release_backoff()
                if not self.queue:
                    return 0
                if not self.breaker.allow():
                    # Open: bind a small slice through the oracle —
                    # popped WITHOUT encoding (the wave would only be
                    # discarded).
                    return self._fallback_schedule(self._take_pods(
                        self.breaker.config.fallback_batch
                    ))
                # Half-open probe: fall through to a normal device wave.
            batch_pods, batch = self._take_batch()
            if batch_pods is None:
                return 0
            try:
                inflight = self._launch(batch_pods, batch)
            except Exception:
                if self.breaker is None:
                    raise
                log.exception("cycle dispatch failed; breaker accounting")
                self.breaker.record_failure()
                self._requeue_front(batch_pods)
                return 0
            if self._feed is not None:
                # Encode the NEXT full batch while _complete below waits
                # out the device round trip (the one overlap window the
                # unpipelined cycle has).
                self._feed.stage(self.queue, self.pod_spec.batch)
            return self._complete(inflight)
        # Pipelined: up to ``depth`` waves in flight, so each wave's
        # device compute AND its result-fetch round trip overlap the host
        # work of later cycles (through a remote device relay the fetch
        # RTT alone is tens of ms).  The snapshot mutates WITHOUT
        # retiring the pipeline (wave cadence decouples from watch
        # cadence):
        #  - pod events touch capacity accounting only;
        #  - capacity-only node deltas scatter feature columns into the
        #    live table (_dirty_caps), structural adds append past the
        #    high-water mark, and removes tombstone into the wave-epoch
        #    quarantine — all on-stream, no host sync (_drain_node_events);
        #  - _complete lands its bind accounting (and CAS-rollback dirty
        #    rows) in the host mirror before _sync_table re-uploads rows
        #    for the next launch.
        # Only resync (row mapping rebuilt), a tripped breaker, adaptive
        # partial buckets, and quarantine exhaustion still retire it —
        # each counted in pipeline_quiesce_total.
        done = self._deferred_binds
        self._deferred_binds = 0
        if self._nodes_watch.dropped or self._pods_watch.dropped:
            done += self._quiesce("resync")
            log.warning(
                "watch overflow (nodes dropped=%d pods dropped=%d); resyncing",
                self._nodes_watch.dropped, self._pods_watch.dropped,
            )
            self.resync()
        elif self._watch_fault():
            # Injected watch loss: quiesce the pipeline (resync mutates
            # the row->node mapping) and relist, same as an overflow.
            done += self._quiesce("resync")
            self.resync()
        self._drain_external()
        self._drain_pod_events()
        self._drain_node_events()
        self._loadshed_tick()
        if self.breaker is not None and self.breaker.state != BREAKER_CLOSED:
            # A tripped breaker serializes the pipeline: quiesce so (a)
            # no in-flight device wave can land placements computed
            # against pre-fallback usage after the oracle binds
            # host-side, and (b) the half-open probe resolves at its own
            # dispatch instead of starving behind the depth gate.
            done += self._quiesce("breaker")
            self._sync_table()
            self._process_adjusts()
            self._release_backoff()
            if not self.queue:
                return done
            if not self.breaker.allow():
                done += self._fallback_schedule(self._take_pods(
                    self.breaker.config.fallback_batch
                ))
                return done
            # Half-open probe: launched below through the normal path
            # (the pipeline is empty, so it dispatches this step).
        batch_pods, batch = self._take_batch()
        if len(self._inflights) >= (self.depth if batch_pods else 1):
            done += self._complete(self._inflights.pop(0))
        # After the retire, before the launch: the retired wave's bind
        # accounting and rollback rows are in the host mirror, so the
        # scatter the next launch consumes carries them.
        self._sync_table()
        self._process_adjusts()
        if batch_pods is not None:
            try:
                inflight = self._launch(batch_pods, batch)
            except Exception:
                if self.breaker is None:
                    raise
                log.exception("cycle dispatch failed; breaker accounting")
                self.breaker.record_failure()
                self._requeue_front(batch_pods)
                return done
            self._inflights.append(inflight)
            self.depth_timer.set_level(len(self._inflights))
            if self._feed is not None:
                # Wave N is in flight: peek (never pop) the next full
                # batch and let the worker encode it behind the device.
                self._feed.stage(self.queue, self.pod_spec.batch)
            if self.adaptive_batch and batch.batch < self.pod_spec.batch:
                # Light load (partial bucket): pipelining buys no
                # throughput — the queue is draining faster than it
                # fills — but holding the wave until the NEXT step adds
                # 1-2 extra wave times to every pod's latency.  This was
                # the round-4 "flat 288ms p50 at every sub-knee rate":
                # 3x the 82ms bucket-256 wave, not the wave itself.
                # Retire immediately; full buckets keep the deep
                # pipeline (saturation is where overlap pays).
                done += self._quiesce("adaptive")
        return done

    def flush(self) -> int:
        """Retire every in-flight pipelined batch.  Also surfaces any
        deferred bind credit (exhaustion/resync flushes) so a driver's
        final flush never under-reports."""
        done = self._deferred_binds
        self._deferred_binds = 0
        while self._inflights:
            done += self._complete(self._inflights.pop(0))
        return done

    def _quiesce(self, reason: str) -> int:
        """Retire the whole pipeline for a structural/control event and
        count it (no-op, uncounted, when nothing is in flight)."""
        if not self._inflights:
            return 0
        _PIPE_QUIESCE.inc(reason=reason)
        return self.flush()

    def _nodes_pending(self) -> int:
        """Queued node events.  No longer a quiesce trigger (node deltas
        apply while waves are in flight) — kept as the intake probe for
        drivers and tests.  Watchers without a cheap pending probe
        report whether the LAST drain actually applied anything, instead
        of a permanent 1 (which, when this gated the quiesce, collapsed
        the pipeline to depth-1 on every cycle)."""
        p = getattr(self._nodes_watch, "pending", None)
        return self._last_node_drain if p is None else p

    # ---- fenced store writes (ISSUE 9) ---------------------------------
    #
    # Every store put/CAS reachable from the bind/evict/preempt paths
    # MUST flow through these two funnels (enforced statically by the
    # graftlint ``fenced-store-write`` pass): they consult the reign's
    # LeaseFence before touching the store, so a deposed or paused
    # leader's in-flight waves retire into the ordinary conflict/requeue
    # machinery instead of landing writes behind the new leader.

    def _fence_admit(self, path: str) -> bool:
        f = self.fence
        if f is None or f.admit():
            return True
        _FENCE_REJECTED.inc(path=path)
        return False

    def _fenced_cas(self, key: bytes, value: bytes, *, required_mod: int,
                    path: str):
        """The bind/evict/preempt CAS funnel: shaped exactly like
        ``store.cas`` so a fence refusal reads as a CAS conflict — the
        one failure every caller already absorbs (requeue/backoff)."""
        if not self._fence_admit(path):
            return False, 0, None
        return self.store.cas(key, value, required_mod=required_mod)

    def _fenced_bind_batch(self, entries, watch_id=None):
        """The native-wave bind funnel: a fence refusal fails every
        entry as a conflict (rev 0) without touching the store."""
        if not self._fence_admit("bind"):
            return [0] * len(entries)
        if watch_id is not None:
            return self.store.bind_batch(entries, watch_id)
        return self.store.bind_batch(entries)

    def _bind(self, p: PendingPod, node_name: str) -> bool:
        """CAS spec.nodeName into the pod object; False on conflict
        (including a fence refusal — a deposed reign must not bind;
        every path below terminates in a ``_fenced_cas``, so the fence
        is consulted exactly once per store-write attempt)."""
        if self._bind_fault():
            return False
        key = p.key_bytes
        if p.mod_revision is not None and p.raw is not None:
            # Fast path: splice nodeName into the intake-revision bytes.
            # The CAS itself proves the object hasn't changed since, so
            # no re-read or JSON round trip is needed.
            value = splice_node_name(p.raw, node_name)
            if value is not None:
                ok, _, _ = self._fenced_cas(
                    key, value, required_mod=p.mod_revision, path="bind"
                )
                if not ok:
                    _PODS_SCHEDULED.inc(outcome="conflict")
                    return False
                self.host.add_pod(node_name, p.cpu_milli, p.mem_kib)
                self._note_bound(p.ensure_pod(), node_name, external=False)
                _PODS_SCHEDULED.inc(outcome="bound")
                return True
        cur = self.store.get(key)
        if cur is None:
            _PODS_SCHEDULED.inc(outcome="conflict")
            return False
        if p.mod_revision is None:
            # Webhook intake: no revision was observed at admission.  Bind
            # against the live revision — unless someone already bound it.
            obj = json.loads(cur.value)
            if obj.get("spec", {}).get("nodeName"):
                _PODS_SCHEDULED.inc(outcome="conflict")
                return False
            required = cur.mod_revision
        elif cur.mod_revision != p.mod_revision:
            _PODS_SCHEDULED.inc(outcome="conflict")
            return False
        else:
            # Intake revision still live but no raw bytes captured (the
            # native fast lane keeps PendingPod compact): splice into
            # the store's current bytes — same output as the raw-bytes
            # fast path above, no JSON round trip.
            value = splice_node_name(cur.value, node_name)
            if value is not None:
                ok, _, _ = self._fenced_cas(
                    key, value, required_mod=p.mod_revision, path="bind"
                )
                if not ok:
                    _PODS_SCHEDULED.inc(outcome="conflict")
                    return False
                self.host.add_pod(node_name, p.cpu_milli, p.mem_kib)
                self._note_bound(p.ensure_pod(), node_name, external=False)
                _PODS_SCHEDULED.inc(outcome="bound")
                return True
            obj = json.loads(cur.value)
            required = p.mod_revision
        obj["spec"]["nodeName"] = node_name
        ok, _, _ = self._fenced_cas(
            key,
            json.dumps(obj, separators=(",", ":")).encode(),
            required_mod=required, path="bind",
        )
        if not ok:
            _PODS_SCHEDULED.inc(outcome="conflict")
            return False
        # Keep host accounting; the watch echo of our own write is
        # deduped via _bound.
        self.host.add_pod(node_name, p.cpu_milli, p.mem_kib)
        self._note_bound(p.ensure_pod(), node_name, external=False)
        _PODS_SCHEDULED.inc(outcome="bound")
        return True

    @staticmethod
    def _bind_fault() -> bool:
        """Faultline hook on the bind CAS (component ``coordinator.bind``,
        op ``cas``).  ``delay`` sleeps; every failure kind maps to a
        forced CAS conflict — the one failure this path owns (wire-level
        failures are the store.wire hooks' domain) — which drives the pod
        through the same conflict/requeue machinery a concurrent writer
        would.  Returns True when the bind must report conflict."""
        d = faultline.decide("coordinator.bind", "cas")
        if d is None:
            return False
        if d.kind == "delay":
            time.sleep(d.delay_s)
            return False
        _PODS_SCHEDULED.inc(outcome="conflict")
        return True

    def _retry(self, p: PendingPod) -> None:
        p.attempts += 1
        pol = self.retry_policy
        if p.attempts >= pol.max_attempts:
            # Give-up degrades gracefully: the pod is parked (the
            # reference reports unschedulable the same way), never
            # tight-looped.
            if self._tracer.enabled:
                # Chain closes in the wave-retire pass (after the
                # device/bind spans), not here mid-bind-loop.
                self._trace_gaveup.add(p.key_str)
            _PODS_SCHEDULED.inc(outcome="unschedulable")
            note_give_up("coordinator.bind")
            self.unschedulable[p.key_str] = p.ensure_pod()
            return
        _PODS_SCHEDULED.inc(outcome="retry")
        note_retry("coordinator.bind")
        # Re-read AND re-decode: the CAS may have failed because an external
        # writer bound the pod (retrying would overwrite their bind and
        # double-account) or changed its spec (retrying with stale
        # cpu/mem would overcommit the node).
        cur = self.store.get(p.key_bytes)
        if cur is None:
            return
        fresh = decode_pod(cur.value, self.tracker)
        if fresh.node_name:
            return  # bound externally; the watch echo handles accounting
        p.pod = fresh
        p.cpu_milli = fresh.cpu_milli
        p.mem_kib = fresh.mem_kib
        p.key_str = fresh.key
        p.priority = fresh.priority
        p.mod_revision = cur.mod_revision
        # Refresh the splice-source bytes too — stale raw at the new
        # revision would CAS the OLD object body back in, silently
        # reverting whatever spec change made the first CAS fail.
        p.raw = cur.value
        self._queued_keys.add(p.key_str)
        # Backoff requeue (RetryPolicy): the pod sits out a jittered,
        # attempt-scaled delay instead of re-entering the very next wave
        # — a conflict storm becomes visible backpressure
        # (coordinator_backoff_depth) rather than a tight loop.
        p.not_before = time.perf_counter() + pol.delay_for(
            p.attempts, self._retry_rng
        )
        self._backoff_seq += 1
        heapq.heappush(self._backoff, (p.not_before, self._backoff_seq, p))

    def close(self) -> None:
        """Cancel store watches (native watchers are registered until
        explicitly cancelled — dropping the object alone would leave the
        store dispatching into a 10,000-event queue forever) and stop
        the host-feed worker."""
        for w in (self._nodes_watch, self._pods_watch):
            if w is not None:
                w.cancel()
        self._nodes_watch = self._pods_watch = None
        if self._feed is not None:
            self._feed.close()

    def run_until_idle(self, max_cycles: int = 10000) -> int:
        """Drive cycles until no pending pods remain; returns total binds."""
        total = 0
        idle = 0
        for _ in range(max_cycles):
            n = self.step()
            total += n
            if not self.queue and not self._inflights:
                if self._backoff or self._gang_parked:
                    # Retrying pods (and parked gangs) are on a timer,
                    # not idle: wait out the earliest backoff instead of
                    # burning empty cycles (or worse, exiting with work
                    # pending).
                    time.sleep(min(self.backoff_wait_s() or 0.0, 0.05))
                    idle = 0
                    continue
                idle += 1
                if (
                    idle > 1
                    and self.drain_watches() == 0
                    and not self._external_pending()
                ):
                    break
            else:
                idle = 0
        total += self.flush()
        return total


# Single-device dirty-row scatter (snapshot/node_table.scatter_rows),
# DONATING: the coordinator always reassigns self.table from the
# return, so the churn scatter updates HBM in place instead of
# copy-on-write.  The mesh path swaps in
# parallel.sharded_cycle.make_sharded_scatter — equally donating, with
# the row sharding pinned on top; a replay caller that keeps its input
# table alive must jit its own non-donating wrapper.
_scatter_rows_donated = jax.jit(scatter_rows, donate_argnums=(0,))
