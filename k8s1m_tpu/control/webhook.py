"""Admission-webhook pod intake: the reference's primary intake path.

The reference feeds pods to the scheduler through a ValidatingWebhook —
the apiserver POSTs an AdmissionReview to the leader's ``/validate``
endpoint, which always allows and enqueues pods whose schedulerName
matches (reference pkg/webhook/webhook.go:71-126).  It exists because
the fieldSelector pod watch stalled for tens of seconds above ~5K pods/s
(reference README.adoc:684-695): admission fires *before* the write is
persisted, shaving the store round-trip off schedule latency.

Same contract here: ``WebhookServer`` accepts AdmissionReview v1 JSON,
always allows, and hands matching pods to a sink (the coordinator's
``submit_external``).  A webhook-intake pod carries no mod revision yet
(the object isn't persisted at admission time), so the bind path resolves
the current revision at bind time; the store-watch intake remains the
fallback — a pod whose webhook delivery was lost still arrives via watch
(intake is deduplicated by pod key).

TLS: the reference terminates TLS with terraform-provisioned certs
(dist-scheduler.tf:713-740); pass ``ssl_context`` to match, or run plain
HTTP behind a trusted boundary.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from k8s1m_tpu.config import DEFAULT_SCHEDULER
from k8s1m_tpu.obs.metrics import Counter

log = logging.getLogger("k8s1m.webhook")

_REQUESTS = Counter(
    "webhook_requests_total", "AdmissionReview requests", ("outcome",)
)


def review_response(uid: str) -> bytes:
    return json.dumps(
        {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "response": {"uid": uid, "allowed": True},
        },
        separators=(",", ":"),
    ).encode()


class WebhookServer:
    """Threaded HTTP server for ``POST /validate``.

    ``sink(pod_obj: dict)`` is called for every admitted pod with our
    schedulerName and no nodeName; it must be thread-safe (the
    coordinator's submit_external only appends to a locked queue).
    """

    def __init__(
        self,
        sink,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        scheduler_name: str = DEFAULT_SCHEDULER,
        ssl_context=None,
    ):
        self.sink = sink
        self.scheduler_name = scheduler_name
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route through logging
                log.debug(fmt, *args)

            def do_POST(self):
                if self.path.split("?")[0] != "/validate":
                    self.send_error(404)
                    _REQUESTS.inc(outcome="not_found")
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    review = json.loads(self.rfile.read(length))
                    req = review["request"]
                    uid = req.get("uid", "")
                    obj = req.get("object") or {}
                except Exception:
                    self.send_error(400)
                    _REQUESTS.inc(outcome="bad_request")
                    return
                # Always allow — admission must never block the write path
                # (the reference responds before even parsing the pod,
                # webhook.go:102-125).
                body = review_response(uid)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                spec = obj.get("spec", {})
                if (
                    obj.get("kind") == "Pod"
                    # Unset schedulerName = "default-scheduler" (upstream
                    # semantics): only explicitly-marked pods are claimed,
                    # matching the reference's intake filter
                    # (webhook.go:102-125) and decode_pod_obj.
                    and spec.get("schedulerName") == outer.scheduler_name
                    and not spec.get("nodeName")
                ):
                    _REQUESTS.inc(outcome="enqueued")
                    try:
                        outer.sink(obj)
                    except Exception:
                        log.exception("webhook sink failed")
                else:
                    _REQUESTS.inc(outcome="ignored")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        if ssl_context is not None:
            self._httpd.socket = ssl_context.wrap_socket(
                self._httpd.socket, server_side=True
            )
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="webhook", daemon=True
        )

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "WebhookServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
