"""Admission-webhook pod intake: the reference's primary intake path.

The reference feeds pods to the scheduler through a ValidatingWebhook —
the apiserver POSTs an AdmissionReview to the leader's ``/validate``
endpoint, which always allows and enqueues pods whose schedulerName
matches (reference pkg/webhook/webhook.go:71-126).  It exists because
the fieldSelector pod watch stalled for tens of seconds above ~5K pods/s
(reference README.adoc:684-695): admission fires *before* the write is
persisted, shaving the store round-trip off schedule latency.

Same contract here: ``WebhookServer`` accepts AdmissionReview v1 JSON,
always allows, and hands matching pods to a sink (the coordinator's
``submit_external``).  A webhook-intake pod carries no mod revision yet
(the object isn't persisted at admission time), so the bind path resolves
the current revision at bind time; the store-watch intake remains the
fallback — a pod whose webhook delivery was lost still arrives via watch
(intake is deduplicated by pod key).

Two robustness layers on top (see k8s1m_tpu/loadshed):

- **Admission control**: with a ``controller`` (loadshed
  HealthController) installed, pods our scheduler would claim are
  admission-checked *before* the response — past the overload
  watermarks the answer is HTTP 429 with ``Retry-After``, lowest
  ``spec.priority`` shed first.  This is the same contract
  kube-apiserver priority-and-fairness gives webhook-fronted intake:
  clients see explicit backpressure with a retry hint, never a
  timeout.  "Always allow" still holds for everything the scheduler
  does NOT claim (foreign schedulerName, already-bound pods) — a shed
  scheduler must not veto unrelated admissions.
- **Connection hygiene**: every accepted connection carries a socket
  timeout (``request_timeout_s``), so a stalled client cannot pin a
  ThreadingHTTPServer thread forever — an overload vector admission
  control alone would leave open.

TLS: the reference terminates TLS with terraform-provisioned certs
(dist-scheduler.tf:713-740); pass ``ssl_context`` to match, or run plain
HTTP behind a trusted boundary.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from k8s1m_tpu.config import DEFAULT_SCHEDULER
from k8s1m_tpu.control.objects import pod_key_str_of_obj
from k8s1m_tpu.obs.metrics import Counter
from k8s1m_tpu.obs.podtrace import NULL_TRACER
from k8s1m_tpu.ops.priority import pod_priority_of

log = logging.getLogger("k8s1m.webhook")

_REQUESTS = Counter(
    "webhook_requests_total", "AdmissionReview requests", ("outcome",)
)


def review_response(uid: str) -> bytes:
    return json.dumps(
        {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "response": {"uid": uid, "allowed": True},
        },
        separators=(",", ":"),
    ).encode()


class WebhookServer:
    """Threaded HTTP server for ``POST /validate``.

    ``sink(pod_obj: dict)`` is called for every admitted pod with our
    schedulerName and no nodeName; it must be thread-safe (the
    coordinator's submit_external only appends to a locked queue).
    With a ``controller`` the call becomes ``sink(obj, admitted=True)``
    — admission already ran here, and the marker travels out-of-band so
    the pod object itself stays canonical.
    """

    def __init__(
        self,
        sink,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        scheduler_name: str = DEFAULT_SCHEDULER,
        ssl_context=None,
        # Overload admission (k8s1m_tpu/loadshed.HealthController); None
        # preserves the historical always-allow behavior.
        controller=None,
        # Per-connection socket timeout: a stalled client gets dropped
        # instead of pinning a handler thread indefinitely.
        request_timeout_s: float = 30.0,
        # Per-pod lifecycle tracing (obs/podtrace.py): a sampled pod's
        # trace opens HERE, at webhook receipt — the earliest intake
        # timestamp the system observes — so the admit span covers the
        # admission decision itself.  None = the null tracer (free).
        tracer=None,
    ):
        self.sink = sink
        self.scheduler_name = scheduler_name
        self.controller = controller
        self.tracer = tracer if tracer is not None else NULL_TRACER
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # StreamRequestHandler applies this to the connection in
            # setup(); handle_one_request treats the resulting timeout
            # as a dropped connection.
            timeout = request_timeout_s

            def log_message(self, fmt, *args):  # route through logging
                log.debug(fmt, *args)

            def do_POST(self):
                if self.path.split("?")[0] != "/validate":
                    self.send_error(404)
                    _REQUESTS.inc(outcome="not_found")
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    review = json.loads(self.rfile.read(length))
                    req = review["request"]
                    uid = req.get("uid", "")
                    obj = req.get("object") or {}
                except Exception:
                    # 400 is the contract for malformed reviews, but the
                    # parse failure itself must stay diagnosable.
                    log.debug("malformed AdmissionReview", exc_info=True)
                    self.send_error(400)
                    _REQUESTS.inc(outcome="bad_request")
                    return
                spec = obj.get("spec", {})
                claimed = (
                    obj.get("kind") == "Pod"
                    # Unset schedulerName = "default-scheduler" (upstream
                    # semantics): only explicitly-marked pods are claimed,
                    # matching the reference's intake filter
                    # (webhook.go:102-125) and decode_pod_obj.
                    and spec.get("schedulerName") == outer.scheduler_name
                    and not spec.get("nodeName")
                )
                if claimed and outer.controller is not None:
                    # Tenancy-aware controllers (tenancy.FairAdmission)
                    # derive the tenant from the object and shed per
                    # tenant; the plain HealthController keeps the
                    # priority-only global form.
                    admit_obj = getattr(outer.controller, "admit_obj", None)
                    if admit_obj is not None:
                        allowed = admit_obj(obj, point="webhook")
                    else:
                        allowed = outer.controller.admit(
                            pod_priority_of(obj), point="webhook"
                        )
                else:
                    allowed = True
                if not allowed:
                    # Overload shed: explicit backpressure with a retry
                    # hint (the kube-apiserver priority-and-fairness
                    # answer), never a hang or a silent drop.
                    _REQUESTS.inc(outcome="shed")
                    self.send_response(429)
                    self.send_header(
                        "Retry-After",
                        str(max(1, round(outer.controller.retry_after_s()))),
                    )
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                # Allow — admission must never block the write path
                # (the reference responds before even parsing the pod,
                # webhook.go:102-125).
                body = review_response(uid)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                if claimed:
                    _REQUESTS.inc(outcome="enqueued")
                    tracer = outer.tracer
                    key = ""
                    if tracer.enabled:
                        # Open the trace at webhook receipt — the
                        # earliest intake timestamp the system sees
                        # (only for pods we actually claim: a foreign
                        # scheduler's pod must not hold a live trace
                        # that can never close).
                        key = pod_key_str_of_obj(obj)
                        tracer.begin(
                            key, time.perf_counter(), source="webhook",
                        )
                    try:
                        if outer.controller is not None:
                            # This pod already passed admission here —
                            # the sink must not draw (and count) a
                            # second decision.  Out-of-band kwarg, never
                            # a key smuggled into the pod object (a sink
                            # that persists the object must store the
                            # canonical bytes).
                            outer.sink(obj, admitted=True)
                        else:
                            outer.sink(obj)
                    except Exception:
                        if tracer.enabled:
                            # The pod never reached the queue: close
                            # the receipt-anchored chain or it pins a
                            # live-trace slot forever (max_live leak).
                            tracer.finish(
                                key, "requeue", outcome="sink_error",
                            )
                        log.exception("webhook sink failed")
                else:
                    _REQUESTS.inc(outcome="ignored")

        if ssl_context is None:
            self._httpd = ThreadingHTTPServer((host, port), Handler)
        else:
            # Wrap per-connection with the handshake deferred into the
            # handler thread (same pattern as obs/http.py): wrapping the
            # LISTENING socket runs the TLS handshake inside the
            # serve_forever accept loop, so one client stalling
            # mid-handshake would block every later admission — the
            # exact thread-pinning vector request_timeout_s exists to
            # close.  The pre-wrap settimeout bounds the handshake
            # itself (Handler.timeout only applies after setup()).
            class TLSServer(ThreadingHTTPServer):
                def get_request(self):
                    sock, addr = super().get_request()
                    sock.settimeout(min(10.0, request_timeout_s))
                    return (
                        ssl_context.wrap_socket(
                            sock, server_side=True,
                            do_handshake_on_connect=False,
                        ),
                        addr,
                    )

                def finish_request(self, request, client_address):
                    request.do_handshake()  # in the per-connection thread
                    super().finish_request(request, client_address)

                def handle_error(self, request, client_address):
                    # Failed/stalled handshakes are the client's problem
                    # (ssl.SSLError is an OSError subclass); anything
                    # else is OUR bug and must not vanish.
                    import sys

                    if not isinstance(sys.exc_info()[1], OSError):
                        super().handle_error(request, client_address)

            self._httpd = TLSServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="webhook", daemon=True
        )

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "WebhookServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
