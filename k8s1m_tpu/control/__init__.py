from k8s1m_tpu.control.objects import (  # noqa: F401
    decode_node,
    decode_pod,
    encode_node,
    encode_pod,
    lease_key,
    node_key,
    pod_key,
)
