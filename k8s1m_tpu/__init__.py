"""k8s1m_tpu — a TPU-native million-node Kubernetes scheduling framework.

Re-implements the capabilities of bchess/k8s-1m (reference mounted at
/root/reference) with a TPU-first architecture:

- ``snapshot``    — HBM-resident node table + host-side feature compiler
                    (replaces the label-sharded informer caches of
                    dist-scheduler, reference cmd/dist-scheduler/scheduler.go:201-219).
- ``plugins``     — scheduling-framework Filter/Score plugins as vmapped
                    tensor kernels (replaces the forked kube-scheduler's
                    per-pod Go hot loop, ~560us/pod on 8,670 cores).
- ``engine``      — the per-batch scheduling cycle: filter -> score ->
                    masked top-k with random tie-break -> greedy conflict
                    resolution (replaces scatter/gather + DistPermit +
                    ScoreEvaluator, reference pkg/scoreevaluator/scoreevaluator.go:45-126).
- ``parallel``    — 2D device-mesh sharding (pod-batch x node-shard) via
                    shard_map; ICI collectives replace the fan-out-10 relay
                    tree and CollectScore gRPC gather
                    (reference pkg/schedulerset/schedulerset.go:161-193).
- ``cluster``     — KWOK-style synthetic cluster + load generators
                    (make_nodes / make_pods equivalents, reference kwok/).
- ``oracle``      — pure-Python reference scheduler used as the
                    differential-correctness oracle.
- ``store``       — bindings for the native (C++) memetcd control-plane
                    store (reference mem_etcd/, Rust).
"""

__version__ = "0.1.0"
