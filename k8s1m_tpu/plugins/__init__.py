from k8s1m_tpu.plugins.filters import feasible_mask
from k8s1m_tpu.plugins.registry import Profile, default_profile

__all__ = ["feasible_mask", "Profile", "default_profile"]
